"""Model replicas: warm JIT caches, least-loaded dispatch, hot-swap.

A :class:`Replica` owns one jitted forward of the current model plus a
worker thread draining its private work queue — the thread-backed
analog of a per-chip serving process (process isolation is a deployment
choice layered on top; inside one host, threads share the XLA compile
cache and the weights' device buffers, which is exactly what we want
for N replicas of the same model on one chip).

Batch shapes are bucketed to powers of two up to ``max_batch_size``
(``bucket_for``): the padded batch always hits a warm compilation, so
tail latency never pays a compile. ``warm()`` pre-compiles every bucket
at startup and after every swap — a swapped-in model serves its first
request from a warm cache.

:class:`ReplicaPool` fans work out across replicas by least queued
work, and :meth:`ReplicaPool.swap` hot-swaps the model: the swap rides
the same work queue as inference, so each replica drains everything
already accepted, swaps, re-warms, and only then takes new work — no
request ever observes a half-swapped replica.

The pool is **elastic** (ISSUE 14): :meth:`ReplicaPool.add_replica`
grows it under fire (the new replica warms its buckets BEFORE joining
dispatch, so scale-up never routes traffic onto a cold JIT cache) and
:meth:`ReplicaPool.remove_replica` shrinks it by removing a replica
from dispatch first and only then draining what it already accepted —
zero in-flight requests die on a scale-down. Warm-up H2D rides the
PR 8 :class:`~veles_tpu.loader.prefetch.StagingRing` (bounded device
residency during the bucket sweep) and is recorded as the
``veles_phase_ms{phase="replica_warmup"}`` startup gauge — the
serving half of ROADMAP item 4's cold-start hunt.
"""

import queue
import threading
import time

import numpy

from veles_tpu.logger import Logger


def bucket_for(n, max_batch_size):
    """Smallest power-of-two >= n, clamped to max_batch_size."""
    if n >= max_batch_size:
        return max_batch_size
    b = 1
    while b < n:
        b <<= 1
    return min(b, max_batch_size)


def buckets_upto(max_batch_size):
    out, b = [], 1
    while b < max_batch_size:
        out.append(b)
        b <<= 1
    out.append(max_batch_size)
    return out


class _Swap(object):
    """Queue sentinel: drain, then swap to ``model``."""

    def __init__(self, model):
        self.model = model
        self.done = threading.Event()


class Replica(Logger):
    """One warm copy of the model with a private dispatch queue."""

    #: load charged while a swap is queued/running: a swapping replica
    #: must look maximally busy to pick()/any_idle(), or new batches
    #: would be routed behind its drain + full re-warm while the other
    #: replicas sit idle
    SWAP_LOAD = 1 << 20

    def __init__(self, model, index=0, max_batch_size=64, warm=True):
        super(Replica, self).__init__()
        self.index = index
        self.max_batch_size = int(max_batch_size)
        self._queue = queue.Queue()
        self._pending = 0           # queued + running rows, approx load
        self._pending_lock = threading.Lock()
        self._retired = False       # out of dispatch, refusing batches
        self.batches_done = 0
        self.rows_done = 0
        self._stop = threading.Event()
        self._bind(model, warm=warm)
        self._thread = threading.Thread(
            target=self._work_loop, daemon=True,
            name="replica-%d" % index)
        self._thread.start()

    # -- model binding -----------------------------------------------------

    def _bind(self, model, warm=True):
        import jax
        self.model = model
        self._forward = jax.jit(model.forward_fn())
        self.warmed_buckets = []
        if warm:
            self.warm()

    def warm(self):
        """Compile every batch bucket ahead of traffic.

        The warm-up batches reach the device through the input
        pipeline's :class:`~veles_tpu.loader.prefetch.StagingRing`
        (the same H2D path streamed training shards ride): at most
        two buckets are device-resident during the sweep instead of
        every bucket's zeros accumulating, and on real accelerators
        the placement overlaps the previous bucket's compile. The
        sweep is the ``replica_warmup`` startup phase — scale-up cost
        is measured, not guessed."""
        from veles_tpu.loader.prefetch import warmup_ring
        from veles_tpu.telemetry import profiler
        book = profiler.get_cost_book()
        ring = warmup_ring()
        try:
            with profiler.phase("replica_warmup"):
                for bucket in buckets_upto(self.max_batch_size):
                    x = numpy.zeros(
                        (bucket,) + self.model.sample_shape,
                        numpy.float32)
                    staged, = ring.place((x,))
                    # force compile + execute
                    numpy.asarray(self._forward(staged))
                    # cost harvest AFTER the warming call: its compile
                    # populated the persistent XLA cache, so the
                    # harvest's lower().compile() deserializes instead
                    # of paying a second full compile — and the
                    # roofline table then covers every serving bucket
                    # alongside the train segments
                    book.harvest("serve_forward:b%d" % bucket,
                                 self._forward, (x,))
                    self.warmed_buckets.append(bucket)
        finally:
            ring.clear()
        self.debug("replica %d warm: %s v%d, buckets %s", self.index,
                   self.model.name, self.model.version,
                   self.warmed_buckets)

    # -- inference ---------------------------------------------------------

    def infer(self, batch):
        """Synchronous padded forward (runs on the worker thread)."""
        from veles_tpu.telemetry import profiler
        rows = batch.shape[0]
        bucket = bucket_for(rows, self.max_batch_size)
        if rows < bucket:
            pad = numpy.zeros((bucket - rows,) + batch.shape[1:],
                              batch.dtype)
            batch = numpy.concatenate([batch, pad], axis=0)
        with profiler.timed_op("serve_forward:b%d" % bucket):
            out = numpy.asarray(self._forward(batch))
        return out[:rows], bucket

    @property
    def load(self):
        with self._pending_lock:
            return self._pending

    def submit(self, batch, on_done):
        """Queue a batch; ``on_done(result_rows, bucket, error)`` fires
        on the worker thread. Returns False (nothing queued) once the
        replica is retired — the check shares the load-accounting lock,
        so a True return guarantees :meth:`wait_drained` sees the
        batch."""
        with self._pending_lock:
            if self._retired:
                return False
            self._pending += int(batch.shape[0])
        self._queue.put((batch, on_done))
        return True

    def retire(self, retired=True):
        """Mark the replica as leaving dispatch: subsequent
        :meth:`submit` calls are refused, so a drain that observed an
        empty queue cannot be invalidated by a late batch."""
        with self._pending_lock:
            self._retired = retired

    def swap(self, model):
        """Queue a drain-then-swap; returns an event set when done."""
        op = _Swap(model)
        with self._pending_lock:
            if self._retired:
                # leaving the pool anyway: promoting would only delay
                # the drain, and the queue may already be dead
                op.done.set()
                return op.done
            self._pending += self.SWAP_LOAD
        self._queue.put(op)
        return op.done

    def _work_loop(self):
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                break
            if isinstance(item, _Swap):
                try:
                    self._bind(item.model)
                    self.info("replica %d promoted to %s v%d",
                              self.index, item.model.name,
                              item.model.version)
                finally:
                    with self._pending_lock:
                        self._pending -= self.SWAP_LOAD
                    item.done.set()
                continue
            batch, on_done = item
            try:
                result, bucket = self.infer(batch)
                error = None
            except Exception as e:  # scatter the failure, don't die
                result, bucket = None, 0
                error = e
                self.exception("replica %d batch failed", self.index)
            finally:
                with self._pending_lock:
                    self._pending -= int(batch.shape[0])
            self.batches_done += 1
            self.rows_done += int(batch.shape[0])
            on_done(result, bucket, error)

    def wait_drained(self, timeout=60.0):
        """Block until everything this replica accepted has been
        answered (load 0, queue empty). Callers must have removed the
        replica from dispatch first, or the drain never converges."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.load == 0 and self._queue.empty():
                return True
            time.sleep(0.005)
        return self.load == 0 and self._queue.empty()

    def stop(self):
        self._stop.set()
        self._queue.put(None)
        self._thread.join(timeout=10)
        # fail whatever was still queued: a stranded batch would leave
        # its clients blocked until their response timeout
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if isinstance(item, _Swap):
                with self._pending_lock:
                    self._pending -= self.SWAP_LOAD
                item.done.set()
            elif item is not None:
                batch, on_done = item
                on_done(None, 0, RuntimeError("replica stopped"))

    def stats(self):
        return {"index": self.index, "load": self.load,
                "batches": self.batches_done, "rows": self.rows_done,
                "model": self.model.name, "version": self.model.version}


class ReplicaPool(Logger):
    """Elastic replica set: least-loaded dispatch, atomic swap,
    grow/shrink under live traffic."""

    def __init__(self, model, n_replicas=1, max_batch_size=64,
                 warm=True):
        super(ReplicaPool, self).__init__()
        self.max_batch_size = int(max_batch_size)
        self._dispatch_lock = threading.Lock()
        self._rr = 0
        self._warm = bool(warm)
        self._next_index = 0
        self._model = model
        self.replicas = []
        for _ in range(max(1, int(n_replicas))):
            self.add_replica()

    @property
    def model(self):
        return self._model

    def pick(self):
        """Least-loaded replica; round-robin breaks ties so idle
        replicas alternate instead of replica 0 taking everything."""
        with self._dispatch_lock:
            self._rr += 1
            order = self.replicas[self._rr % len(self.replicas):] + \
                self.replicas[:self._rr % len(self.replicas)]
            return min(order, key=lambda r: r.load)

    def any_idle(self):
        """True when some replica has no queued/running work — the
        batcher's dispatch gate: while every replica is busy, a forming
        batch keeps growing instead of queueing up small fragments."""
        with self._dispatch_lock:
            replicas = list(self.replicas)
        return any(r.load == 0 for r in replicas)

    def submit(self, batch, on_done):
        # pick() releases the dispatch lock before the replica accepts
        # the batch, so the picked replica may retire (scale-down)
        # in between — it refuses atomically and the batch is simply
        # re-picked; by then the victim has left the dispatch list
        while not self.pick().submit(batch, on_done):
            pass

    # -- elasticity --------------------------------------------------------

    def size(self):
        with self._dispatch_lock:
            return len(self.replicas)

    def add_replica(self):
        """Grow the pool by one warm replica. The replica compiles and
        warms every bucket BEFORE it enters the dispatch list, so
        scale-up traffic never lands on a cold JIT cache — the warm-up
        cost lands in ``veles_phase_ms{phase="replica_warmup"}``, not
        in some unlucky client's tail."""
        with self._dispatch_lock:
            index = self._next_index
            self._next_index += 1
            current = self._model
        replica = Replica(current, index=index,
                          max_batch_size=self.max_batch_size,
                          warm=self._warm)
        while True:
            with self._dispatch_lock:
                if replica.model is self._model:
                    self.replicas.append(replica)
                    n = len(self.replicas)
                    break
                # swap() promoted the pool while this replica spent
                # seconds warming against the OLD version — joining
                # dispatch now would serve stale results (and poison
                # the cache under the new version's keys) forever
                current = self._model
            replica.swap(current).wait(120)
        self.info("pool grew to %d replica(s) (+ replica %d)", n, index)
        return replica

    def remove_replica(self, timeout=60.0):
        """Shrink by one: the victim leaves the dispatch list FIRST
        (new batches can no longer route to it), then drains whatever
        it already accepted, then stops — zero in-flight requests die.
        The last replica is never removed. Returns the drained replica
        or None when the pool is already at one."""
        with self._dispatch_lock:
            if len(self.replicas) <= 1:
                return None
            # take the least-loaded: the shortest drain, so capacity
            # recovers to the target fastest
            victim = min(self.replicas, key=lambda r: r.load)
            self.replicas.remove(victim)
            n = len(self.replicas)
        # refuse batches from a concurrent submit() that picked the
        # victim before it left the list — without this, a batch can
        # land AFTER the drain check and strand its futures forever
        victim.retire()
        if not victim.wait_drained(timeout):
            # drain stalled (wedged forward): put it back rather than
            # kill requests — the autoscaler retries next tick
            self.warning("replica %d did not drain in %.0fs; "
                         "returning it to dispatch", victim.index,
                         timeout)
            victim.retire(False)
            with self._dispatch_lock:
                self.replicas.append(victim)
            return None
        victim.stop()
        self.info("pool shrank to %d replica(s) (- replica %d)", n,
                  victim.index)
        return victim

    # -- swap / stats / lifecycle ------------------------------------------

    def swap(self, model, timeout=120.0):
        """Hot-swap every replica, one at a time: each drains its
        accepted work, promotes, re-warms, and rejoins dispatch while
        the others keep serving — capacity dips by 1/N, never to 0.
        A replica added concurrently (autoscaler) re-checks the pool
        model under the dispatch lock before joining, so setting
        ``_model`` and snapshotting the list in ONE critical section
        guarantees every replica is either in this snapshot (promoted
        here) or promotes itself before dispatch."""
        with self._dispatch_lock:
            self._model = model
            replicas = list(self.replicas)
        for replica in replicas:
            done = replica.swap(model)
            if not done.wait(timeout):
                raise TimeoutError(
                    "replica %d did not finish the swap in %.0fs" %
                    (replica.index, timeout))
        self.info("pool promoted to %s v%d", model.name, model.version)

    def stats(self):
        with self._dispatch_lock:
            replicas = list(self.replicas)
        return [r.stats() for r in replicas]

    def stop(self):
        with self._dispatch_lock:
            replicas = list(self.replicas)
            self.replicas = []
        for replica in replicas:
            replica.stop()
