"""Dynamic-batching inference serving (a new layer over the platform).

The training side of the stack serves HTTP through
:class:`~veles_tpu.restful_api.RESTfulAPI` riding a live workflow: one
request, one forward dispatch. This package is the production serving
path the ROADMAP north star asks for — concurrent requests coalesce
into hardware-sized batches, one jitted forward runs per batch, and a
pool of warm model replicas absorbs the traffic:

* :mod:`~veles_tpu.serving.model_store` — load serveable models from
  :class:`~veles_tpu.snapshotter.SnapshotterToFile` outputs, live
  workflows or ``export/`` packages; version pinning and hot-swap.
* :mod:`~veles_tpu.serving.replica` — N model replicas with warm JIT
  caches keyed by batch-shape buckets, least-loaded dispatch.
* :mod:`~veles_tpu.serving.engine` — the dynamic batcher: bounded
  admission queue, pad-to-bucket batching, scatter back to futures.
* :mod:`~veles_tpu.serving.frontend` — the HTTP frontend (same request
  contract as ``restful_api``), overload → 503 + ``Retry-After``.
* :mod:`~veles_tpu.serving.metrics` — QPS / queue depth / batch
  occupancy / latency percentiles, exposed at ``/metrics`` and pushed
  to the :mod:`~veles_tpu.web_status` dashboard.

Entry point: ``python -m veles_tpu serve --model <snapshot-or-package>``
(see ``docs/SERVING.md``).
"""

from veles_tpu.serving.engine import DynamicBatcher, EngineOverloaded
from veles_tpu.serving.model_store import ModelStore, ServeableModel
from veles_tpu.serving.replica import Replica, ReplicaPool

__all__ = ["DynamicBatcher", "EngineOverloaded", "ModelStore",
           "ServeableModel", "Replica", "ReplicaPool"]
