"""Dynamic-batching inference serving (a new layer over the platform).

The training side of the stack serves HTTP through
:class:`~veles_tpu.restful_api.RESTfulAPI` riding a live workflow: one
request, one forward dispatch. This package is the production serving
path the ROADMAP north star asks for — concurrent requests coalesce
into hardware-sized batches, one jitted forward runs per batch, and an
**elastic** pool of warm model replicas absorbs the traffic:

* :mod:`~veles_tpu.serving.model_store` — load serveable models from
  :class:`~veles_tpu.snapshotter.SnapshotterToFile` outputs, live
  workflows or ``export/`` packages; version pinning, hot-swap, and
  keep-last-K retention so long-running servers don't hoard versions.
* :mod:`~veles_tpu.serving.replica` — N model replicas with warm JIT
  caches keyed by batch-shape buckets, least-loaded dispatch,
  grow/shrink under live traffic (scale-down drains, zero in-flight
  loss; warm-up rides the staging-ring H2D path).
* :mod:`~veles_tpu.serving.engine` — the dynamic batcher: result
  cache consult → tenant admission → pad-to-bucket batching → scatter
  back to futures (and into the cache).
* :mod:`~veles_tpu.serving.cache` — content-addressed LRU result
  cache with byte budget, TTL, and epoch invalidation on hot swap.
* :mod:`~veles_tpu.serving.admission` — weighted-fair per-tenant QoS
  admission (interactive > batch > best_effort); an overloaded tenant
  sheds onto itself with Retry-After from its own drain rate.
* :mod:`~veles_tpu.serving.autoscale` — telemetry-driven replica
  autoscaler: bursts scale up fast, idle drains slow, flap never.
* :mod:`~veles_tpu.serving.frontend` — the HTTP frontend (same request
  contract as ``restful_api``), multi-model routing by name, overload
  → 503 + ``Retry-After``.
* :mod:`~veles_tpu.serving.metrics` — QPS / queue depth / batch
  occupancy / latency percentiles, exposed at ``/metrics`` and pushed
  to the :mod:`~veles_tpu.web_status` dashboard.

Entry point: ``python -m veles_tpu serve --model <snapshot-or-package>``
(see ``docs/SERVING.md``).
"""

from veles_tpu.serving.engine import DynamicBatcher, EngineOverloaded
from veles_tpu.serving.model_store import ModelStore, ServeableModel
from veles_tpu.serving.replica import Replica, ReplicaPool

__all__ = ["DynamicBatcher", "EngineOverloaded", "ModelStore",
           "ServeableModel", "Replica", "ReplicaPool"]
