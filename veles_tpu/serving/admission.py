"""Per-tenant weighted-fair QoS admission for the serving engine.

PR 3's admission was one global outstanding-sample cap: past it,
*everyone* got 503 — a single greedy client could starve every other
tenant of the service. This controller replaces that gate with
weighted-fair token accounting:

* every tenant has a **weight** (share of capacity) and a **QoS
  class** — ``interactive`` > ``batch`` > ``best_effort`` — which
  multiplies the weight (4x / 2x / 1x by default), so an interactive
  tenant's traffic displaces batch backfill, never the reverse;
* a tenant's **guaranteed share** is ``capacity * w_i / W`` where
  ``W`` sums the weights of *recently active* tenants (an idle
  tenant's share is lendable, a returning tenant reclaims it within
  one ``activity_window_s``);
* admission is **work-conserving with reservations**: a tenant under
  its share is always admitted (global capacity permitting); a tenant
  *over* its share may borrow only headroom no active tenant has a
  claim on — the sum of other active tenants' unused shares stays
  reserved for them. An overloaded tenant therefore sheds onto
  itself: the greedy client hits ITS bound while the light tenant's
  reserved share admits every one of its requests
  (``tests/test_serving_elastic.py::
  test_greedy_tenant_cannot_starve_weighted_share``);
* ``Retry-After`` on a shed is computed from **that tenant's own
  drain rate** (completions/s over a sliding window): the answer to
  "when will MY backlog clear", not a global constant.

The default tenant (no ``X-Tenant`` header) degenerates to exactly
the old behavior — one tenant owning 100% of capacity IS the global
cap — so single-tenant deployments see no change.

Telemetry: ``veles_serving_tenant_{admitted,shed}_total{tenant,qos}``,
``veles_serving_tenant_outstanding{tenant}``, and the windowed
``veles_serving_tenant_shed_ratio{tenant}`` gauge the
``tenant_shed_burn`` alert rule watches.
"""

import math
import threading
import time

# the share math itself lives in veles_tpu/fairshare.py — ONE ledger
# shared with the training scheduler (veles_tpu/sched); QOS_MULTIPLIER
# and DEFAULT_QOS stay importable from here for compatibility
from veles_tpu.fairshare import (DEFAULT_QOS, QOS_MULTIPLIER,
                                 ShareAccount, guaranteed_share,
                                 reserved_claim)
from veles_tpu.logger import Logger
from veles_tpu.serving.engine import EngineOverloaded
from veles_tpu.telemetry.registry import get_registry

DEFAULT_TENANT = "default"

#: hard bound on distinct tenant buckets: the ``X-Tenant`` header is
#: CLIENT-controlled, so without a cap a client spraying random names
#: allocates unbounded accounting state and per-tenant metric children.
#: Past the cap (after reclaiming idle auto-created buckets) unknown
#: names share one ``overflow`` bucket — the spray degrades into a
#: single tenant shedding onto itself instead of a memory leak.
MAX_TENANTS = 256
OVERFLOW_TENANT = "overflow"

#: shed-ratio gauge publishes only once this many admission decisions
#: landed in the window (mirrors the cache hit-ratio discipline)
SHED_RATIO_MIN_WINDOW = 20


class TenantOverloaded(EngineOverloaded):
    """This tenant's share is exhausted — retry after ITS drain."""

    def __init__(self, tenant, retry_after=1):
        super(TenantOverloaded, self).__init__(
            "tenant %r is over its admission share" % tenant,
            retry_after=retry_after)
        self.tenant = tenant


#: a serving tenant IS a fair-share account (the historical name is
#: kept: tests and the frontend construct tenants through the
#: controller, but the class identity is part of the module surface)
_Tenant = ShareAccount


class AdmissionController(Logger):
    """Weighted-fair per-tenant admission over one shared capacity."""

    def __init__(self, capacity, tenants=None, default_weight=1.0,
                 default_qos=DEFAULT_QOS, activity_window_s=10.0,
                 drain_window_s=5.0, registry=None, model="default",
                 max_tenants=MAX_TENANTS):
        super(AdmissionController, self).__init__()
        self.capacity = int(capacity)
        self.model = str(model)
        self.max_tenants = max(2, int(max_tenants))
        self.activity_window_s = float(activity_window_s)
        self.drain_window_s = float(drain_window_s)
        self.default_weight = float(default_weight)
        self.default_qos = default_qos
        self._lock = threading.Lock()
        self._tenants = {}
        self._pinned_qos = set()
        self._total = 0
        for spec in (tenants or {}).items():
            name, cfg = spec
            if isinstance(cfg, dict):
                self._tenants[name] = _Tenant(
                    name, weight=cfg.get("weight", 1.0),
                    qos=cfg.get("qos", default_qos))
            else:
                self._tenants[name] = _Tenant(name, weight=float(cfg),
                                              qos=default_qos)
        # operator-declared tenants are never evicted for cardinality
        self._configured = set(self._tenants)
        # every family carries the model label: multi-model serving
        # runs one controller per model, and unlabeled children would
        # merge across them (and one model's idle-eviction would reset
        # another's live counters)
        registry = registry or get_registry()
        self._m_admitted = registry.counter(
            "veles_serving_tenant_admitted_total",
            "Samples admitted per tenant",
            labels=("model", "tenant", "qos"))
        self._m_shed = registry.counter(
            "veles_serving_tenant_shed_total",
            "Samples shed per tenant (503)",
            labels=("model", "tenant", "qos"))
        self._g_outstanding = registry.gauge(
            "veles_serving_tenant_outstanding",
            "In-flight samples per tenant",
            labels=("model", "tenant"))
        self._g_shed_ratio = registry.gauge(
            "veles_serving_tenant_shed_ratio",
            "Shed fraction over the recent decision window per tenant",
            labels=("model", "tenant"))

    # -- tenant registry ---------------------------------------------------

    def _tenant(self, name, qos=None, now=None):
        tenant = self._tenants.get(name)
        if tenant is None:
            if len(self._tenants) >= self.max_tenants:
                self._evict_idle_locked(now)
            if len(self._tenants) >= self.max_tenants and \
                    name != OVERFLOW_TENANT:
                # every bucket is busy or recently active: unknown
                # names share the overflow bucket (callers must use
                # the RETURNED tenant's name for settle/metrics)
                return self._tenant(OVERFLOW_TENANT, qos=qos, now=now)
            tenant = self._tenants[name] = _Tenant(
                name, weight=self.default_weight,
                qos=qos or self.default_qos)
        elif qos and tenant.qos != qos and name not in self._pinned_qos:
            tenant.qos = qos            # client-declared class (unpinned)
        return tenant

    def _evict_idle_locked(self, now=None):
        """Reclaim auto-created buckets idle past the activity window:
        their shares are no longer reserved anyway, and dropping their
        metric children is what keeps /metrics cardinality bounded."""
        now = time.time() if now is None else now
        for name in list(self._tenants):
            if name in self._configured or name == DEFAULT_TENANT:
                continue
            tenant = self._tenants[name]
            if tenant.outstanding == 0 and \
                    now - tenant.last_active > self.activity_window_s:
                del self._tenants[name]
                self._g_outstanding.remove(model=self.model,
                                           tenant=name)
                self._g_shed_ratio.remove(model=self.model,
                                          tenant=name)
                self._m_admitted.remove(model=self.model, tenant=name)
                self._m_shed.remove(model=self.model, tenant=name)

    def configure(self, name, weight=None, qos=None, pin_qos=False):
        """Operator-set weight/class for a tenant; ``pin_qos`` stops
        clients from self-promoting via the QoS header."""
        with self._lock:
            tenant = self._tenant(name)
            self._configured.add(tenant.name)
            if weight is not None:
                tenant.weight = float(weight)
            if qos is not None:
                if qos not in QOS_MULTIPLIER:
                    raise ValueError("unknown QoS class %r (one of %s)"
                                     % (qos, sorted(QOS_MULTIPLIER)))
                tenant.qos = qos
            if pin_qos:
                self._pinned_qos.add(name)
        return self

    # -- the admission decision --------------------------------------------

    def _share_locked(self, tenant, now):
        """This tenant's guaranteed share (>=1) vs active peers."""
        return guaranteed_share(self.capacity, tenant,
                                self._tenants.values(), now,
                                self.activity_window_s)

    def _reserved_locked(self, tenant, now):
        """Unused share active OTHER tenants still hold a claim on."""
        return reserved_claim(self.capacity, tenant,
                              self._tenants.values(), now,
                              self.activity_window_s)

    def admit(self, tenant_name=None, n=1, qos=None, now=None):
        """Admit ``n`` samples for the tenant or raise
        :class:`TenantOverloaded` with its drain-derived Retry-After.
        Returns the ACCOUNTING bucket name — usually ``tenant_name``,
        but past the tenant cap an unknown name aliases to the shared
        overflow bucket, and :meth:`settle` must use the returned
        name or the outstanding count leaks."""
        now = time.time() if now is None else now
        name = tenant_name or DEFAULT_TENANT
        with self._lock:
            tenant = self._tenant(name, qos=qos, now=now)
            tenant.last_active = now
            admitted = False
            if self._total + n <= self.capacity:
                share = self._share_locked(tenant, now)
                if tenant.outstanding + n <= share:
                    admitted = True          # inside the guarantee
                else:
                    # borrowing: only headroom nobody active claims
                    reserved = self._reserved_locked(tenant, now)
                    free = self.capacity - self._total - reserved
                    admitted = n <= free
            if admitted:
                tenant.outstanding += n
                tenant.admitted_total += n
                self._total += n
                tenant.record_decision(True)
                retry_after = None
            else:
                tenant.shed_total += n
                tenant.record_decision(False)
                retry_after = self._retry_after_locked(tenant, now)
            self._publish_locked(tenant)
        if retry_after is not None:
            self._m_shed.labels(model=self.model, tenant=tenant.name,
                                qos=tenant.qos).inc(n)
            raise TenantOverloaded(tenant.name, retry_after=retry_after)
        self._m_admitted.labels(model=self.model, tenant=tenant.name,
                                qos=tenant.qos).inc(n)
        return tenant.name

    def _retry_after_locked(self, tenant, now):
        """ceil(own backlog / own drain rate), clamped to [1, 30] —
        a tenant that drains fast gets told to come right back; one
        with a dead-slow backlog is not told to hammer every second."""
        rate = tenant.drain_rate(now, self.drain_window_s)
        if rate <= 0.0:
            return 1                     # no history: optimistic
        return int(min(30, max(1, math.ceil(
            max(tenant.outstanding, 1) / rate))))

    def settle(self, tenant_name=None, n=1, now=None):
        """``n`` of the tenant's samples finished (any outcome)."""
        now = time.time() if now is None else now
        name = tenant_name or DEFAULT_TENANT
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                return
            tenant.outstanding = max(0, tenant.outstanding - n)
            self._total = max(0, self._total - n)
            for _ in range(n):
                tenant.completions.append(now)
            self._publish_locked(tenant)

    # -- reading -----------------------------------------------------------

    def _publish_locked(self, tenant):
        self._g_outstanding.labels(model=self.model,
                                   tenant=tenant.name).set(
            tenant.outstanding)
        if len(tenant.decisions) >= SHED_RATIO_MIN_WINDOW:
            self._g_shed_ratio.labels(
                model=self.model, tenant=tenant.name).set(
                tenant.shed_window / float(len(tenant.decisions)))

    def total_outstanding(self):
        with self._lock:
            return self._total

    def stats(self, now=None):
        now = time.time() if now is None else now
        with self._lock:
            return {
                "capacity": self.capacity,
                "outstanding": self._total,
                "tenants": {
                    t.name: {
                        "weight": t.weight, "qos": t.qos,
                        "outstanding": t.outstanding,
                        "admitted": t.admitted_total,
                        "shed": t.shed_total,
                        "share": round(self._share_locked(t, now), 1),
                        "drain_per_s": round(
                            t.drain_rate(now, self.drain_window_s), 2),
                    } for t in self._tenants.values()},
            }
