"""Serveable model loading: snapshots, live workflows, export packages.

A :class:`ServeableModel` is the minimal thing a replica needs to run
inference: an ordered list of ``(apply_fn, params)`` layers composing a
pure batch forward, plus the sample shape the frontend validates
against. Three construction paths cover the platform's artifacts:

* :meth:`ServeableModel.from_workflow` — a live (initialized or
  restored) workflow with a ``forwards`` chain; the units' own pure
  ``apply`` methods are reused, so serving math is bit-identical to the
  training-time forward.
* :meth:`ServeableModel.from_snapshot` — a
  :class:`~veles_tpu.snapshotter.SnapshotterToFile` output (plain path,
  ``_current`` symlink, directory of snapshots, ``http(s)://`` or
  ``sqlite://`` URI — everything ``import_`` accepts).
* :meth:`ServeableModel.from_package` — an ``export/`` inference
  package (directory or ``.tar`` with ``contents.json``); the dense
  unit classes are rebuilt as standalone closures from the stored
  weights, no workflow object required.

:class:`ModelStore` keeps named, versioned models with pinning and
atomic promotion — the hot-swap contract the replica pool drains
against (see ``docs/SERVING.md``).
"""

import io
import json
import os
import tarfile
import threading

import numpy

from veles_tpu.logger import Logger


class ModelLoadError(Exception):
    """The artifact at the given path is not a serveable model."""


def _softmax(y):
    import jax.numpy as jnp
    z = y - jnp.max(y, axis=1, keepdims=True)
    e = jnp.exp(z)
    return e / jnp.sum(e, axis=1, keepdims=True)


def _dense_layer(entry, resolve):
    """Rebuild one package unit as ``(apply_fn, params)``."""
    cls = entry["class"]["name"]
    data = entry["data"]
    if cls in ("All2All", "All2AllTanh", "All2AllRELU",
               "All2AllStrictRELU", "All2AllSigmoid", "All2AllSoftmax"):
        from veles_tpu.nn.activation import get_activation
        activation = data["activation"]
        out_shape = tuple(data["output_sample_shape"])
        act = None if activation == "softmax" else \
            get_activation(activation)
        params = {"weights": resolve(data["weights"])}
        if "bias" in data:
            params["bias"] = resolve(data["bias"])

        def apply(params, x, _act=act, _out=out_shape):
            import jax.numpy as jnp
            batch = x.shape[0]
            y = jnp.dot(x.reshape(batch, -1), params["weights"])
            if "bias" in params:
                y = y + params["bias"]
            y = _softmax(y) if _act is None else _act(y)
            return y.reshape((batch,) + _out)

        return apply, params
    if cls == "ActivationUnit":
        from veles_tpu.nn.activation import get_activation
        act = get_activation(data["activation"])
        return (lambda params, x, _act=act: _act(x)), {}
    if cls == "DropoutForward":
        # inference: inverted dropout is identity
        return (lambda params, x: x), {}
    raise ModelLoadError(
        "package unit %r is not supported by the serving loader "
        "(serve the snapshot instead — from_workflow reuses any "
        "unit's own apply)" % cls)


class ServeableModel(object):
    """An immutable inference function: layers + params + geometry."""

    def __init__(self, layers, sample_shape, name="model", version=1,
                 source=None):
        self.layers = list(layers)       # [(apply_fn, params_dict), ...]
        self.sample_shape = tuple(sample_shape)
        self.name = name
        self.version = int(version)
        self.source = source

    def __repr__(self):
        return "<ServeableModel %s v%d sample=%s from %s>" % (
            self.name, self.version, self.sample_shape, self.source)

    def forward_fn(self):
        """A pure ``fn(x) -> y`` over device arrays, closing over the
        params — the thing replicas ``jax.jit``."""
        import jax.numpy as jnp
        layers = [(fn, {k: jnp.asarray(v) for k, v in params.items()})
                  for fn, params in self.layers]

        def forward(x):
            for fn, params in layers:
                x = fn(params, x)
            return x

        return forward

    def __call__(self, batch):
        """Convenience un-warmed forward (tests, sanity checks)."""
        import jax
        if getattr(self, "_jitted", None) is None:
            self._jitted = jax.jit(self.forward_fn())
        batch = numpy.ascontiguousarray(batch, numpy.float32)
        return numpy.asarray(self._jitted(batch))

    # -- construction ------------------------------------------------------

    @classmethod
    def from_workflow(cls, workflow, name=None, version=1, source=None):
        forwards = getattr(workflow, "forwards", None)
        if not forwards:
            raise ModelLoadError(
                "workflow %r has no forwards chain to serve" % workflow)
        layers = []
        for fwd in forwards:
            if hasattr(fwd, "testing"):
                # dropout & co. must be identity at serving time
                fwd.testing = True
            params = {}
            if getattr(fwd, "has_weights", False):
                params["weights"] = numpy.asarray(
                    fwd.weights.map_read(), numpy.float32)
                if getattr(fwd, "include_bias", False) and \
                        fwd.bias.mem is not None:
                    params["bias"] = numpy.asarray(
                        fwd.bias.map_read(), numpy.float32)
            layers.append((fwd.apply, params))
        sample_shape = cls._workflow_sample_shape(workflow, forwards)
        return cls(layers, sample_shape,
                   name=name or getattr(workflow, "name", "model"),
                   version=version, source=source)

    @staticmethod
    def _workflow_sample_shape(workflow, forwards):
        loader = getattr(workflow, "loader", None)
        if loader is not None and \
                getattr(loader.minibatch_data, "mem", None) is not None:
            return tuple(loader.minibatch_data.shape[1:])
        first = forwards[0]
        if getattr(first, "has_weights", False) and \
                first.weights.mem is not None:
            return (int(first.weights.shape[0]),)
        raise ModelLoadError("cannot infer the model's sample shape")

    @classmethod
    def from_snapshot(cls, uri, name=None, version=1):
        from veles_tpu.snapshotter import SnapshotterToFile
        workflow = SnapshotterToFile.import_(uri)
        return cls.from_workflow(workflow, name=name, version=version,
                                 source=str(uri))

    @classmethod
    def from_package(cls, path, name=None, version=1):
        contents, members = _read_package(path)
        wf_info = contents.get("workflow") or {}
        arrays = {m: members[m] for m in members}

        def resolve(ref):
            arr = arrays.get(ref)
            if arr is None:
                raise ModelLoadError("package member %r missing" % ref)
            return numpy.asarray(arr, numpy.float32)

        layers = [_dense_layer(entry, resolve)
                  for entry in wf_info.get("units", [])]
        if not layers:
            raise ModelLoadError("package %s has no units" % path)
        input_shape = contents.get("input_shape")
        if input_shape:
            sample_shape = tuple(input_shape[1:])
        else:
            first_w = layers[0][1].get("weights")
            if first_w is None:
                raise ModelLoadError("cannot infer sample shape from %s"
                                     % path)
            sample_shape = (int(first_w.shape[0]),)
        return cls(layers, sample_shape,
                   name=name or wf_info.get("name", "model"),
                   version=version, source=str(path))


def _read_package(path):
    """contents.json + decoded ``@NNNN`` npy members, dir or tar."""
    members = {}
    if os.path.isdir(path):
        with open(os.path.join(path, "contents.json"), "rb") as f:
            contents = json.loads(f.read())
        for fname in os.listdir(path):
            if fname.startswith("@") and fname.endswith(".npy"):
                members[fname[:-len(".npy")]] = numpy.load(
                    os.path.join(path, fname), allow_pickle=False)
    else:
        with tarfile.open(path, "r") as tar:
            contents = json.loads(tar.extractfile("contents.json").read())
            for info in tar.getmembers():
                if info.name.startswith("@") and \
                        info.name.endswith(".npy"):
                    members[info.name[:-len(".npy")]] = numpy.load(
                        io.BytesIO(tar.extractfile(info).read()),
                        allow_pickle=False)
    return contents, members


def _is_package(path):
    if os.path.isdir(path):
        return os.path.exists(os.path.join(path, "contents.json"))
    if str(path).endswith(".tar") and os.path.exists(path):
        try:
            with tarfile.open(path, "r") as tar:
                return "contents.json" in tar.getnames()
        except tarfile.TarError:
            return False
    return False


class ModelStore(Logger):
    """Named, versioned serveable models with pinning and retention.

    ``load()`` auto-detects the artifact kind; versions count up per
    name. ``get(name)`` returns the pinned version if one is set, else
    the newest — the replica pool promotes whatever ``get`` returns, so
    pin-then-swap is the rollback procedure (``docs/SERVING.md``).

    **Disk hygiene** (ISSUE 14): a long-running multi-model server
    swaps new versions in for months — without retention every retired
    version's weights stay resident and every snapshot file it was
    loaded from stays on disk. ``keep_last=K`` bounds each name to its
    newest K versions: on ``add()``, older *unpinned* versions are
    retired from memory, and with ``prune_disk=True`` their source
    snapshot **files** are deleted too (only plain local files the
    store itself loaded — never directories, URIs, packages, or a
    source another retained version still references). Pinned versions
    are exempt: a pin is the operator's rollback anchor and outlives
    any retention sweep.
    """

    def __init__(self, keep_last=None, prune_disk=False):
        super(ModelStore, self).__init__()
        self._lock = threading.Lock()
        self._models = {}   # name -> {version: ServeableModel}
        self._pins = {}     # name -> version
        self.keep_last = int(keep_last) if keep_last else None
        self.prune_disk = bool(prune_disk)

    def load(self, source, name=None, version=None):
        """Load an artifact and register it; returns the model.

        ``source`` may be an export package (dir / ``.tar`` holding
        ``contents.json``), a snapshot file or URI, or a snapshot
        *directory* (the newest snapshot inside is taken — the shape
        ``SnapshotterToFile`` leaves behind). A corrupt newest entry
        in a snapshot directory (crash mid-copy, torn rsync) is
        skipped with a warning and the next-newest loadable snapshot
        serves instead — a serving restart must come up with the best
        artifact that actually loads, mirroring the trainer's
        auto-resume discipline (``snapshotter.restore_latest``)."""
        path = str(source)
        if _is_package(path):
            model = ServeableModel.from_package(path, name=name)
        elif os.path.isdir(path):
            model = self._load_from_snapshot_dir(path, name)
        else:
            model = ServeableModel.from_snapshot(path, name=name)
        return self.add(model, version=version)

    def _load_from_snapshot_dir(self, path, name):
        from veles_tpu.snapshotter import snapshot_candidates
        candidates = snapshot_candidates(path)
        if not candidates:
            raise ModelLoadError("no snapshots under %s" % path)
        last_error = None
        for candidate in candidates:
            try:
                return ServeableModel.from_snapshot(candidate,
                                                    name=name)
            except Exception as e:
                last_error = e
                self.warning("skipping corrupt/unloadable snapshot "
                             "%s: %s", candidate, e)
        raise ModelLoadError(
            "no loadable snapshot under %s (newest error: %s)" %
            (path, last_error))

    def add(self, model, version=None, name=None):
        """Register under ``name`` (default: the model's own name).
        A serving route passes its route name so two routes hosting
        variants that share a model name never overwrite each other's
        version maps — the model object itself is not renamed."""
        with self._lock:
            key = name or model.name
            versions = self._models.setdefault(key, {})
            if version is None:
                version = max(versions, default=0) + 1
            model.version = int(version)
            versions[model.version] = model
            retired = self._retire_locked(key)
        self.info("registered %s v%d (from %s)", key,
                  model.version, model.source)
        for old in retired:
            self._prune_source(old)
        return model

    def _retire_locked(self, name):
        """Drop the oldest unpinned versions beyond ``keep_last``."""
        if not self.keep_last:
            return []
        versions = self._models.get(name, {})
        pinned = self._pins.get(name)
        retired = []
        for v in sorted(versions):
            if len(versions) <= self.keep_last:
                break
            if v == pinned or v == max(versions):
                continue                # pinned + newest are exempt
            retired.append(versions.pop(v))
        return retired

    def _prune_source(self, model):
        """Delete a retired version's snapshot FILE, conservatively."""
        self.info("retired %s v%d (keep_last=%d)", model.name,
                  model.version, self.keep_last)
        if not self.prune_disk:
            return
        source = model.source
        if not source or not os.path.isfile(source) or \
                _is_package(source):
            return                      # only plain local snapshot files
        with self._lock:
            still_used = any(
                m.source == source
                for versions in self._models.values()
                for m in versions.values())
        if still_used:
            return
        try:
            os.remove(source)
            self.info("pruned retired snapshot %s", source)
        except OSError as e:
            self.warning("could not prune %s: %s", source, e)

    def get(self, name=None, version=None):
        with self._lock:
            if name is None:
                if len(self._models) != 1:
                    raise KeyError(
                        "store holds %d models — name one of %s" %
                        (len(self._models), sorted(self._models)))
                name = next(iter(self._models))
            versions = self._models.get(name)
            if not versions:
                raise KeyError("no model named %r" % name)
            if version is None:
                version = self._pins.get(name, max(versions))
            model = versions.get(int(version))
            if model is None:
                raise KeyError("no version %s of %r (have %s)" %
                               (version, name, sorted(versions)))
            return model

    def pin(self, name, version):
        """Pin ``get(name)`` to an exact version (rollback lever)."""
        with self._lock:
            versions = self._models.get(name) or {}
            if int(version) not in versions:
                raise KeyError("no version %s of %r (have %s)" %
                               (version, name, sorted(versions)))
            self._pins[name] = int(version)

    def unpin(self, name):
        with self._lock:
            self._pins.pop(name, None)

    def versions(self, name):
        with self._lock:
            return sorted(self._models.get(name, {}))

    def names(self):
        with self._lock:
            return sorted(self._models)
