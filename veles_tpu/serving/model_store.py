"""Serveable model loading: snapshots, live workflows, export packages.

A :class:`ServeableModel` is the minimal thing a replica needs to run
inference: an ordered list of ``(apply_fn, params)`` layers composing a
pure batch forward, plus the sample shape the frontend validates
against. Three construction paths cover the platform's artifacts:

* :meth:`ServeableModel.from_workflow` — a live (initialized or
  restored) workflow with a ``forwards`` chain; the units' own pure
  ``apply`` methods are reused, so serving math is bit-identical to the
  training-time forward.
* :meth:`ServeableModel.from_snapshot` — a
  :class:`~veles_tpu.snapshotter.SnapshotterToFile` output (plain path,
  ``_current`` symlink, directory of snapshots, ``http(s)://`` or
  ``sqlite://`` URI — everything ``import_`` accepts).
* :meth:`ServeableModel.from_package` — an ``export/`` inference
  package (directory or ``.tar`` with ``contents.json``); the dense
  unit classes are rebuilt as standalone closures from the stored
  weights, no workflow object required.

:class:`ModelStore` keeps named, versioned models with pinning and
atomic promotion — the hot-swap contract the replica pool drains
against (see ``docs/SERVING.md``).
"""

import io
import json
import os
import tarfile
import threading

import numpy

from veles_tpu.logger import Logger


class ModelLoadError(Exception):
    """The artifact at the given path is not a serveable model."""


def _softmax(y):
    import jax.numpy as jnp
    z = y - jnp.max(y, axis=1, keepdims=True)
    e = jnp.exp(z)
    return e / jnp.sum(e, axis=1, keepdims=True)


def _dense_layer(entry, resolve):
    """Rebuild one package unit as ``(apply_fn, params)``."""
    cls = entry["class"]["name"]
    data = entry["data"]
    if cls in ("All2All", "All2AllTanh", "All2AllRELU",
               "All2AllStrictRELU", "All2AllSigmoid", "All2AllSoftmax"):
        from veles_tpu.nn.activation import get_activation
        activation = data["activation"]
        out_shape = tuple(data["output_sample_shape"])
        act = None if activation == "softmax" else \
            get_activation(activation)
        params = {"weights": resolve(data["weights"])}
        if "bias" in data:
            params["bias"] = resolve(data["bias"])

        def apply(params, x, _act=act, _out=out_shape):
            import jax.numpy as jnp
            batch = x.shape[0]
            y = jnp.dot(x.reshape(batch, -1), params["weights"])
            if "bias" in params:
                y = y + params["bias"]
            y = _softmax(y) if _act is None else _act(y)
            return y.reshape((batch,) + _out)

        return apply, params
    if cls == "ActivationUnit":
        from veles_tpu.nn.activation import get_activation
        act = get_activation(data["activation"])
        return (lambda params, x, _act=act: _act(x)), {}
    if cls == "DropoutForward":
        # inference: inverted dropout is identity
        return (lambda params, x: x), {}
    raise ModelLoadError(
        "package unit %r is not supported by the serving loader "
        "(serve the snapshot instead — from_workflow reuses any "
        "unit's own apply)" % cls)


class ServeableModel(object):
    """An immutable inference function: layers + params + geometry."""

    def __init__(self, layers, sample_shape, name="model", version=1,
                 source=None):
        self.layers = list(layers)       # [(apply_fn, params_dict), ...]
        self.sample_shape = tuple(sample_shape)
        self.name = name
        self.version = int(version)
        self.source = source

    def __repr__(self):
        return "<ServeableModel %s v%d sample=%s from %s>" % (
            self.name, self.version, self.sample_shape, self.source)

    def forward_fn(self):
        """A pure ``fn(x) -> y`` over device arrays, closing over the
        params — the thing replicas ``jax.jit``."""
        import jax.numpy as jnp
        layers = [(fn, {k: jnp.asarray(v) for k, v in params.items()})
                  for fn, params in self.layers]

        def forward(x):
            for fn, params in layers:
                x = fn(params, x)
            return x

        return forward

    def __call__(self, batch):
        """Convenience un-warmed forward (tests, sanity checks)."""
        import jax
        if getattr(self, "_jitted", None) is None:
            self._jitted = jax.jit(self.forward_fn())
        batch = numpy.ascontiguousarray(batch, numpy.float32)
        return numpy.asarray(self._jitted(batch))

    # -- construction ------------------------------------------------------

    @classmethod
    def from_workflow(cls, workflow, name=None, version=1, source=None):
        forwards = getattr(workflow, "forwards", None)
        if not forwards:
            raise ModelLoadError(
                "workflow %r has no forwards chain to serve" % workflow)
        layers = []
        for fwd in forwards:
            if hasattr(fwd, "testing"):
                # dropout & co. must be identity at serving time
                fwd.testing = True
            params = {}
            if getattr(fwd, "has_weights", False):
                params["weights"] = numpy.asarray(
                    fwd.weights.map_read(), numpy.float32)
                if getattr(fwd, "include_bias", False) and \
                        fwd.bias.mem is not None:
                    params["bias"] = numpy.asarray(
                        fwd.bias.map_read(), numpy.float32)
            layers.append((fwd.apply, params))
        sample_shape = cls._workflow_sample_shape(workflow, forwards)
        return cls(layers, sample_shape,
                   name=name or getattr(workflow, "name", "model"),
                   version=version, source=source)

    @staticmethod
    def _workflow_sample_shape(workflow, forwards):
        loader = getattr(workflow, "loader", None)
        if loader is not None and \
                getattr(loader.minibatch_data, "mem", None) is not None:
            return tuple(loader.minibatch_data.shape[1:])
        first = forwards[0]
        if getattr(first, "has_weights", False) and \
                first.weights.mem is not None:
            return (int(first.weights.shape[0]),)
        raise ModelLoadError("cannot infer the model's sample shape")

    @classmethod
    def from_snapshot(cls, uri, name=None, version=1):
        from veles_tpu.snapshotter import SnapshotterToFile
        workflow = SnapshotterToFile.import_(uri)
        return cls.from_workflow(workflow, name=name, version=version,
                                 source=str(uri))

    @classmethod
    def from_package(cls, path, name=None, version=1):
        contents, members = _read_package(path)
        wf_info = contents.get("workflow") or {}
        arrays = {m: members[m] for m in members}

        def resolve(ref):
            arr = arrays.get(ref)
            if arr is None:
                raise ModelLoadError("package member %r missing" % ref)
            return numpy.asarray(arr, numpy.float32)

        layers = [_dense_layer(entry, resolve)
                  for entry in wf_info.get("units", [])]
        if not layers:
            raise ModelLoadError("package %s has no units" % path)
        input_shape = contents.get("input_shape")
        if input_shape:
            sample_shape = tuple(input_shape[1:])
        else:
            first_w = layers[0][1].get("weights")
            if first_w is None:
                raise ModelLoadError("cannot infer sample shape from %s"
                                     % path)
            sample_shape = (int(first_w.shape[0]),)
        return cls(layers, sample_shape,
                   name=name or wf_info.get("name", "model"),
                   version=version, source=str(path))


def _read_package(path):
    """contents.json + decoded ``@NNNN`` npy members, dir or tar."""
    members = {}
    if os.path.isdir(path):
        with open(os.path.join(path, "contents.json"), "rb") as f:
            contents = json.loads(f.read())
        for fname in os.listdir(path):
            if fname.startswith("@") and fname.endswith(".npy"):
                members[fname[:-len(".npy")]] = numpy.load(
                    os.path.join(path, fname), allow_pickle=False)
    else:
        with tarfile.open(path, "r") as tar:
            contents = json.loads(tar.extractfile("contents.json").read())
            for info in tar.getmembers():
                if info.name.startswith("@") and \
                        info.name.endswith(".npy"):
                    members[info.name[:-len(".npy")]] = numpy.load(
                        io.BytesIO(tar.extractfile(info).read()),
                        allow_pickle=False)
    return contents, members


def _is_package(path):
    if os.path.isdir(path):
        return os.path.exists(os.path.join(path, "contents.json"))
    if str(path).endswith(".tar") and os.path.exists(path):
        try:
            with tarfile.open(path, "r") as tar:
                return "contents.json" in tar.getnames()
        except tarfile.TarError:
            return False
    return False


class ModelStore(Logger):
    """Named, versioned serveable models with pinning.

    ``load()`` auto-detects the artifact kind; versions count up per
    name. ``get(name)`` returns the pinned version if one is set, else
    the newest — the replica pool promotes whatever ``get`` returns, so
    pin-then-swap is the rollback procedure (``docs/SERVING.md``).
    """

    def __init__(self):
        super(ModelStore, self).__init__()
        self._lock = threading.Lock()
        self._models = {}   # name -> {version: ServeableModel}
        self._pins = {}     # name -> version

    def load(self, source, name=None, version=None):
        """Load an artifact and register it; returns the model.

        ``source`` may be an export package (dir / ``.tar`` holding
        ``contents.json``), a snapshot file or URI, or a snapshot
        *directory* (the newest snapshot inside is taken — the shape
        ``SnapshotterToFile`` leaves behind)."""
        path = str(source)
        if _is_package(path):
            model = ServeableModel.from_package(path, name=name)
        else:
            if os.path.isdir(path):
                from veles_tpu.snapshotter import latest_snapshot
                path = latest_snapshot(path)
            model = ServeableModel.from_snapshot(path, name=name)
        return self.add(model, version=version)

    def add(self, model, version=None):
        with self._lock:
            versions = self._models.setdefault(model.name, {})
            if version is None:
                version = max(versions, default=0) + 1
            model.version = int(version)
            versions[model.version] = model
        self.info("registered %s v%d (from %s)", model.name,
                  model.version, model.source)
        return model

    def get(self, name=None, version=None):
        with self._lock:
            if name is None:
                if len(self._models) != 1:
                    raise KeyError(
                        "store holds %d models — name one of %s" %
                        (len(self._models), sorted(self._models)))
                name = next(iter(self._models))
            versions = self._models.get(name)
            if not versions:
                raise KeyError("no model named %r" % name)
            if version is None:
                version = self._pins.get(name, max(versions))
            model = versions.get(int(version))
            if model is None:
                raise KeyError("no version %s of %r (have %s)" %
                               (version, name, sorted(versions)))
            return model

    def pin(self, name, version):
        """Pin ``get(name)`` to an exact version (rollback lever)."""
        with self._lock:
            versions = self._models.get(name) or {}
            if int(version) not in versions:
                raise KeyError("no version %s of %r (have %s)" %
                               (version, name, sorted(versions)))
            self._pins[name] = int(version)

    def unpin(self, name):
        with self._lock:
            self._pins.pop(name, None)

    def versions(self, name):
        with self._lock:
            return sorted(self._models.get(name, {}))

    def names(self):
        with self._lock:
            return sorted(self._models)
