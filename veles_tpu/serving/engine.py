"""The dynamic batcher: coalesce requests, one forward per batch.

Requests enter through :meth:`DynamicBatcher.submit` (one sample → one
:class:`concurrent.futures.Future`). Admission is a **bounded** queue:
when it is full, ``submit`` raises :class:`EngineOverloaded`
immediately — the frontend turns that into HTTP 503 + ``Retry-After``,
so overload sheds load instead of stacking unbounded blocked threads
(the failure mode the old one-request-one-dispatch path had).

The batcher thread collects up to ``max_batch_size`` samples or waits
at most ``batch_timeout_ms`` past the first sample of a batch — the
standard latency/throughput knob: a lone request pays at most the
window; a burst fills the batch instantly and never waits. Collected
batches go to the replica pool (least-loaded replica, padded to a warm
bucket) and results scatter back row-by-row to the waiting futures.
Dispatch is asynchronous: while replica A runs batch N, the batcher is
already collecting batch N+1 for replica B.
"""

import concurrent.futures
import queue
import threading
import time

import numpy

from veles_tpu.logger import Logger


class EngineOverloaded(Exception):
    """Admission queue full — retry later (HTTP 503)."""

    def __init__(self, message="serving queue is full", retry_after=1):
        super(EngineOverloaded, self).__init__(message)
        self.retry_after = int(retry_after)


class _Request(object):
    __slots__ = ("sample", "future", "enqueued_at")

    def __init__(self, sample):
        self.sample = sample
        self.future = concurrent.futures.Future()
        self.enqueued_at = time.time()


class DynamicBatcher(Logger):
    """Collect → pad → forward → scatter, against a replica pool."""

    def __init__(self, pool, max_batch_size=None, batch_timeout_ms=5.0,
                 max_queue=256, metrics=None):
        super(DynamicBatcher, self).__init__()
        self.pool = pool
        self.max_batch_size = int(max_batch_size or pool.max_batch_size)
        self.batch_timeout_s = float(batch_timeout_ms) / 1000.0
        self._queue = queue.Queue()
        # admission bounds TOTAL outstanding samples (waiting for the
        # batcher + dispatched to a replica but not yet scattered) —
        # bounding only the pre-batcher queue would let the unbounded
        # replica queues absorb arbitrary backlog and defeat the 503
        self.max_queue = int(max_queue)
        self._outstanding = 0
        self._outstanding_lock = threading.Lock()
        self.metrics = metrics
        if metrics is not None:
            metrics.attach_queue_depth(self.queue_depth)
            metrics.attach_replica_stats(pool.stats)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._batch_loop,
                                        daemon=True, name="batcher")
        self._thread.start()

    # -- request side ------------------------------------------------------

    def submit(self, sample):
        """One sample in, one Future out; EngineOverloaded when full."""
        sample = numpy.ascontiguousarray(sample, numpy.float32)
        expected = self.pool.model.sample_shape
        if tuple(sample.shape) != expected:
            try:
                sample = sample.reshape(expected)
            except ValueError:
                raise ValueError(
                    "sample shape %s does not match the model's %s" %
                    (tuple(sample.shape), expected))
        request = _Request(sample)
        if self._stop.is_set():
            raise EngineOverloaded("engine stopped", retry_after=5)
        with self._outstanding_lock:
            if self._outstanding >= self.max_queue:
                raise EngineOverloaded(retry_after=1)
            self._outstanding += 1
        self._queue.put(request)
        if self._stop.is_set():
            # stop() may have drained the queue between the check above
            # and the put — drain again so no request lands on a dead
            # queue with its future forever unresolved (each item is
            # popped exactly once, so racing the loop's drain is safe)
            self._drain_stopped()
        return request.future

    def _drain_stopped(self):
        while True:
            try:
                request = self._queue.get_nowait()
            except queue.Empty:
                break
            request.future.set_exception(
                EngineOverloaded("engine stopped", retry_after=5))
            self._settle(1)

    def _settle(self, n):
        with self._outstanding_lock:
            self._outstanding -= n

    def queue_depth(self):
        """Outstanding samples (admission-queue + in-replica)."""
        with self._outstanding_lock:
            return self._outstanding

    # -- batcher thread ----------------------------------------------------

    def _collect(self):
        """Block for the first sample, then fill the batch until the
        window closes or the batch is full — and while every replica
        is still busy, keep growing past the window (continuous
        batching): dispatching a fragment early would only queue it
        behind the running batch, whereas growing it matches the batch
        size to the service rate under load and keeps single-request
        latency at one window when the pool is idle."""
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return None
        batch = [first]
        deadline = time.time() + self.batch_timeout_s
        while len(batch) < self.max_batch_size:
            remaining = deadline - time.time()
            if remaining <= 0:
                if self.pool.any_idle() or self._stop.is_set():
                    break
                remaining = 0.001  # all replicas busy: keep growing
            try:
                batch.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                if remaining > 0.002 or self.pool.any_idle() \
                        or self._stop.is_set():
                    break
        return batch

    def _batch_loop(self):
        while not self._stop.is_set():
            requests = self._collect()
            if not requests:
                continue
            batch = numpy.stack([r.sample for r in requests])
            self.pool.submit(batch, self._scatter_cb(requests))
        # engine stopping: fail whatever is still queued
        self._drain_stopped()

    def _scatter_cb(self, requests):
        def scatter(result, bucket, error):
            self._settle(len(requests))
            if error is not None:
                for r in requests:
                    if not r.future.done():
                        r.future.set_exception(error)
                return
            if self.metrics is not None:
                self.metrics.record_batch(len(requests), bucket)
            for i, r in enumerate(requests):
                if not r.future.done():
                    r.future.set_result(
                        numpy.array(result[i], copy=True))
        return scatter

    # -- lifecycle ---------------------------------------------------------

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)
