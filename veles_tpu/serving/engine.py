"""The dynamic batcher: coalesce requests, one forward per batch.

Requests enter through :meth:`DynamicBatcher.submit` (one sample → one
:class:`concurrent.futures.Future`). Two new strata sit in front of
the batch queue (ISSUE 14):

* a **content-addressed result cache**
  (:class:`~veles_tpu.serving.cache.ResultCache`): a hit returns an
  already-resolved future before admission is even consulted — hot
  repeated inputs cost one dict lookup, zero accelerator time;
* **per-tenant QoS admission**
  (:class:`~veles_tpu.serving.admission.AdmissionController`):
  weighted-fair shares with QoS classes replace PR 3's single global
  outstanding cap, so an overloaded tenant sheds onto itself — the
  frontend turns :class:`EngineOverloaded` (or its per-tenant subclass
  ``TenantOverloaded``) into HTTP 503 + ``Retry-After`` computed from
  that tenant's own drain rate.

The batcher thread collects up to ``max_batch_size`` samples or waits
at most ``batch_timeout_ms`` past the first sample of a batch — the
standard latency/throughput knob: a lone request pays at most the
window; a burst fills the batch instantly and never waits. Collected
batches go to the replica pool (least-loaded replica, padded to a warm
bucket) and results scatter back row-by-row to the waiting futures —
and, on the way out, into the cache (epoch-fenced, so a result
computed against a swapped-out model version is dropped, not cached).
Dispatch is asynchronous: while replica A runs batch N, the batcher is
already collecting batch N+1 for replica B.
"""

import concurrent.futures
import queue
import threading
import time

import numpy

from veles_tpu.logger import Logger


class EngineOverloaded(Exception):
    """Admission queue full — retry later (HTTP 503)."""

    def __init__(self, message="serving queue is full", retry_after=1):
        super(EngineOverloaded, self).__init__(message)
        self.retry_after = int(retry_after)


class DeadlineExceeded(Exception):
    """The request's client deadline passed while it queued — shed at
    dequeue, before any compute (HTTP 504)."""


class _Request(object):
    __slots__ = ("sample", "future", "enqueued_at", "tenant",
                 "cache_key", "cache_token", "deadline")

    def __init__(self, sample, tenant=None, cache_key=None,
                 cache_token=None, deadline=None):
        self.sample = sample
        self.future = concurrent.futures.Future()
        self.enqueued_at = time.time()
        self.tenant = tenant
        self.cache_key = cache_key
        self.cache_token = cache_token
        #: absolute wall time (or None): past it, nobody is waiting
        #: for the answer any more
        self.deadline = deadline


class DynamicBatcher(Logger):
    """Cache → admit → collect → pad → forward → scatter."""

    def __init__(self, pool, max_batch_size=None, batch_timeout_ms=5.0,
                 max_queue=256, metrics=None, cache=None,
                 admission=None):
        super(DynamicBatcher, self).__init__()
        self.pool = pool
        self.max_batch_size = int(max_batch_size or pool.max_batch_size)
        self.batch_timeout_s = float(batch_timeout_ms) / 1000.0
        self._queue = queue.Queue()
        # admission bounds TOTAL outstanding samples (waiting for the
        # batcher + dispatched to a replica but not yet scattered) —
        # bounding only the pre-batcher queue would let the unbounded
        # replica queues absorb arbitrary backlog and defeat the 503.
        # The controller's default tenant owning 100% of the capacity
        # IS the old global cap; named tenants split it weighted-fair.
        self.max_queue = int(max_queue)
        if admission is None:
            from veles_tpu.serving.admission import AdmissionController
            admission = AdmissionController(capacity=self.max_queue)
        self.admission = admission
        self.cache = cache
        self.metrics = metrics
        if metrics is not None:
            metrics.attach_queue_depth(self.queue_depth)
            metrics.attach_replica_stats(pool.stats)
            if cache is not None:
                metrics.attach_cache_stats(cache.stats)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._batch_loop,
                                        daemon=True, name="batcher")
        self._thread.start()

    # -- request side ------------------------------------------------------

    def submit(self, sample, tenant=None, qos=None, deadline=None):
        """One sample in, one Future out; EngineOverloaded when the
        tenant's share (or the engine) is full. A cache hit resolves
        immediately — no admission, no batch, no forward.
        ``deadline`` (absolute wall time) marks the moment the caller
        stops waiting: a request still queued past it is shed at
        dequeue with :class:`DeadlineExceeded` instead of computed."""
        sample = numpy.ascontiguousarray(sample, numpy.float32)
        model = self.pool.model
        expected = model.sample_shape
        if tuple(sample.shape) != expected:
            try:
                sample = sample.reshape(expected)
            except ValueError:
                raise ValueError(
                    "sample shape %s does not match the model's %s" %
                    (tuple(sample.shape), expected))
        if self._stop.is_set():
            raise EngineOverloaded("engine stopped", retry_after=5)
        cache_key = cache_token = None
        if self.cache is not None:
            cache_key = self.cache.key_for(sample, model.name,
                                           model.version)
            hit = self.cache.get(cache_key)
            if hit is not None:
                future = concurrent.futures.Future()
                future.set_result(hit)       # read-only cached array
                if self.metrics is not None:
                    self.metrics.record_cache_hit()
                return future
            cache_token = self.cache.token()
        # raises on shed; returns the accounting bucket (an unknown
        # tenant past the cap aliases to "overflow" — settle must use
        # the same bucket or outstanding counts leak)
        tenant = self.admission.admit(tenant, qos=qos)
        request = _Request(sample, tenant=tenant, cache_key=cache_key,
                           cache_token=cache_token, deadline=deadline)
        self._queue.put(request)
        if self._stop.is_set():
            # stop() may have drained the queue between the check above
            # and the put — drain again so no request lands on a dead
            # queue with its future forever unresolved (each item is
            # popped exactly once, so racing the loop's drain is safe)
            self._drain_stopped()
        return request.future

    def _drain_stopped(self):
        while True:
            try:
                request = self._queue.get_nowait()
            except queue.Empty:
                break
            request.future.set_exception(
                EngineOverloaded("engine stopped", retry_after=5))
            self.admission.settle(request.tenant)

    def queue_depth(self):
        """Outstanding samples (admission-queue + in-replica)."""
        return self.admission.total_outstanding()

    # -- batcher thread ----------------------------------------------------

    def _collect(self):
        """Block for the first sample, then fill the batch until the
        window closes or the batch is full — and while every replica
        is still busy, keep growing past the window (continuous
        batching): dispatching a fragment early would only queue it
        behind the running batch, whereas growing it matches the batch
        size to the service rate under load and keeps single-request
        latency at one window when the pool is idle."""
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return None
        batch = [first]
        deadline = time.time() + self.batch_timeout_s
        while len(batch) < self.max_batch_size:
            remaining = deadline - time.time()
            if remaining <= 0:
                if self.pool.any_idle() or self._stop.is_set():
                    break
                remaining = 0.001  # all replicas busy: keep growing
            try:
                batch.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                if remaining > 0.002 or self.pool.any_idle() \
                        or self._stop.is_set():
                    break
        return batch

    def _shed_expired(self, requests):
        """Drop entries whose client deadline already passed — at
        dequeue, BEFORE any compute: a stalled queue degrades by
        shedding stale work, not by computing answers nobody is
        waiting for. Returns the still-live remainder."""
        now = time.time()
        live = []
        for r in requests:
            if r.deadline is not None and now > r.deadline:
                self.admission.settle(r.tenant)
                if not r.future.done():
                    r.future.set_exception(DeadlineExceeded(
                        "deadline passed %.0f ms ago while queued"
                        % ((now - r.deadline) * 1000.0)))
                if self.metrics is not None:
                    self.metrics.record_deadline_shed()
            else:
                live.append(r)
        return live

    def _batch_loop(self):
        while not self._stop.is_set():
            requests = self._collect()
            if not requests:
                continue
            requests = self._shed_expired(requests)
            if not requests:
                continue
            batch = numpy.stack([r.sample for r in requests])
            self.pool.submit(batch, self._scatter_cb(requests))
        # engine stopping: fail whatever is still queued
        self._drain_stopped()

    def _scatter_cb(self, requests):
        def scatter(result, bucket, error):
            for r in requests:
                self.admission.settle(r.tenant)
            if error is not None:
                for r in requests:
                    if not r.future.done():
                        r.future.set_exception(error)
                return
            if self.metrics is not None:
                self.metrics.record_batch(len(requests), bucket)
            for i, r in enumerate(requests):
                row = numpy.array(result[i], copy=True)
                if self.cache is not None and r.cache_key is not None:
                    # the same array is handed to the client AND
                    # cached: freezing it makes the share safe (the
                    # frontend only serializes it), and a cache hit
                    # later returns it without another copy —
                    # bit-identical by construction. Cache off keeps
                    # the per-caller copy writable, as before.
                    row.setflags(write=False)
                    self.cache.put(r.cache_key, row, r.cache_token)
                if not r.future.done():
                    r.future.set_result(row)
        return scatter

    # -- lifecycle ---------------------------------------------------------

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)
