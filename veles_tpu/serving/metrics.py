"""Serving metrics: QPS, queue depth, batch occupancy, latency tails.

One :class:`ServingMetrics` instance is shared by the batcher, the
replica pool and the HTTP frontend. Everything is lock-protected plain
Python — recording a sample is a deque append, far below the cost of
the forward pass it measures. ``snapshot()`` renders the JSON served at
``/metrics.json`` and pushed to the :mod:`~veles_tpu.web_status`
dashboard (schema unchanged since PR 3).

The reservoir + nearest-rank percentile machinery that used to live
here is now the process-wide telemetry core
(:mod:`veles_tpu.telemetry.registry`); this module imports it and
additionally mirrors every sample into the shared registry, so the
serving counters appear in the Prometheus text exposition at
``/metrics`` next to the training and coordinator series.

Percentiles come from a bounded reservoir of the most recent
``reservoir_size`` latencies (exact over that window, not an estimate
over all time — the window is what an operator watching a live service
wants). QPS is counted over a sliding ``qps_window`` seconds.
"""

import collections
import threading
import time

from veles_tpu.telemetry.registry import (Reservoir, get_registry,
                                          percentile)

__all__ = ["ServingMetrics", "percentile"]


class _EndpointStats(object):
    """Counters + latency reservoir for one endpoint."""

    def __init__(self, reservoir_size, qps_window):
        self.requests = 0
        self.responses = collections.Counter()  # status code -> count
        self.latencies_ms = Reservoir(reservoir_size)
        self.arrivals = collections.deque()     # timestamps, qps window
        self.qps_window = qps_window

    def record(self, status, latency_ms, now):
        self.requests += 1
        self.responses[int(status)] += 1
        if latency_ms is not None:
            self.latencies_ms.add(float(latency_ms))
        self.arrivals.append(now)
        horizon = now - self.qps_window
        while self.arrivals and self.arrivals[0] < horizon:
            self.arrivals.popleft()

    def snapshot(self, now):
        horizon = now - self.qps_window
        while self.arrivals and self.arrivals[0] < horizon:
            self.arrivals.popleft()
        lat = self.latencies_ms.sorted_values()
        return {
            "requests": self.requests,
            "responses": {str(k): v for k, v in
                          sorted(self.responses.items())},
            "qps": round(len(self.arrivals) / self.qps_window, 2),
            "p50_ms": round(percentile(lat, 50), 3),
            "p95_ms": round(percentile(lat, 95), 3),
            "p99_ms": round(percentile(lat, 99), 3),
        }


class ServingMetrics(object):
    """Shared, thread-safe metrics hub for one serving process."""

    def __init__(self, reservoir_size=4096, qps_window=10.0,
                 registry=None, model_label="default"):
        self._lock = threading.Lock()
        self._reservoir_size = reservoir_size
        self._qps_window = qps_window
        self._endpoints = {}
        self._rejected = 0          # admission-control 503s
        self._cached = 0            # requests answered from the cache
        self._deadline_shed = 0     # expired-in-queue drops (504)
        self._batches = 0
        self._batch_rows = 0
        self._batch_capacity = 0    # sum of bucket sizes actually run
        self._occupancy = collections.deque(maxlen=reservoir_size)
        self._queue_depth_fn = None
        self._replica_stats_fn = None
        self._cache_stats_fn = None
        self._started = time.time()
        self._model = {}
        self.model_label = str(model_label)
        # mirror into the process-wide registry (Prometheus /metrics)
        registry = registry or get_registry()
        self._m_requests = registry.counter(
            "veles_serving_requests_total", "Requests per endpoint",
            labels=("endpoint", "status"))
        self._m_latency = registry.histogram(
            "veles_serving_latency_ms", "End-to-end request latency",
            labels=("endpoint",), reservoir_size=reservoir_size)
        # engine-side families carry the model label: one ServingMetrics
        # per hosted model would otherwise merge its series with every
        # other model's (the endpoint-labeled families above are
        # already distinguished by their per-route paths)
        label = {"model": self.model_label}
        self._m_rejected = registry.counter(
            "veles_serving_rejected_total",
            "Requests shed by admission control (503)",
            labels=("model",)).labels(**label)
        self._m_batches = registry.counter(
            "veles_serving_batches_total", "Engine batches run",
            labels=("model",)).labels(**label)
        self._m_batch_rows = registry.counter(
            "veles_serving_batch_rows_total", "Real samples batched",
            labels=("model",)).labels(**label)
        self._m_occupancy = registry.histogram(
            "veles_serving_batch_occupancy",
            "Real rows / compiled bucket size per batch",
            labels=("model",),
            reservoir_size=reservoir_size).labels(**label)
        self._m_queue_depth = registry.gauge(
            "veles_serving_queue_depth",
            "Live admission-queue depth (refreshed on snapshot)",
            labels=("model",)).labels(model=self.model_label)
        self._m_deadline_shed = registry.counter(
            "veles_serving_deadline_shed_total",
            "Requests shed at dequeue because their client deadline "
            "had already passed (no compute spent)",
            labels=("model",)).labels(**label)

    # -- wiring ------------------------------------------------------------

    def attach_queue_depth(self, fn):
        """``fn() -> int``: live depth of the admission queue."""
        self._queue_depth_fn = fn

    def attach_replica_stats(self, fn):
        """``fn() -> list of per-replica dicts`` (see ReplicaPool)."""
        self._replica_stats_fn = fn

    def attach_cache_stats(self, fn):
        """``fn() -> dict`` (see :class:`ResultCache.stats`)."""
        self._cache_stats_fn = fn

    def record_cache_hit(self):
        """A request was answered from the result cache (no batch)."""
        with self._lock:
            self._cached += 1

    def record_deadline_shed(self):
        """A queued request expired before compute and was dropped."""
        with self._lock:
            self._deadline_shed += 1
        self._m_deadline_shed.inc()

    def set_model(self, name, version):
        with self._lock:
            self._model = {"name": name, "version": version}

    # -- recording ---------------------------------------------------------

    def record_request(self, endpoint, status, latency_ms=None):
        now = time.time()
        with self._lock:
            stats = self._endpoints.get(endpoint)
            if stats is None:
                stats = self._endpoints[endpoint] = _EndpointStats(
                    self._reservoir_size, self._qps_window)
            stats.record(status, latency_ms, now)
            if int(status) == 503:
                self._rejected += 1
        # registry mirrors outside our lock: it takes its own (only) one
        self._m_requests.labels(endpoint=endpoint,
                                status=str(int(status))).inc()
        if latency_ms is not None:
            self._m_latency.labels(endpoint=endpoint).observe(latency_ms)
        if int(status) == 503:
            self._m_rejected.inc()

    def record_batch(self, rows, bucket):
        """One engine batch ran: ``rows`` real samples padded to
        ``bucket``. Occupancy = rows / bucket — the fraction of the
        compiled batch that was real work."""
        occupancy = float(rows) / max(int(bucket), 1)
        with self._lock:
            self._batches += 1
            self._batch_rows += int(rows)
            self._batch_capacity += int(bucket)
            self._occupancy.append(occupancy)
        self._m_batches.inc()
        self._m_batch_rows.inc(int(rows))
        self._m_occupancy.observe(occupancy)

    # -- reading -----------------------------------------------------------

    def snapshot(self):
        now = time.time()
        with self._lock:
            occ = sorted(self._occupancy)
            per_endpoint = {name: stats.snapshot(now)
                            for name, stats in self._endpoints.items()}
            total_qps = round(sum(e["qps"] for e in per_endpoint.values()),
                              2)
            out = {
                "uptime_s": round(now - self._started, 1),
                "model": dict(self._model),
                "qps": total_qps,
                "rejected_total": self._rejected,
                "cached_total": self._cached,
                "deadline_shed_total": self._deadline_shed,
                "endpoints": per_endpoint,
                "batches": {
                    "count": self._batches,
                    "rows": self._batch_rows,
                    "mean_size": round(
                        self._batch_rows / max(self._batches, 1), 2),
                    "occupancy_mean": round(
                        sum(occ) / max(len(occ), 1), 3),
                    "occupancy_p50": round(percentile(occ, 50), 3),
                },
            }
        # callables outside the lock: they take their own locks
        out["queue_depth"] = (self._queue_depth_fn()
                              if self._queue_depth_fn is not None else 0)
        # mirror into the registry so alert rules (serving_queue_deep)
        # and the federated cluster view can see the depth — refreshed
        # by every snapshot (the status reporter ticks it every ~2 s)
        self._m_queue_depth.set(out["queue_depth"])
        if self._replica_stats_fn is not None:
            out["replicas"] = self._replica_stats_fn()
        if self._cache_stats_fn is not None:
            out["cache"] = self._cache_stats_fn()
        return out

    def dashboard_block(self):
        """The condensed block pushed to web_status ``/update`` and
        rendered on ``/status.html`` (QPS, queue depth, p95)."""
        snap = self.snapshot()
        lat = [e for e in snap["endpoints"].values()]
        p95 = max([e["p95_ms"] for e in lat], default=0.0)
        return {
            "qps": snap["qps"],
            "queue_depth": snap["queue_depth"],
            "p95_ms": p95,
            "rejected_total": snap["rejected_total"],
            "batch_mean_size": snap["batches"]["mean_size"],
            "model": snap["model"],
        }
