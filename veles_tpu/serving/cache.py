"""Content-addressed result cache in front of the dynamic batcher.

The cheapest inference is the one never run: serving traffic from
millions of users is heavily repeat-skewed (the same image thumbnail,
the same feature row, the same canned prompt), and the engine's
responses are deterministic per model version — the replica scatter
returns bit-identical rows for identical inputs regardless of which
bucket the batch padded to. So a hit can short-circuit the whole
admission → batch → forward → scatter path into one dict lookup.

**Key.** ``sha1(model name | version | dtype | shape | sample bytes)``
— the canonical (contiguous ``float32``, shape-normalized) input bytes
the engine would batch, plus the model identity. The compiled bucket
is deliberately NOT part of the key: the lookup happens *before*
admission (the point is to skip the batcher), and row results are
bucket-independent by construction (zero-pad rows never feed back into
real rows; ``tests/test_serving_elastic.py`` pins the bit-identity).

**Bounds.** LRU over both an entry count and a byte budget (key bytes
+ stored result ``nbytes``), plus a TTL — an entry older than
``ttl_s`` is a miss and is dropped on touch. Eviction is O(1) per
entry (``OrderedDict``).

**Invalidation.** ``invalidate()`` bumps an epoch and clears the
store atomically — the hot-swap/promotion hook. In-flight requests
that sampled the OLD model carry the epoch they were admitted under
(:meth:`token`); ``put`` discards any insert whose token is stale, so
a result computed by v1 can never be served after the pool promoted
to v2 (the swap-atomicity contract the frontend test hammers).

Telemetry: ``veles_serving_cache_{hits,misses,evictions,
stale_puts}_total{model}``, ``veles_serving_cache_bytes`` /
``_entries`` gauges, and a windowed ``veles_serving_cache_hit_ratio``
gauge (the series the ``serving_cache_collapse`` alert rule watches —
only published once the window holds enough lookups to mean
something, so an idle cache never fires it).
"""

import collections
import hashlib
import threading
import time

from veles_tpu.logger import Logger
from veles_tpu.telemetry.registry import get_registry

#: lookups the hit-ratio window must hold before the gauge publishes —
#: a ratio over three requests is noise, not a collapse signal
HIT_RATIO_MIN_WINDOW = 50


class ResultCache(Logger):
    """Bounded, TTL'd, epoch-invalidated LRU of per-sample results."""

    def __init__(self, max_bytes=64 << 20, max_entries=100000,
                 ttl_s=300.0, model="default", registry=None,
                 ratio_window=512):
        super(ResultCache, self).__init__()
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()  # key -> _Entry
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self.ttl_s = float(ttl_s)
        self.model = str(model)
        self._bytes = 0
        self._epoch = 0
        self._window = collections.deque(maxlen=int(ratio_window))
        self._window_hits = 0   # running count of 1s in `_window`
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        registry = registry or get_registry()
        label = {"model": self.model}
        self._m_hits = registry.counter(
            "veles_serving_cache_hits_total",
            "Result-cache hits (forward skipped)",
            labels=("model",)).labels(**label)
        self._m_misses = registry.counter(
            "veles_serving_cache_misses_total",
            "Result-cache misses", labels=("model",)).labels(**label)
        self._m_evictions = registry.counter(
            "veles_serving_cache_evictions_total",
            "Result-cache evictions (LRU/TTL/byte budget)",
            labels=("model",)).labels(**label)
        self._m_stale = registry.counter(
            "veles_serving_cache_stale_puts_total",
            "Inserts discarded because the model swapped mid-flight",
            labels=("model",)).labels(**label)
        self._g_bytes = registry.gauge(
            "veles_serving_cache_bytes", "Bytes resident in the cache",
            labels=("model",)).labels(**label)
        self._g_entries = registry.gauge(
            "veles_serving_cache_entries", "Entries resident",
            labels=("model",)).labels(**label)
        self._g_ratio = registry.gauge(
            "veles_serving_cache_hit_ratio",
            "Hit ratio over the recent lookup window",
            labels=("model",)).labels(**label)

    # -- keying ------------------------------------------------------------

    @staticmethod
    def key_for(sample, name, version):
        """Content address of one canonical (normalized) sample."""
        h = hashlib.sha1()
        h.update(("%s|%d|%s|%s|" % (name, version, sample.dtype,
                                    sample.shape)).encode())
        h.update(sample.tobytes())
        return h.digest()

    def token(self):
        """The epoch a request was admitted under; pass to :meth:`put`
        so a result computed against a swapped-out model is dropped."""
        with self._lock:
            return self._epoch

    # -- lookup / insert ---------------------------------------------------

    def get(self, key, now=None):
        """Result array for ``key`` or None (miss/expired)."""
        now = time.time() if now is None else now
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and now - entry.t <= self.ttl_s:
                self._entries.move_to_end(key)
                self.hits += 1
                self._record_lookup_locked(1)
                hit = entry.value
            else:
                if entry is not None:       # expired: drop on touch
                    self._evict_locked(key)
                self.misses += 1
                self._record_lookup_locked(0)
                hit = None
            self._publish_locked()
        (self._m_hits if hit is not None else self._m_misses).inc()
        return hit

    def put(self, key, value, token, now=None):
        """Insert (a copy is NOT taken — callers hand over ownership);
        silently dropped when ``token`` predates an invalidation."""
        now = time.time() if now is None else now
        size = len(key) + int(getattr(value, "nbytes", 64))
        with self._lock:
            if token != self._epoch:
                self._m_stale.inc()
                return False
            if size > self.max_bytes:
                return False                # bigger than the whole budget
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.size
            self._entries[key] = _Entry(value, now, size)
            self._bytes += size
            evicted = 0
            while (self._bytes > self.max_bytes or
                   len(self._entries) > self.max_entries):
                victim, entry = self._entries.popitem(last=False)
                self._bytes -= entry.size
                self.evictions += 1
                evicted += 1
            self._publish_locked()
        if evicted:
            self._m_evictions.inc(evicted)
        return True

    def _evict_locked(self, key):
        entry = self._entries.pop(key, None)
        if entry is not None:
            self._bytes -= entry.size
            self.evictions += 1
            self._m_evictions.inc()

    # -- invalidation ------------------------------------------------------

    def invalidate(self):
        """Atomically drop everything and fence in-flight inserts
        (hot swap / promotion hook). Returns entries dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self._epoch += 1
            self._publish_locked()
        if n:
            self.debug("cache %s invalidated: %d entries dropped",
                       self.model, n)
        return n

    # -- reading -----------------------------------------------------------

    def _record_lookup_locked(self, hit):
        """Window append with a running hit count — the ratio gauge
        publishes on every lookup, so summing the window there would
        be O(window) work inside the hot-path lock."""
        if len(self._window) == self._window.maxlen:
            self._window_hits -= self._window.popleft()
        self._window.append(hit)
        self._window_hits += hit

    def _publish_locked(self):
        self._g_bytes.set(self._bytes)
        self._g_entries.set(len(self._entries))
        if len(self._window) >= min(HIT_RATIO_MIN_WINDOW,
                                    self._window.maxlen):
            self._g_ratio.set(self._window_hits /
                              float(len(self._window)))

    def hit_ratio(self):
        """All-time hit ratio (stats/snapshot; the gauge is windowed)."""
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def stats(self):
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_ratio": round(self.hits /
                                   max(self.hits + self.misses, 1), 4),
                "epoch": self._epoch,
            }

    def __len__(self):
        with self._lock:
            return len(self._entries)


class _Entry(object):
    __slots__ = ("value", "t", "size")

    def __init__(self, value, t, size):
        self.value = value
        self.t = t
        self.size = size
