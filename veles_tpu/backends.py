"""Device abstraction: pluggable compute backends.

Re-designs ``veles/backends.py`` for the XLA world. The reference
dispatched between OpenCL/CUDA/numpy devices and rebound per-unit
``ocl_run``/``cuda_run``/``numpy_run`` methods; here the backends are

* ``tpu``   — JAX on TPU chips (the production path),
* ``cpu``   — JAX on host CPU (same code, same numerics tests),
* ``numpy`` — pure-numpy pseudo-device (no JAX at all; debugging and
  the loss-parity oracle),
* ``auto``  — first available of tpu > cpu > numpy
  (``veles/backends.py:405-422``).

``Device(backend=...)`` dispatches on the backend name through
:class:`BackendRegistry` like the reference (``backends.py:190-197``).
The OpenCL autotune database (BLOCK_SIZE/VECTOR_OPT per device,
``backends.py:672-731``) has no TPU analogue by design: XLA's
compilation cache plays that role; what survives is the *rating* notion
(``computing_power``) used for load balancing.
"""

import os
import threading

from veles_tpu.config import root
from veles_tpu.envknob import env_knob
from veles_tpu.logger import Logger
from veles_tpu.cmdline import CommandLineArgumentsRegistry


class BackendRegistry(CommandLineArgumentsRegistry):
    """Metaclass mapping backend names to Device classes."""

    backends = {}

    def __init__(cls, name, bases, namespace):
        super(BackendRegistry, cls).__init__(name, bases, namespace)
        backend = namespace.get("BACKEND")
        if backend:
            BackendRegistry.backends[backend] = cls


def resolve_backend(name=None):
    """Resolve a backend name, expanding ``auto`` by priority."""
    name = (name or env_knob("VELES_TPU_BACKEND") or
            root.common.engine.get("backend", "auto"))
    if name == "auto":
        for candidate in ("tpu", "cpu", "numpy"):
            if BackendRegistry.backends[candidate].available():
                return candidate
        raise RuntimeError("no backend available")
    return name


class Device(Logger, metaclass=BackendRegistry):
    """Base device; ``Device(backend="tpu")`` dispatches to a subclass."""

    BACKEND = None

    def __new__(cls, *args, **kwargs):
        if cls is not Device:
            return object.__new__(cls)
        backend = resolve_backend(kwargs.get("backend"))
        target = BackendRegistry.backends.get(backend)
        if target is None or target is Device:
            raise ValueError(
                "unknown backend %r; registered: %s" %
                (backend, sorted(BackendRegistry.backends)))
        return object.__new__(target)

    def __init__(self, **kwargs):
        kwargs.pop("backend", None)
        device_index = kwargs.pop("device_index", 0)
        super(Device, self).__init__(**kwargs)
        self.device_index = device_index

    # -- capabilities ------------------------------------------------------

    @property
    def backend_name(self):
        return self.BACKEND

    @property
    def exists(self):
        """True for real accelerators (numpy pseudo-device → False)."""
        return True

    @property
    def is_jax(self):
        return False

    def sync(self):
        """Block until all queued device work has completed."""

    def compute_dtype(self, dtype=None):
        import numpy
        return numpy.dtype(dtype or root.common.engine.get(
            "precision_type", "float32"))

    def thread_pool_attach(self):
        """Per-thread context hook (the CUDA push/pop analogue); no-op."""

    def thread_pool_detach(self):
        pass

    @classmethod
    def available(cls):
        return False

    # Devices appear in pickled workflows: store only identity.
    def __getstate__(self):
        return {"BACKEND": self.BACKEND, "device_index": self.device_index}

    def __setstate__(self, state):
        self.__init__(device_index=state.get("device_index", 0))

    @staticmethod
    def init_parser(parser):
        parser.add_argument(
            "-a", "--backend", default="auto",
            choices=sorted(BackendRegistry.backends) + ["auto"],
            help="computation backend")
        parser.add_argument(
            "-d", "--device", default="0",
            help="device index (for multi-chip hosts)")
        parser.add_argument(
            "--jax-coordinator", default=None, metavar="HOST:PORT",
            help="multi-host pod: jax.distributed coordinator address "
                 "(process 0's host); omit on single-host runs")
        parser.add_argument(
            "--jax-processes", type=int, default=None,
            help="multi-host pod: total process (host) count")
        parser.add_argument(
            "--jax-process-id", type=int, default=None,
            help="multi-host pod: this process's index")
        return parser

    def __repr__(self):
        return "<%s backend=%s>" % (type(self).__name__, self.BACKEND)


def veles_cache_dir(*parts):
    """``~/.veles_tpu/cache/<parts...>`` (or the configured cache
    root), created on demand — ONE home for every persistent cache:
    the XLA compile cache, the kernel-autotune database
    (:mod:`veles_tpu.ops.autotune`) and the generated-dataset cache
    (:mod:`veles_tpu.loader.dataset_cache`)."""
    base = root.common.dirs.get("cache", os.path.join(
        os.path.expanduser("~"), ".veles_tpu", "cache"))
    path = os.path.join(base, *parts)
    os.makedirs(path, exist_ok=True)
    return path


def _cache_namespace():
    """Per-platform/per-host cache subdirectory name.

    XLA:CPU persists AOT *executables*: a cache written under one CPU
    feature set reloads on a different host with a real SIGILL risk
    (the loader warns "could lead to execution errors"). Key the dir by
    platform + jax version + a fingerprint of the host's CPU flags so
    feature-mismatched AOT results are never shared (VERDICT r3 weak #5).
    """
    import hashlib
    import platform

    import jax
    parts = [jax.default_backend(), jax.__version__, platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 lists features under "flags", aarch64 under
                # "Features" — either way the sorted set is the identity
                # an AOT executable is only valid for
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split()[2:]))
                    parts.append(hashlib.sha256(
                        flags.encode()).hexdigest()[:12])
                    break
    except OSError:
        pass  # non-Linux: platform+version+arch keying still helps
    return "-".join(parts)


def _enable_persistent_compile_cache():
    """Point XLA's persistent compilation cache at the veles cache dir
    (the role of the reference's on-disk kernel binary cache,
    ``veles/accelerated_units.py:605-673``): first compile of a big
    model costs minutes, every later process pays ~nothing."""
    import jax
    if jax.config.jax_compilation_cache_dir:
        return  # user/installation already configured one
    import os
    try:
        cache_dir = veles_cache_dir("xla", _cache_namespace())
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        # also persist XLA-internal (autotune) caches where supported
        try:
            jax.config.update("jax_persistent_cache_enable_xla_caches",
                              "all")
        except Exception:
            pass
    except Exception:  # cache is an optimization, never a failure
        pass


class JaxDevice(Device):
    """Common behavior for JAX-backed devices (TPU and CPU)."""

    PLATFORM = None

    def __init__(self, **kwargs):
        super(JaxDevice, self).__init__(**kwargs)
        import jax
        self._jax_ = jax
        _enable_persistent_compile_cache()
        # LOCAL devices only: under multi-controller SPMD jax.devices()
        # lists every process's devices, and committing unit arrays to
        # another process's device makes them unreadable locally
        devices = [d for d in jax.local_devices()
                   if self.PLATFORM in (None, d.platform)]
        if not devices:
            raise RuntimeError("no %s devices visible to JAX" % self.PLATFORM)
        self.jax_devices = devices
        self.jax_device = devices[min(self.device_index, len(devices) - 1)]
        self.debug("using %s (%d %s device(s) visible)",
                   self.jax_device, len(devices), self.PLATFORM or "jax")

    @property
    def is_jax(self):
        return True

    def put(self, array):
        """Host → device memory (HBM on TPU)."""
        return self._jax_.device_put(array, self.jax_device)

    def get(self, array):
        """Device → host numpy (always a COPY).

        ``numpy.asarray`` of a CPU jax.Array is a zero-copy VIEW of
        the XLA buffer. The fused trainers donate their param buffers
        every segment, so any such view left in a unit's ``mem``
        between epochs dangles once XLA frees the donated storage —
        observed as heap-reuse garbage in weight reads and "double
        free or corruption" aborts at interpreter exit, dependent on
        allocator layout (the order-dependent eager-vs-fused test
        flake). A copy pins the bytes for as long as the host array
        lives, whatever the device buffer's fate.
        """
        import numpy
        return numpy.array(array)

    def sync(self):
        # effects_barrier waits for all dispatched computations; the
        # device_put fallback only orders transfers, kept as last resort
        barrier = getattr(self._jax_, "effects_barrier", None)
        if barrier is not None:
            barrier()
        else:  # pragma: no cover
            self._jax_.block_until_ready(
                self._jax_.device_put(0, self.jax_device))

    @property
    def memory_stats(self):
        try:
            return self.jax_device.memory_stats() or {}
        except Exception:
            return {}


class TPUDevice(JaxDevice):
    """JAX on TPU. One chip by default; meshes live in veles_tpu.parallel."""

    BACKEND = "tpu"
    PLATFORM = "tpu"

    @classmethod
    def available(cls):
        try:
            import jax
            return any(d.platform == "tpu" for d in jax.devices())
        except Exception:
            return False


class CPUDevice(JaxDevice):
    """JAX on host CPU: identical program, interpretable numerics."""

    BACKEND = "cpu"
    PLATFORM = "cpu"

    def __init__(self, **kwargs):
        # A child process (warm evaluator, spawned slave) inherits a
        # sitecustomize that pins the TPU-relay platform; the
        # JAX_PLATFORMS env var alone does not undo that, so an
        # explicitly-CPU device must flip the config BEFORE
        # jax.devices() runs — otherwise the child initializes (and
        # BLOCKS on) the relay while e.g. a benchmark holds the chip.
        import jax
        try:
            from jax._src import xla_bridge
            initialized = xla_bridge.backends_are_initialized()
        except Exception:
            initialized = False
        # Flip only when the PROCESS is declared CPU-only (the env var
        # every spawned evaluator/slave/test sets): a mixed process
        # that later wants Device(backend="tpu") must not have its
        # global platform config pinned by a passing cpu device.
        # Reading config.jax_platforms does NOT initialize backends
        # (calling jax.default_backend() here would — and block on a
        # busy relay).
        if (not initialized and
                env_knob("VELES_TPU_BACKEND") in ("cpu", "numpy")
                and (jax.config.jax_platforms or "") != "cpu"):
            jax.config.update("jax_platforms", "cpu")
        super(CPUDevice, self).__init__(**kwargs)

    @classmethod
    def available(cls):
        try:
            import jax
            return any(d.platform == "cpu" for d in jax.devices())
        except Exception:
            return False


class NumpyDevice(Device):
    """Pure-numpy pseudo-device (``veles/backends.py:918-948``)."""

    BACKEND = "numpy"

    @property
    def exists(self):
        return False

    @classmethod
    def available(cls):
        return True


_default_device = None
_default_lock = threading.Lock()


def default_device():
    """Process-wide lazily created device honoring config/env selection."""
    global _default_device
    with _default_lock:
        if _default_device is None:
            _default_device = Device(backend=None)
        return _default_device
