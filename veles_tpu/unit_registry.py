"""Registry of all Unit subclasses.

Re-designs ``veles/unit_registry.py:51-179``: a metaclass records every
Unit subclass so the CLI frontend, forge packaging and workflow
introspection can enumerate the available unit types; it also folds in
the command-line argument registry so any unit can contribute flags.
Each class gets a stable ``__id__`` UUID used by the export package
format (consumed by the native runner, cf. ``libVeles/src/unit_factory.cc``).
"""

import uuid

from veles_tpu.cmdline import CommandLineArgumentsRegistry

#: Namespace for deterministic unit UUIDs (so the same class name always
#: exports the same id — the native runner keys its factory on these).
_UNIT_NAMESPACE = uuid.UUID("6ba7b812-9dad-11d1-80b4-00c04fd430c8")


class UnitRegistry(CommandLineArgumentsRegistry):
    """Metaclass: every concrete Unit subclass lands in ``units``."""

    units = {}

    def __init__(cls, name, bases, namespace):
        super(UnitRegistry, cls).__init__(name, bases, namespace)
        if namespace.get("hide_from_registry", False):
            return
        if "__id__" not in namespace:
            cls.__id__ = str(uuid.uuid5(_UNIT_NAMESPACE, name))
        UnitRegistry.units[name] = cls

    @staticmethod
    def find(name):
        return UnitRegistry.units.get(name)

    @staticmethod
    def find_by_id(uid):
        for cls in UnitRegistry.units.values():
            if getattr(cls, "__id__", None) == uid:
                return cls
        return None


class MappedUnitRegistry(UnitRegistry):
    """Registry variant with an extra user-facing mapping key.

    Subclass hierarchies that need name→class lookup by a custom key
    (loaders, normalizers) set ``MAPPING`` on their classes; cf.
    ``veles/unit_registry.py:178``.
    """

    mapping = "base"
    base = object

    def __init__(cls, name, bases, namespace):
        super(MappedUnitRegistry, cls).__init__(name, bases, namespace)
        mapping = namespace.get("MAPPING")
        if mapping:
            registry = type(cls).mapped
            registry[mapping] = cls

    mapped = {}
