"""veles-tpu: a TPU-native deep-learning workflow platform.

A from-scratch re-design of the capabilities of Samsung VELES
(reference: /root/reference, surveyed in SURVEY.md) for TPU hardware:
the unit/workflow dataflow model survives as the model-description layer,
while execution lowers whole training steps into single XLA computations
(jax.jit / pjit over a device mesh), with Pallas kernels for the hot ops.

Public API mirrors the reference's importable launcher
(``veles/__init__.py:141-189``): ``veles_tpu.run(workflow_cls, config, ...)``.
"""

__version__ = "0.1.0"
__license__ = "Apache 2.0"

__root__ = __path__[0].rsplit("/", 1)[0]  # repo root

from veles_tpu.config import root  # noqa: E402,F401


def run(workflow_factory, config_update=None, snapshot=None, **kwargs):
    """Programmatic launcher: build and run a workflow standalone.

    Mirrors the reference's ``veles(workflow, config, **kwargs)`` entry
    (``veles/__init__.py:141-189``): apply config overrides, construct the
    workflow under a Launcher, initialize and run it, return the workflow.
    """
    try:
        from veles_tpu.launcher import Launcher
    except ImportError as exc:
        raise NotImplementedError(
            "the launcher subsystem is not available: %s" % exc)

    if config_update:
        root.update(config_update)
    launcher = Launcher(**{k: v for k, v in kwargs.items()
                           if k in Launcher.KWARGS})
    wf_kwargs = {k: v for k, v in kwargs.items() if k not in Launcher.KWARGS}
    if snapshot is not None:
        from veles_tpu.snapshotter import SnapshotterToFile
        workflow = SnapshotterToFile.import_(snapshot)
        workflow.workflow = launcher
    else:
        workflow = workflow_factory(launcher, **wf_kwargs)
    launcher.initialize()
    launcher.run()
    return workflow
