"""StandardWorkflow: declarative model construction.

The Znicz ``StandardWorkflow`` builds the canonical training topology
from a ``layers`` config list (the reference MNIST/CIFAR/AlexNet sample
configs are exactly such lists). Re-provided here: each descriptor is
``{"type": <name>, ...params}``; the builder wires

    repeater -> loader -> forwards... -> evaluator -> decision
    decision -> gd[k] ... gd[0] -> repeater   (gd gated off non-TRAIN)
    end_point <- decision (gate: decision.complete)

and pairs every parameterized forward with its vjp-based GD unit. The
result runs eagerly (unit graph) or fused (veles_tpu.train), identically.
"""

from veles_tpu.accelerated_units import AcceleratedWorkflow
from veles_tpu.nn.activation import ActivationUnit
from veles_tpu.nn.all2all import (All2All, All2AllRELU, All2AllSigmoid,
                                  All2AllSoftmax, All2AllStrictRELU,
                                  All2AllTanh)
from veles_tpu.nn.attention import MultiHeadAttentionForward
from veles_tpu.nn.moe import MoEForward
from veles_tpu.nn.conv import (Conv, ConvRELU, ConvSigmoid,
                               ConvStrictRELU, ConvTanh, Deconv)
from veles_tpu.nn.decision import DecisionGD, DecisionMSE
from veles_tpu.nn.dropout import DropoutBackward, DropoutForward
from veles_tpu.nn.evaluator import EvaluatorMSE, EvaluatorSoftmax
from veles_tpu.nn.gd import GradientDescentBase
from veles_tpu.nn.normalization import LRNormalizerForward
from veles_tpu.nn.pooling import (AvgPooling, Depooling, MaxAbsPooling,
                                  MaxPooling)
from veles_tpu.plumbing import Repeater

#: layer descriptor type -> forward unit class (Znicz MAPPING names)
LAYER_TYPES = {
    "all2all": All2All,
    "all2all_tanh": All2AllTanh,
    "all2all_relu": All2AllRELU,
    "all2all_str": All2AllStrictRELU,
    "all2all_sigmoid": All2AllSigmoid,
    "softmax": All2AllSoftmax,
    "conv": Conv,
    "conv_tanh": ConvTanh,
    "conv_relu": ConvRELU,
    "conv_str": ConvStrictRELU,
    "conv_sigmoid": ConvSigmoid,
    "deconv": Deconv,
    "max_pooling": MaxPooling,
    "maxabs_pooling": MaxAbsPooling,
    "avg_pooling": AvgPooling,
    "depooling": Depooling,
    "norm": LRNormalizerForward,
    "dropout": DropoutForward,
    "activation": ActivationUnit,
    "attention": MultiHeadAttentionForward,
    "moe": MoEForward,
}


class StandardWorkflow(AcceleratedWorkflow):
    """Canonical training workflow from a loader + layers config."""

    hide_from_registry = True

    def __init__(self, workflow=None, loader=None, layers=(),
                 loss="softmax", learning_rate=0.01, weights_decay=0.0,
                 momentum=0.0, lr_decay=1.0, solver="sgd",
                 max_epochs=None, fail_iterations=100,
                 mse_target_attr="minibatch_data", **kwargs):
        super(StandardWorkflow, self).__init__(workflow, **kwargs)
        if loader is None:
            raise ValueError("StandardWorkflow needs a loader factory")

        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.loader = loader(self) if callable(loader) else loader
        self.loader.link_from(self.repeater)

        # -- forward chain -------------------------------------------------
        self.forwards = []
        prev, prev_attr = self.loader, "minibatch_data"
        for i, descr in enumerate(layers):
            descr = dict(descr)
            ltype = descr.pop("type")
            cls = LAYER_TYPES.get(ltype)
            if cls is None:
                raise ValueError("unknown layer type %r (have %s)" %
                                 (ltype, sorted(LAYER_TYPES)))
            lr = descr.pop("learning_rate", learning_rate)
            wd = descr.pop("weights_decay", weights_decay)
            mom = descr.pop("momentum", momentum)
            descr.setdefault("name", "%s%d" % (ltype, i))
            fwd = cls(self, **descr)
            fwd._gd_hyper = dict(learning_rate=lr, weights_decay=wd,
                                 momentum=mom)
            fwd.link_from(prev)
            fwd.link_attrs(prev, ("input", prev_attr))
            self.forwards.append(fwd)
            prev, prev_attr = fwd, "output"

        # -- evaluator + decision ------------------------------------------
        head = self.forwards[-1]
        if loss == "softmax":
            self.evaluator = EvaluatorSoftmax(self, name="evaluator")
            self.evaluator.link_attrs(self.loader,
                                      ("labels", "minibatch_labels"))
            self.decision = DecisionGD(self, max_epochs=max_epochs,
                                       fail_iterations=fail_iterations,
                                       name="decision")
            self.decision.link_attrs(self.evaluator,
                                     ("minibatch_n_err", "n_err"))
        elif loss == "mse":
            self.evaluator = EvaluatorMSE(self, name="evaluator")
            self.evaluator.link_attrs(self.loader,
                                      ("target", mse_target_attr))
            self.evaluator.link_attrs(self.loader,
                                      ("indices", "minibatch_indices"))
            self.decision = DecisionMSE(self, max_epochs=max_epochs,
                                        fail_iterations=fail_iterations,
                                        name="decision")
            self.decision.link_attrs(self.evaluator,
                                     ("minibatch_mse", "mse_per_sample"))
        else:
            raise ValueError("loss must be softmax or mse")
        self.evaluator.link_from(head)
        self.evaluator.link_attrs(head, "output")
        self.evaluator.link_attrs(self.loader,
                                  ("batch_size", "minibatch_size"))
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "last_minibatch", "epoch_ended",
                                 "epoch_number", "class_lengths",
                                 "minibatch_size")

        # -- backward chain ------------------------------------------------
        self.gds = []
        err_src, err_attr = self.evaluator, "err_output"
        for fwd in reversed(self.forwards):
            gd_cls = (DropoutBackward if isinstance(fwd, DropoutForward)
                      else GradientDescentBase)
            hyper = getattr(fwd, "_gd_hyper", {})
            gd = gd_cls(self, forward=fwd,
                        learning_rate=hyper.get("learning_rate",
                                                learning_rate),
                        weights_decay=hyper.get("weights_decay",
                                                weights_decay),
                        momentum=hyper.get("momentum", momentum),
                        solver=solver,
                        solver_hp={"lr_decay": lr_decay}
                        if lr_decay != 1.0 else {},
                        need_err_input=fwd is not self.forwards[0],
                        name="gd_" + fwd.name)
            gd.link_from(self.gds[-1] if self.gds else self.decision)
            gd.link_attrs(err_src, ("err_output", err_attr))
            gd.gate_skip = self.decision.gd_skip
            self.gds.append(gd)
            err_src, err_attr = gd, "err_input"

        self.repeater.link_from(self.gds[-1] if self.gds
                                else self.decision)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete

    def set_testing(self, testing=True):
        """Inference mode: dropout off, no err_output generation, one
        forward-only epoch (then the decision stops the loop) — what
        ``--test`` and ensemble evaluation run."""
        self.evaluator.testing = testing
        self.decision.testing = testing
        if testing:
            # a snapshot-resumed workflow arrives with complete=True;
            # the test pass must re-open the loop for one epoch
            self.decision.complete.value = False
        for fwd in self.forwards:
            if isinstance(fwd, DropoutForward):
                fwd.testing = testing
