"""Array: numpy-semantics buffer with an HBM-resident device half.

Re-designs ``veles/memory.py:110-511``. The reference's Array pairs a
host numpy array with an OpenCL/CUDA buffer under an explicit coherence
protocol (``map_read``/``map_write``/``map_invalidate``/``unmap``).
That protocol survives here as the *host-sync discipline* over a
``jax.Array``:

* ``map_read()``  — make the host view valid (device → host if dirty);
* ``map_write()`` — host will read+write; device copy becomes stale;
* ``map_invalidate()`` — host will overwrite everything; skip the
  device→host copy (pure invalidation);
* ``unmap()``     — push host changes back to device (host → HBM).

Units written against this contract run unchanged on tpu/cpu/numpy.
The step compiler (veles_tpu.train) bypasses the protocol entirely by
keeping weights device-resident across steps — ``devmem`` hands it the
raw ``jax.Array`` and ``assign_devmem`` accepts the updated one back,
which is how donation/aliasing avoids host round-trips in the hot loop.

Global memory accounting mirrors the reference's Watcher
(``veles/memory.py:56-107``).
"""

import threading

import numpy

# coherence states
CLEAN = 0        # host == device
HOST_DIRTY = 1   # host modified; device stale
DEV_DIRTY = 2    # device modified; host stale


class Watcher(object):
    """Process-wide device-memory accounting (``memory.py:56-107``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.peak = 0
        self.count = 0

    def add(self, nbytes):
        with self._lock:
            self.total += nbytes
            self.count += 1
            self.peak = max(self.peak, self.total)

    def remove(self, nbytes):
        with self._lock:
            self.total -= nbytes
            self.count -= 1

    def report(self):
        return {"bytes_in_use": self.total, "peak_bytes": self.peak,
                "arrays": self.count}


watcher = Watcher()


class Array(object):
    """Host numpy array + lazily attached device buffer."""

    def __init__(self, data=None, shape=None, dtype=None):
        self._lock_ = threading.RLock()
        self.device = None
        self._devmem_ = None
        self._state_ = CLEAN
        self._accounted_ = 0
        if data is not None:
            self.mem = numpy.asarray(data, dtype=dtype)
        elif shape is not None:
            self.mem = numpy.zeros(shape, dtype=dtype or numpy.float32)
        else:
            self.mem = None

    # -- basic protocol ----------------------------------------------------

    @property
    def shape(self):
        return self.mem.shape if self.mem is not None else None

    @property
    def dtype(self):
        return self.mem.dtype if self.mem is not None else None

    @property
    def size(self):
        return self.mem.size if self.mem is not None else 0

    @property
    def nbytes(self):
        return self.mem.nbytes if self.mem is not None else 0

    def __bool__(self):
        return self.mem is not None and self.mem.size > 0

    def __len__(self):
        return len(self.mem) if self.mem is not None else 0

    def __getitem__(self, index):
        self.map_read()
        return self.mem[index]

    def __setitem__(self, index, value):
        """Element write. ``map_write`` syncs coherence state under
        the lock; the element store itself is not thread-safe by
        design — the lock protects the coherence protocol, not
        concurrent host mutation of the same buffer."""
        self.map_write()
        self.mem[index] = value

    def reset(self, new_mem=None):
        """Replace the host buffer; device copy is dropped."""
        with self._lock_:
            self._drop_devmem()
            self.mem = new_mem
            self._state_ = HOST_DIRTY if new_mem is not None else CLEAN

    # -- device attachment -------------------------------------------------

    def initialize(self, device):
        """Attach to a device; upload happens lazily on first devmem use."""
        with self._lock_:
            if device is not None and not device.exists:
                device = None  # numpy pseudo-device: host only
            if device is not self.device:
                self.map_read()      # preserve newest data on the host
                self._drop_devmem()  # release old device buffer+accounting
            self.device = device
            if self.mem is not None and device is not None:
                self._state_ = HOST_DIRTY
        return self

    @property
    def devmem(self):
        """The device-resident ``jax.Array`` (uploading if stale)."""
        with self._lock_:
            if self.device is None:
                return self.mem
            if self._devmem_ is None or self._state_ == HOST_DIRTY:
                self._upload()
            return self._devmem_

    def assign_devmem(self, new_devmem):
        """Accept an updated device array (output of a jitted step)."""
        with self._lock_:
            if self.device is None:
                # host-only array: the "device" result is a host value.
                # COPY, never view: ``new_devmem`` is typically a
                # jax.Array the next donating segment call will delete
                # under any zero-copy view (backends.JaxDevice.get has
                # the full story) — ``mem`` must own its bytes.
                self.mem = numpy.array(new_devmem)
                self._state_ = CLEAN
                return
            self._devmem_ = new_devmem
            self._state_ = DEV_DIRTY
            # account buffers that arrive device-side too (forward
            # outputs, err_inputs): without this the Watcher's
            # in-use/peak report only saw host-uploaded weights
            old = self._accounted_
            new = getattr(new_devmem, "nbytes", 0)
            if old != new:
                if old:
                    watcher.remove(old)
                if new:
                    watcher.add(new)
                self._accounted_ = new

    def _upload(self):
        """Host -> device copy + accounting. Caller holds
        ``self._lock_``."""
        old = self._accounted_
        self._devmem_ = self.device.put(self.mem)
        self._accounted_ = self.nbytes
        if old != self._accounted_:
            if old:
                watcher.remove(old)
            watcher.add(self._accounted_)
        self._state_ = CLEAN

    def _drop_devmem(self):
        """Release the device buffer + accounting. Caller holds
        ``self._lock_``."""
        if self._accounted_:
            watcher.remove(self._accounted_)
            self._accounted_ = 0
        self._devmem_ = None

    # -- coherence protocol ------------------------------------------------

    def map_read(self):
        """Make the host view valid."""
        with self._lock_:
            if self._state_ == DEV_DIRTY and self._devmem_ is not None:
                self.mem = self.device.get(self._devmem_)
                self._state_ = CLEAN
        return self.mem

    def map_write(self):
        """Host will read-modify-write: sync down, mark device stale."""
        with self._lock_:
            if self._state_ == DEV_DIRTY and self._devmem_ is not None:
                self.mem = self.device.get(self._devmem_)
            self._ensure_writable()
            self._state_ = HOST_DIRTY
        return self.mem

    def map_invalidate(self):
        """Host will overwrite entirely: skip the device→host copy."""
        with self._lock_:
            if self.mem is not None and not self.mem.flags.writeable:
                # caller overwrites everything: a fresh buffer suffices,
                # no need to copy bytes that are about to be clobbered
                self.mem = numpy.empty_like(self.mem)
            self._state_ = HOST_DIRTY
        return self.mem

    def _ensure_writable(self):
        """Caller holds ``self._lock_``."""
        # device→host views (numpy.asarray of a jax.Array) are read-only;
        # a host write mapping must always hand out a mutable buffer
        if self.mem is not None and not self.mem.flags.writeable:
            self.mem = numpy.array(self.mem)

    def release_devmem(self):
        """Drop the device buffer (syncing host first if device-dirty).

        The next ``devmem`` access re-uploads, so this is always safe;
        use when a staged copy supersedes this Array's device residence
        (e.g. dp row-sharding keeps only 1/N per device — holding the
        original full copy too would defeat the sharding's HBM saving).
        """
        with self._lock_:
            self.map_read()  # sync host if device-dirty (RLock reenters)
            self._drop_devmem()

    def unmap(self):
        """Flush host writes to the device (upload if dirty)."""
        with self._lock_:
            if self.device is not None and self._state_ == HOST_DIRTY \
                    and self.mem is not None:
                self._upload()

    # -- pickling: device half is transient -------------------------------

    def __getstate__(self):
        self.map_read()
        return {"mem": self.mem}

    def __setstate__(self, state):
        self._lock_ = threading.RLock()
        self.device = None
        self._devmem_ = None
        self._state_ = CLEAN
        self._accounted_ = 0
        self.mem = state["mem"]

    def __repr__(self):
        return "<Array %s %s on %s>" % (
            self.shape, self.dtype,
            self.device.backend_name if self.device else "host")


def assert_addr(a, b):
    """Assert two Arrays share the same host buffer (reference helper)."""
    if a.mem is not b.mem:
        raise ValueError("arrays do not share memory")


def roundup(value, multiple):
    """Round ``value`` up to a multiple (``veles/memory.py`` helper)."""
    remainder = value % multiple
    return value if remainder == 0 else value + multiple - remainder
