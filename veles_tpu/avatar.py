"""Avatar unit (re-designs ``veles/avatar.py:22``).

Mirrors a chosen set of attributes from a source unit each time it runs
— the mechanism the reference used to expose one workflow's state to
another across process boundaries. In-process it is an attribute
snapshot barrier: downstream units see a consistent copy taken at a
well-defined point of the graph, decoupled from the source's later
mutations.
"""

import numpy

from veles_tpu.memory import Array
from veles_tpu.units import Unit


class Avatar(Unit):
    """Copies ``attrs`` from ``source`` onto itself on every run."""

    def __init__(self, workflow, **kwargs):
        self.attrs = tuple(kwargs.pop("attrs", ()))
        source = kwargs.pop("source", None)
        super(Avatar, self).__init__(workflow, **kwargs)
        self.source = source
        self.demand("source")

    def clone(self):
        for attr in self.attrs:
            value = getattr(self.source, attr)
            if isinstance(value, Array):
                mirror = getattr(self, attr, None)
                if not isinstance(mirror, Array):
                    mirror = Array()
                    setattr(self, attr, mirror)
                mirror.reset(numpy.array(value.map_read(), copy=True))
            else:
                import copy
                setattr(self, attr, copy.deepcopy(value))

    def initialize(self, **kwargs):
        self.clone()

    def run(self):
        self.clone()
