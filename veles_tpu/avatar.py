"""Avatar units (re-design ``veles/avatar.py:22``).

Mirror a chosen set of attributes from a source unit — the mechanism
the reference used to expose one workflow's state to another across
process boundaries.

In-process, :class:`Avatar` is an attribute snapshot barrier:
downstream units see a consistent copy taken at a well-defined point
of the graph, decoupled from the source's later mutations.

Cross-process (VERDICT r3 missing #2), the same snapshot is SERVED:
:class:`AvatarServer` wraps an Avatar and answers pull requests over
the coordinator wire (``parallel/coordinator.py`` Protocol framing +
``parallel/wire.py`` restricted codec — numpy and primitives only, so
a hostile peer cannot smuggle code the way the reference's raw
network pickles could); :class:`RemoteAvatar` is the unit a CLIENT
workflow links into its graph — each run pulls the latest snapshot and
exposes the attributes locally, feeding one workflow from another
live one.
"""

import threading

import numpy

from veles_tpu.memory import Array
from veles_tpu.units import Unit


class Avatar(Unit):
    """Copies ``attrs`` from ``source`` onto itself on every run."""

    def __init__(self, workflow, **kwargs):
        self.attrs = tuple(kwargs.pop("attrs", ()))
        source = kwargs.pop("source", None)
        super(Avatar, self).__init__(workflow, **kwargs)
        self.source = source
        self.demand("source")

    def clone(self):
        for attr in self.attrs:
            value = getattr(self.source, attr)
            if isinstance(value, Array):
                mirror = getattr(self, attr, None)
                if not isinstance(mirror, Array):
                    mirror = Array()
                    setattr(self, attr, mirror)
                mirror.reset(numpy.array(value.map_read(), copy=True))
            else:
                import copy
                setattr(self, attr, copy.deepcopy(value))

    def initialize(self, **kwargs):
        self.clone()
        self._notify_cloned()

    def run(self):
        self.clone()
        self._notify_cloned()

    def _notify_cloned(self):
        # AvatarServer hooks here to re-publish after every snapshot.
        # Trailing underscore: the hook is a bound method of the LIVE
        # server (socket/locks) and must never ride the unit pickle
        # (Distributable.__getstate__ drops *_ attrs).
        hook = getattr(self, "on_cloned_", None)
        if hook is not None:
            hook()


class AvatarServer(object):
    """Serves an Avatar's snapshot to RemoteAvatar pullers.

    A tiny threaded accept loop (the coordinator's service pattern):
    each connection speaks Protocol frames; every ``{"req": "pull"}``
    is answered with ``{"rev": n, "attrs": {name: <wire blob>}}``.
    Snapshots are encoded once per Avatar.run() (``publish``), not per
    request, so many clients cost one encode.
    """

    def __init__(self, avatar, host="127.0.0.1", port=0):
        import socket

        if host not in ("127.0.0.1", "localhost", "::1"):
            # Avatar frames carry no auth (unlike the coordinator's
            # nonce+HMAC handshake) — anyone who can reach the port can
            # pull the model. Loopback is the supported deployment.
            import logging
            logging.getLogger("AvatarServer").warning(
                "binding to non-loopback %s: avatar pulls are "
                "UNAUTHENTICATED; tunnel over SSH or keep on loopback",
                host)
        self.avatar = avatar
        self._lock = threading.Lock()
        self._encoded = {}
        self._rev = 0
        self._done = threading.Event()
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        self.publish()
        # serve the snapshot published at link time even before run()
        avatar.on_cloned_ = self.publish
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True,
                                        name="avatar-server")
        self._thread.start()

    def publish(self):
        """Re-encode the avatar's current attribute values."""
        from veles_tpu.parallel import wire

        encoded = {}
        for attr in self.avatar.attrs:
            value = getattr(self.avatar, attr, None)
            if isinstance(value, Array):
                value = value.map_read()
            encoded[attr] = wire.encode(value)
        with self._lock:
            self._encoded = encoded
            self._rev += 1

    def _accept_loop(self):
        from veles_tpu.parallel.coordinator import Protocol

        while not self._done.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, daemon=True,
                             args=(Protocol(sock),)).start()

    def _serve(self, proto):
        try:
            while not self._done.is_set():
                msg = proto.recv()
                if not isinstance(msg, dict) or msg.get("req") != "pull":
                    proto.send({"error": "unknown request"})
                    continue
                # snapshot under the lock, SEND outside it: a client
                # that stops reading must stall only its own
                # connection, never publish() on the training thread
                with self._lock:
                    reply = {"rev": self._rev,
                             "attrs": dict(self._encoded)}
                proto.send(reply)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            proto.close()

    def stop(self):
        self._done.set()
        try:
            self._listener.close()
        except OSError:
            pass


class RemoteAvatar(Unit):
    """Client-side mirror: pulls a served Avatar's snapshot each run.

    ``address`` is the AvatarServer's (host, port). Mirrored ndarrays
    become :class:`Array` attributes (so downstream ``link_attrs``
    work exactly as against a local Avatar); scalars/containers are
    set as plain values. ``rev`` exposes the server's snapshot
    revision for staleness checks.
    """

    def __init__(self, workflow, **kwargs):
        self.attrs = tuple(kwargs.pop("attrs", ()))
        address = kwargs.pop("address", None)
        super(RemoteAvatar, self).__init__(workflow, **kwargs)
        self.address = address
        self.rev = -1
        self.demand("address")

    def init_unpickled(self):
        super(RemoteAvatar, self).init_unpickled()
        self._proto_ = None

    def _connect(self):
        import socket

        from veles_tpu.parallel.coordinator import Protocol

        if self._proto_ is None:
            self._proto_ = Protocol(
                socket.create_connection(tuple(self.address), timeout=30))
        return self._proto_

    def pull(self):
        from veles_tpu.parallel import wire

        proto = self._connect()
        proto.send({"req": "pull"})
        reply = proto.recv()
        if "error" in reply:
            raise RuntimeError("avatar pull failed: %s" % reply["error"])
        for attr, blob in reply["attrs"].items():
            if self.attrs and attr not in self.attrs:
                continue
            value = wire.decode(blob)  # restricted: numpy + primitives
            if isinstance(value, numpy.ndarray):
                mirror = getattr(self, attr, None)
                if not isinstance(mirror, Array):
                    mirror = Array()
                    setattr(self, attr, mirror)
                mirror.reset(value)
            else:
                setattr(self, attr, value)
        self.rev = reply["rev"]

    def initialize(self, **kwargs):
        self.pull()

    def run(self):
        self.pull()

    def close(self):
        if getattr(self, "_proto_", None) is not None:
            self._proto_.close()
            self._proto_ = None
