"""Snapshotter: whole-workflow checkpoint / resume.

Re-designs ``veles/snapshotter.py`` (SnapshotterBase :84, gating
:159-174, export :387-409, import_ :236-246) around the same design
choice the reference made: a checkpoint is the **entire workflow
object** — topology, weights, optimizer state, loader position, epoch
counters — plus the named PRNG registry, so a resumed run continues
*mid-epoch* with the identical random stream. The ``*_``-transient
attribute convention (:class:`veles_tpu.distributable.Pickleable`)
defines what is dropped and rebuilt; :class:`veles_tpu.memory.Array`
``map_read()``-s device memory in ``__getstate__`` so HBM-resident
weights land in the file.

Differences from the reference, deliberate on TPU:

* device buffers are never pickled — the restored workflow re-attaches
  to whatever device ``initialize(device=...)`` receives (a snapshot
  taken on TPU restores onto CPU and vice versa);
* the reference's ODBC target is realized as
  :class:`SnapshotterToDB` over stdlib sqlite3 (no ODBC driver ships
  here); restore accepts plain paths, ``http(s)://`` and
  ``sqlite://db#key`` URIs. File targets keep gz/bz2/xz compression
  and a ``_current`` symlink; a snapshot is a single self-describing
  pickle stream with a small header dict.
"""

import bz2
import gzip
import lzma
import os
import pickle
import tempfile
import time

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.mutable import Bool
from veles_tpu.result_provider import IResultProvider
from veles_tpu.units import Unit

#: extension -> opener; "" means raw
CODECS = {
    "": open,
    "gz": gzip.open,
    "bz2": bz2.open,
    "xz": lzma.open,
}


#: magic bytes -> opener (robust against misleading file names)
MAGIC = ((b"\x1f\x8b", gzip.open), (b"BZh", bz2.open),
         (b"\xfd7zXZ\x00", lzma.open))


def _maybe_decompress(payload):
    """Inverse of :func:`_compress` for in-memory payloads, sniffing
    the codec from magic bytes (shared by the http/sqlite restores)."""
    import io
    for magic, opener in MAGIC:
        if payload.startswith(magic):
            with opener(io.BytesIO(payload), "rb") as fin:
                return fin.read()
    return payload


def _compress(payload, compression):
    """Compress a snapshot payload in memory; validates the codec."""
    import io
    if not compression:
        return payload  # "" / None = uncompressed, always valid
    if compression not in CODECS:
        raise ValueError("unknown compression %r (have %s)" %
                         (compression, sorted(k for k in CODECS if k)))
    buf = io.BytesIO()
    with CODECS[compression](buf, "wb") as fout:
        fout.write(payload)
    return buf.getvalue()


def _open_for_read(path):
    """Open a snapshot for reading, sniffing the compression codec from
    the file's magic bytes (extension-independent, so symlinks or renamed
    files always load)."""
    with open(path, "rb") as probe:
        head = probe.read(8)
    for magic, opener in MAGIC:
        if head.startswith(magic):
            return opener(path, "rb")
    return open(path, "rb")


class SnapshotterBase(Unit, IResultProvider):
    """Gating + lifecycle; subclasses implement :meth:`export`.

    Gates (``veles/snapshotter.py:159-174``): a snapshot is taken every
    ``interval`` runs, but not more often than every ``time_interval``
    seconds, never on slaves, and not at all when
    ``root.common.disable.snapshotting`` is set.
    """

    hide_from_registry = True
    view_group = "SERVICE"

    def __init__(self, workflow, **kwargs):
        self.prefix = kwargs.pop("prefix", "wf")
        self.interval = kwargs.pop("interval", 1)
        self.time_interval = kwargs.pop("time_interval", 15.0)
        self.compression = kwargs.pop("compression", "gz")
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        self.suffix = ""
        self.destination = None
        self.time = 0.0
        self._skipped_counter = 0
        self.skip = Bool(False)

    def initialize(self, **kwargs):
        self.time = time.time()

    def run(self):
        if self.is_slave or root.common.disable.get("snapshotting", False):
            return
        if bool(self.skip):
            return
        self._skipped_counter += 1
        if self._skipped_counter < self.interval:
            return
        if time.time() - self.time < self.time_interval:
            return
        self._skipped_counter = 0
        self.export()
        self.time = time.time()

    def export(self):
        raise NotImplementedError

    def get_metric_values(self):
        """The newest snapshot path lands in the results JSON so meta-runs
        (ensemble test) can reload members (``model_workflow.py:115-124``)."""
        return {"Snapshot": self.destination} if self.destination else {}


class SnapshotterToFile(SnapshotterBase):
    """Pickle the owning workflow (+PRNG registry) to a file.

    File name: ``<directory>/<prefix>_<suffix>.<epoch>.pickle[.gz]``;
    a ``<prefix>_current.pickle`` symlink always points at the newest
    snapshot (``veles/snapshotter.py:387-409``).
    """

    def __init__(self, workflow, **kwargs):
        self.directory = kwargs.pop(
            "directory", root.common.dirs.get("snapshots", "."))
        super(SnapshotterToFile, self).__init__(workflow, **kwargs)

    def export(self):
        wf = self.workflow
        suffix = ("_" + self.suffix) if self.suffix else ""
        # ensemble members run the same workflow file concurrently from
        # the same CWD — each must write distinct snapshots (and distinct
        # "_current" pointers) or members overwrite each other
        # (``veles/ensemble/model_workflow.py`` separates them by log_id)
        member_tag = ""
        if root.common.ensemble.get("size", 0):
            member_tag = "_m%d" % root.common.ensemble.get("model_index", 0)
        suffix += member_tag
        path, nbytes = save_snapshot(
            wf, self.directory, tag=suffix, prefix=self.prefix,
            compression=self.compression, link_tag=member_tag)
        self.destination = path
        self.info("snapshotted to %s (%.1f MiB)", path,
                  nbytes / 1048576.0)

    @staticmethod
    def _wf_epoch(wf):
        return wf_epoch(wf)

    @staticmethod
    def import_(uri):
        """Load a snapshot from a file path or URI.

        The reference accepted file/http/odbc URIs for ``--snapshot``
        (``veles/__main__.py:539-589``); here: plain paths,
        ``http(s)://`` (fetched to memory) and ``sqlite://<db>#<key>``
        (the :class:`SnapshotterToDB` store). Returns the workflow with
        the PRNG registry restored so random streams continue where
        they left off."""
        if isinstance(uri, str) and uri.startswith(("http://",
                                                    "https://")):
            # unpickling a snapshot EXECUTES code from it: only restore
            # from servers you trust; over plain http a MITM gets that
            # execution too
            import logging
            log = logging.getLogger("Snapshotter")
            if uri.startswith("http://"):
                log.warning(
                    "restoring over plaintext http: a man-in-the-middle "
                    "can inject a pickle that executes arbitrary code — "
                    "use https or a local file (%s)", uri)
            else:
                log.warning("remote snapshot restore runs pickled code "
                            "from %s — make sure you trust this server",
                            uri)
            import urllib.request
            with urllib.request.urlopen(uri, timeout=60) as resp:
                payload = resp.read()
            return load_workflow(_maybe_decompress(payload))
        if isinstance(uri, str) and uri.startswith("sqlite://"):
            return SnapshotterToDB.import_(uri)
        return load_workflow(uri)


class SnapshotterToDB(SnapshotterBase):
    """Snapshot into a SQL database (the reference's ODBC target,
    ``veles/snapshotter.py:427-518``, realized over stdlib sqlite3 —
    no ODBC driver ships in this environment).

    URI form for restore: ``sqlite:///path/to/file.db#<key>`` where
    ``<key>`` defaults to the newest row.
    """

    def __init__(self, workflow, **kwargs):
        self.database = kwargs.pop("database", None)
        super(SnapshotterToDB, self).__init__(workflow, **kwargs)
        if not self.database:
            raise ValueError("SnapshotterToDB needs database=path.db")

    @staticmethod
    def _ensure_schema(conn):
        conn.execute(
            "CREATE TABLE IF NOT EXISTS snapshots ("
            " key TEXT PRIMARY KEY, checksum TEXT, epoch INTEGER,"
            " created REAL, payload BLOB)")

    def export(self):
        import sqlite3
        wf = self.workflow
        payload = _compress(dump_workflow(wf), self.compression)
        epoch = SnapshotterToFile._wf_epoch(wf)
        key = "%s_%s.%d" % (self.prefix, self.suffix or "snap", epoch)
        with sqlite3.connect(self.database) as conn:
            self._ensure_schema(conn)
            conn.execute(
                "INSERT OR REPLACE INTO snapshots VALUES (?, ?, ?, ?, ?)",
                (key, wf.checksum, epoch, time.time(),
                 sqlite3.Binary(payload)))
        self.destination = "sqlite://%s#%s" % (self.database, key)
        self.info("snapshotted to %s (%.1f MiB)", self.destination,
                  len(payload) / 1048576.0)

    @staticmethod
    def import_(uri):
        import sqlite3
        spec = uri[len("sqlite://"):]
        database, _, key = spec.partition("#")
        if not os.path.exists(database):
            # a restore must not create an empty DB on a typo'd path
            raise FileNotFoundError("no snapshot database: %s" % database)
        with sqlite3.connect(database) as conn:
            if key:
                row = conn.execute(
                    "SELECT payload FROM snapshots WHERE key = ?",
                    (key,)).fetchone()
            else:
                row = conn.execute(
                    "SELECT payload FROM snapshots "
                    "ORDER BY created DESC LIMIT 1").fetchone()
        if row is None:
            raise KeyError("no snapshot %r in %s" % (key, database))
        return load_workflow(_maybe_decompress(bytes(row[0])))


def wf_epoch(wf):
    """The epoch number a snapshot of ``wf`` is named after."""
    decision = getattr(wf, "decision", None)
    if decision is not None:
        return int(getattr(decision, "epoch_number", 0) or 0)
    loader = getattr(wf, "loader", None)
    if loader is not None:
        return int(getattr(loader, "epoch_number", 0) or 0)
    return 0


def save_snapshot(workflow, directory, tag="", prefix="wf",
                  compression="gz", link_tag="", payload=None):
    """Atomically write ONE snapshot file and refresh its ``_current``
    link; returns ``(path, payload_bytes)``. ``payload`` accepts a
    pre-computed :func:`dump_workflow` blob so a caller can serialize
    under its own locks and pay the compress+disk cost outside them.

    The shared writer behind :class:`SnapshotterToFile.export` and the
    master-side auto-snapshot hook (``launcher.py`` — a master's
    workflow graph never executes, so the Snapshotter *unit* cannot
    gate there; adding one would also change the topology checksum
    slaves handshake against). Staging goes through a HIDDEN
    ``.*.tmp`` file renamed into place, so a crash mid-write leaves
    only debris that :func:`latest_snapshot` skips."""
    import logging
    ext = ("." + compression) if compression else ""
    name = "%s%s.%d.pickle%s" % (prefix, tag, wf_epoch(workflow), ext)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, name)
    if payload is None:
        payload = dump_workflow(workflow)
    # write to a temp file then rename: a crash mid-write must not
    # destroy the previous snapshot of the same name
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".", suffix=".tmp")
    os.close(fd)
    try:
        with CODECS.get(compression, open)(tmp, "wb") as fout:
            fout.write(payload)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    link_path = os.path.join(
        directory, "%s%s_current.pickle%s" % (prefix, link_tag, ext))
    # the link_tag (ensemble member id) keeps concurrent members from
    # racing over a shared "_current" pointer
    try:
        if os.path.islink(link_path) or os.path.exists(link_path):
            os.unlink(link_path)
        os.symlink(os.path.basename(path), link_path)
    except OSError as exc:  # filesystems without symlinks
        logging.getLogger("Snapshotter").debug(
            "could not update %s: %s", link_path, exc)
    return path, len(payload)


def snapshot_candidates(directory, prefix=None):
    """Snapshot paths under a :class:`SnapshotterToFile` directory,
    best-first: the ``_current`` link's resolved target leads, the
    rest follow newest-mtime-first. In-progress staging files
    (hidden / ``*.tmp``) are never candidates — a restore racing an
    export must not pick a half-written artifact."""
    current = None
    rest = []
    for name in os.listdir(directory):
        if name.startswith(".") or name.endswith(".tmp"):
            continue
        if ".pickle" not in name:
            continue
        if prefix is not None and not name.startswith(prefix):
            continue
        path = os.path.join(directory, name)
        if "_current.pickle" in name:
            resolved = os.path.realpath(path)
            if os.path.exists(resolved):
                current = resolved
        else:
            rest.append(path)
    rest.sort(key=os.path.getmtime, reverse=True)
    if current is not None:
        rest = [p for p in rest if os.path.realpath(p) != current]
        return [current] + rest
    return rest


def latest_snapshot(directory, prefix=None):
    """Newest snapshot in a :class:`SnapshotterToFile` directory.

    Prefers the ``*_current.pickle*`` symlink the exporter maintains
    (resolved to its target); falls back to the most recently modified
    ``*.pickle*`` file on filesystems without symlinks; skips
    in-progress ``.tmp`` staging files. The serving model store
    (``veles_tpu/serving/model_store.py``) points at a snapshot
    directory and gets the freshest checkpoint."""
    candidates = snapshot_candidates(directory, prefix)
    if not candidates:
        raise FileNotFoundError(
            "no snapshots under %s%s" %
            (directory, " with prefix %r" % prefix if prefix else ""))
    return candidates[0]


def restore_latest(directory, prefix=None):
    """Load the newest LOADABLE snapshot: ``(workflow, path)``.

    A truncated or corrupt newest artifact (crash mid-copy, torn
    rsync, disk-full tail) falls back to the previous snapshot with a
    warning instead of crashing the resume — the auto-resume path
    (``Launcher(auto_resume=dir)``) must come back up with the best
    state that actually loads."""
    import logging
    log = logging.getLogger("Snapshotter")
    candidates = snapshot_candidates(directory, prefix)
    if not candidates:
        raise FileNotFoundError(
            "no snapshots under %s%s" %
            (directory, " with prefix %r" % prefix if prefix else ""))
    last_error = None
    for path in candidates:
        try:
            return load_workflow(path), path
        except Exception as e:  # noqa: BLE001 — any load failure
            last_error = e
            log.warning("snapshot %s is unloadable (%s: %s); falling "
                        "back to the previous artifact", path,
                        type(e).__name__, e)
    raise FileNotFoundError(
        "no loadable snapshot under %s (%d candidate(s), last error: "
        "%s)" % (directory, len(candidates), last_error))


class _LauncherCuttingPickler(pickle.Pickler):
    """Pickles a workflow WITHOUT its launcher: the launcher object is
    replaced by a persistent id (restored as ``None``). This replaces
    the old ``workflow._workflow = None``-around-dump dance, which
    mutated shared state — the master-side auto-snapshot hook
    (ISSUE 12) dumps while OTHER threads merge slave updates, and
    those threads' ``is_master`` checks must not go blind mid-dump."""

    def __init__(self, fileobj, launcher):
        super(_LauncherCuttingPickler, self).__init__(
            fileobj, protocol=pickle.HIGHEST_PROTOCOL)
        self._launcher = launcher

    def persistent_id(self, obj):
        if self._launcher is not None and obj is self._launcher:
            return "veles-launcher"
        return None


class _SnapshotUnpickler(pickle.Unpickler):
    def persistent_load(self, pid):
        return None  # the restored workflow re-binds to a new launcher


def dump_workflow(workflow):
    """Serialize a workflow to bytes (header + graph + PRNG registry).

    Thread-safe w.r.t. concurrent unit execution/merges: nothing on
    the workflow is mutated (see :class:`_LauncherCuttingPickler`)."""
    import io
    blob = {
        "format": 1,
        "checksum": workflow.checksum,
        "random": dict(prng._generators),
        "workflow": workflow,
    }
    buf = io.BytesIO()
    _LauncherCuttingPickler(buf, workflow._workflow).dump(blob)
    return buf.getvalue()


def _loads_snapshot(payload):
    import io
    return _SnapshotUnpickler(io.BytesIO(payload)).load()


def load_workflow(path_or_bytes):
    """Inverse of :func:`dump_workflow`; accepts a path or raw bytes."""
    if isinstance(path_or_bytes, bytes):
        blob = _loads_snapshot(path_or_bytes)
    else:
        with _open_for_read(path_or_bytes) as fin:
            blob = _loads_snapshot(fin.read())
    if not isinstance(blob, dict) or "workflow" not in blob:
        # a pickle that loads but is not a snapshot (somebody pointed
        # a restore at an arbitrary .pickle) must fail integrity here,
        # not explode attribute-by-attribute later
        raise pickle.UnpicklingError(
            "not a veles snapshot stream (missing workflow header)")
    for key, gen in blob.get("random", {}).items():
        prng._generators[key] = gen
    workflow = blob["workflow"]
    def mark(container):
        container._restored_from_snapshot_ = True
        for unit in container:
            unit._restored_from_snapshot_ = True
            if hasattr(unit, "__iter__"):  # nested workflows, any depth
                mark(unit)

    mark(workflow)
    if workflow.checksum != blob["checksum"]:
        workflow.warning("restored workflow checksum differs from the "
                         "one recorded at snapshot time")
    return workflow


def unit_sizes(workflow):
    """Per-unit pickled sizes — the reference's size diagnostics
    (``veles/snapshotter.py`` "took too much space" reporting).

    All units are put in stripped mode for the whole measurement:
    cross-unit references (``forward``, attribute links) then pickle as
    near-empty stubs, so each number reflects that unit's own payload.
    """
    sizes = {}
    units = list(workflow)
    for unit in units:
        unit.stripped_pickle = True
    try:
        for unit in units:
            try:
                sizes[unit.name] = len(pickle.dumps(
                    unit, protocol=pickle.HIGHEST_PROTOCOL))
            except Exception:
                sizes[unit.name] = -1
    finally:
        for unit in units:
            unit.stripped_pickle = False
    return sizes
