"""Snapshotter: whole-workflow checkpoint / resume.

Re-designs ``veles/snapshotter.py`` (SnapshotterBase :84, gating
:159-174, export :387-409, import_ :236-246) around the same design
choice the reference made: a checkpoint is the **entire workflow
object** — topology, weights, optimizer state, loader position, epoch
counters — plus the named PRNG registry, so a resumed run continues
*mid-epoch* with the identical random stream. The ``*_``-transient
attribute convention (:class:`veles_tpu.distributable.Pickleable`)
defines what is dropped and rebuilt; :class:`veles_tpu.memory.Array`
``map_read()``-s device memory in ``__getstate__`` so HBM-resident
weights land in the file.

Differences from the reference, deliberate on TPU:

* device buffers are never pickled — the restored workflow re-attaches
  to whatever device ``initialize(device=...)`` receives (a snapshot
  taken on TPU restores onto CPU and vice versa);
* the reference's ODBC target is realized as
  :class:`SnapshotterToDB` over stdlib sqlite3 (no ODBC driver ships
  here); restore accepts plain paths, ``http(s)://`` and
  ``sqlite://db#key`` URIs. File targets keep gz/bz2/xz compression
  and a ``_current`` symlink; a snapshot is a single self-describing
  pickle stream with a small header dict.
"""

import bz2
import gzip
import lzma
import os
import pickle
import tempfile
import time

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.mutable import Bool
from veles_tpu.result_provider import IResultProvider
from veles_tpu.units import Unit

#: extension -> opener; "" means raw
CODECS = {
    "": open,
    "gz": gzip.open,
    "bz2": bz2.open,
    "xz": lzma.open,
}


#: magic bytes -> opener (robust against misleading file names)
MAGIC = ((b"\x1f\x8b", gzip.open), (b"BZh", bz2.open),
         (b"\xfd7zXZ\x00", lzma.open))


def _maybe_decompress(payload):
    """Inverse of :func:`_compress` for in-memory payloads, sniffing
    the codec from magic bytes (shared by the http/sqlite restores)."""
    import io
    for magic, opener in MAGIC:
        if payload.startswith(magic):
            with opener(io.BytesIO(payload), "rb") as fin:
                return fin.read()
    return payload


def _compress(payload, compression):
    """Compress a snapshot payload in memory; validates the codec."""
    import io
    if not compression:
        return payload  # "" / None = uncompressed, always valid
    if compression not in CODECS:
        raise ValueError("unknown compression %r (have %s)" %
                         (compression, sorted(k for k in CODECS if k)))
    buf = io.BytesIO()
    with CODECS[compression](buf, "wb") as fout:
        fout.write(payload)
    return buf.getvalue()


def _atomic_write(directory, name, write_fn):
    """Write ``directory/name`` via a HIDDEN ``.*.tmp`` staging file +
    rename. ONE copy of the invariant every snapshot artifact relies
    on: a crash mid-write must neither destroy an existing artifact of
    the same name nor leave behind anything
    :func:`snapshot_candidates` could mistake for a candidate (it
    skips hidden / ``*.tmp`` names). ``write_fn(tmp_path)`` produces
    the staged content."""
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".", suffix=".tmp")
    os.close(fd)
    try:
        write_fn(tmp)
        os.replace(tmp, os.path.join(directory, name))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _open_for_read(path):
    """Open a snapshot for reading, sniffing the compression codec from
    the file's magic bytes (extension-independent, so symlinks or renamed
    files always load)."""
    with open(path, "rb") as probe:
        head = probe.read(8)
    for magic, opener in MAGIC:
        if head.startswith(magic):
            return opener(path, "rb")
    return open(path, "rb")


class SnapshotterBase(Unit, IResultProvider):
    """Gating + lifecycle; subclasses implement :meth:`export`.

    Gates (``veles/snapshotter.py:159-174``): a snapshot is taken every
    ``interval`` runs, but not more often than every ``time_interval``
    seconds, never on slaves, and not at all when
    ``root.common.disable.snapshotting`` is set.
    """

    hide_from_registry = True
    view_group = "SERVICE"

    def __init__(self, workflow, **kwargs):
        self.prefix = kwargs.pop("prefix", "wf")
        self.interval = kwargs.pop("interval", 1)
        self.time_interval = kwargs.pop("time_interval", 15.0)
        self.compression = kwargs.pop("compression", "gz")
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        self.suffix = ""
        self.destination = None
        self.time = 0.0
        self._skipped_counter = 0
        self.skip = Bool(False)

    def initialize(self, **kwargs):
        self.time = time.time()

    def run(self):
        if self.is_slave or root.common.disable.get("snapshotting", False):
            return
        if bool(self.skip):
            return
        self._skipped_counter += 1
        if self._skipped_counter < self.interval:
            return
        if time.time() - self.time < self.time_interval:
            return
        self._skipped_counter = 0
        self.export()
        self.time = time.time()

    def export(self):
        raise NotImplementedError

    def get_metric_values(self):
        """The newest snapshot path lands in the results JSON so meta-runs
        (ensemble test) can reload members (``model_workflow.py:115-124``)."""
        return {"Snapshot": self.destination} if self.destination else {}


class SnapshotterToFile(SnapshotterBase):
    """Pickle the owning workflow (+PRNG registry) to a file.

    File name: ``<directory>/<prefix>_<suffix>.<epoch>.pickle[.gz]``;
    a ``<prefix>_current.pickle`` symlink always points at the newest
    snapshot (``veles/snapshotter.py:387-409``).
    """

    def __init__(self, workflow, **kwargs):
        self.directory = kwargs.pop(
            "directory", root.common.dirs.get("snapshots", "."))
        super(SnapshotterToFile, self).__init__(workflow, **kwargs)

    def export(self):
        wf = self.workflow
        suffix = ("_" + self.suffix) if self.suffix else ""
        # ensemble members run the same workflow file concurrently from
        # the same CWD — each must write distinct snapshots (and distinct
        # "_current" pointers) or members overwrite each other
        # (``veles/ensemble/model_workflow.py`` separates them by log_id)
        member_tag = ""
        if root.common.ensemble.get("size", 0):
            member_tag = "_m%d" % root.common.ensemble.get("model_index", 0)
        suffix += member_tag
        path, nbytes = save_snapshot(
            wf, self.directory, tag=suffix, prefix=self.prefix,
            compression=self.compression, link_tag=member_tag)
        self.destination = path
        self.info("snapshotted to %s (%.1f MiB)", path,
                  nbytes / 1048576.0)

    @staticmethod
    def _wf_epoch(wf):
        return wf_epoch(wf)

    @staticmethod
    def import_(uri):
        """Load a snapshot from a file path or URI.

        The reference accepted file/http/odbc URIs for ``--snapshot``
        (``veles/__main__.py:539-589``); here: plain paths,
        ``http(s)://`` (fetched to memory) and ``sqlite://<db>#<key>``
        (the :class:`SnapshotterToDB` store). Returns the workflow with
        the PRNG registry restored so random streams continue where
        they left off."""
        if isinstance(uri, str) and uri.startswith(("http://",
                                                    "https://")):
            # unpickling a snapshot EXECUTES code from it: only restore
            # from servers you trust; over plain http a MITM gets that
            # execution too
            import logging
            log = logging.getLogger("Snapshotter")
            if uri.startswith("http://"):
                log.warning(
                    "restoring over plaintext http: a man-in-the-middle "
                    "can inject a pickle that executes arbitrary code — "
                    "use https or a local file (%s)", uri)
            else:
                log.warning("remote snapshot restore runs pickled code "
                            "from %s — make sure you trust this server",
                            uri)
            import urllib.request
            with urllib.request.urlopen(uri, timeout=60) as resp:
                payload = resp.read()
            return load_workflow(_maybe_decompress(payload))
        if isinstance(uri, str) and uri.startswith("sqlite://"):
            return SnapshotterToDB.import_(uri)
        return load_workflow(uri)


class SnapshotterToDB(SnapshotterBase):
    """Snapshot into a SQL database (the reference's ODBC target,
    ``veles/snapshotter.py:427-518``, realized over stdlib sqlite3 —
    no ODBC driver ships in this environment).

    URI form for restore: ``sqlite:///path/to/file.db#<key>`` where
    ``<key>`` defaults to the newest row.
    """

    def __init__(self, workflow, **kwargs):
        self.database = kwargs.pop("database", None)
        super(SnapshotterToDB, self).__init__(workflow, **kwargs)
        if not self.database:
            raise ValueError("SnapshotterToDB needs database=path.db")

    @staticmethod
    def _ensure_schema(conn):
        conn.execute(
            "CREATE TABLE IF NOT EXISTS snapshots ("
            " key TEXT PRIMARY KEY, checksum TEXT, epoch INTEGER,"
            " created REAL, payload BLOB)")

    def export(self):
        import sqlite3
        wf = self.workflow
        payload = _compress(dump_workflow(wf), self.compression)
        epoch = SnapshotterToFile._wf_epoch(wf)
        key = "%s_%s.%d" % (self.prefix, self.suffix or "snap", epoch)
        with sqlite3.connect(self.database) as conn:
            self._ensure_schema(conn)
            conn.execute(
                "INSERT OR REPLACE INTO snapshots VALUES (?, ?, ?, ?, ?)",
                (key, wf.checksum, epoch, time.time(),
                 sqlite3.Binary(payload)))
        self.destination = "sqlite://%s#%s" % (self.database, key)
        self.info("snapshotted to %s (%.1f MiB)", self.destination,
                  len(payload) / 1048576.0)

    @staticmethod
    def import_(uri):
        import sqlite3
        spec = uri[len("sqlite://"):]
        database, _, key = spec.partition("#")
        if not os.path.exists(database):
            # a restore must not create an empty DB on a typo'd path
            raise FileNotFoundError("no snapshot database: %s" % database)
        with sqlite3.connect(database) as conn:
            if key:
                row = conn.execute(
                    "SELECT payload FROM snapshots WHERE key = ?",
                    (key,)).fetchone()
            else:
                row = conn.execute(
                    "SELECT payload FROM snapshots "
                    "ORDER BY created DESC LIMIT 1").fetchone()
        if row is None:
            raise KeyError("no snapshot %r in %s" % (key, database))
        return load_workflow(_maybe_decompress(bytes(row[0])))


def wf_epoch(wf):
    """The epoch number a snapshot of ``wf`` is named after."""
    decision = getattr(wf, "decision", None)
    if decision is not None:
        return int(getattr(decision, "epoch_number", 0) or 0)
    loader = getattr(wf, "loader", None)
    if loader is not None:
        return int(getattr(loader, "epoch_number", 0) or 0)
    return 0


def save_snapshot(workflow, directory, tag="", prefix="wf",
                  compression="gz", link_tag="", payload=None):
    """Atomically write ONE snapshot file and refresh its ``_current``
    link; returns ``(path, payload_bytes)``. ``payload`` accepts a
    pre-computed :func:`dump_workflow` blob so a caller can serialize
    under its own locks and pay the compress+disk cost outside them.

    The shared writer behind :class:`SnapshotterToFile.export` and the
    master-side auto-snapshot hook (``launcher.py`` — a master's
    workflow graph never executes, so the Snapshotter *unit* cannot
    gate there; adding one would also change the topology checksum
    slaves handshake against). Staging goes through a HIDDEN
    ``.*.tmp`` file renamed into place, so a crash mid-write leaves
    only debris that :func:`latest_snapshot` skips."""
    import logging
    ext = ("." + compression) if compression else ""
    name = "%s%s.%d.pickle%s" % (prefix, tag, wf_epoch(workflow), ext)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, name)
    if payload is None:
        payload = dump_workflow(workflow)

    def stage(tmp):
        with CODECS.get(compression, open)(tmp, "wb") as fout:
            fout.write(payload)

    _atomic_write(directory, name, stage)
    link_path = os.path.join(
        directory, "%s%s_current.pickle%s" % (prefix, link_tag, ext))
    # the link_tag (ensemble member id) keeps concurrent members from
    # racing over a shared "_current" pointer
    try:
        if os.path.islink(link_path) or os.path.exists(link_path):
            os.unlink(link_path)
        os.symlink(os.path.basename(path), link_path)
    except OSError as exc:  # filesystems without symlinks
        logging.getLogger("Snapshotter").debug(
            "could not update %s: %s", link_path, exc)
    return path, len(payload)


#: suffix of a sharded checkpoint GENERATION directory (ISSUE 13):
#: per-process ``part<k>.pickle[.gz]`` shard files + a ``MANIFEST.json``
#: written last by process 0 — the manifest doubles as the completeness
#: marker, so a generation torn by a mid-save death is never a restore
#: candidate
SHARDED_SUFFIX = ".shards"
MANIFEST_NAME = "MANIFEST.json"


def _candidate_mtime(path):
    """Sort key for candidates: a generation directory ages by its
    manifest (the last artifact written), not the dir inode."""
    if os.path.isdir(path):
        manifest = os.path.join(path, MANIFEST_NAME)
        if os.path.exists(manifest):
            return os.path.getmtime(manifest)
    return os.path.getmtime(path)


def snapshot_candidates(directory, prefix=None):
    """Snapshot paths under a :class:`SnapshotterToFile` directory,
    best-first: the ``_current`` link's resolved target leads, the
    rest follow newest-mtime-first. Candidates are single snapshot
    files AND sharded-generation directories (``*.shards`` with a
    manifest). In-progress staging files (hidden / ``*.tmp``) and
    manifest-less generation dirs are never candidates — a restore
    racing an export (or surviving a mid-save death) must not pick a
    half-written artifact."""
    current = None
    rest = []
    for name in os.listdir(directory):
        if name.startswith(".") or name.endswith(".tmp"):
            continue
        path = os.path.join(directory, name)
        if prefix is not None and not name.startswith(prefix):
            continue
        if "_current.pickle" in name:
            # may resolve to a single file OR a sharded generation
            # directory (isdir follows symlinks, so this check must
            # come first or a dir-pointing link gets misclassified)
            resolved = os.path.realpath(path)
            if os.path.isdir(resolved) and not os.path.exists(
                    os.path.join(resolved, MANIFEST_NAME)):
                continue  # link points at a torn generation
            if os.path.exists(resolved):
                current = resolved
            continue
        if os.path.isdir(path):
            if not name.endswith(SHARDED_SUFFIX):
                continue
            if not os.path.exists(os.path.join(path, MANIFEST_NAME)):
                continue  # torn generation: process 0 never finished
            rest.append(path)
            continue
        if ".pickle" not in name:
            continue
        rest.append(path)
    rest.sort(key=_candidate_mtime, reverse=True)
    if current is not None:
        rest = [p for p in rest if os.path.realpath(p) != current]
        return [current] + rest
    return rest


def prune_sharded_generations(directory, keep, prefix="wf"):
    """Keep-last-``keep`` retention for sharded generation dirs
    (mirrors ``ModelStore``'s keep-last-K semantics at the checkpoint
    tier). Only COMPLETE generations (manifest present) are ever
    candidates — a torn dir is a mid-save in progress, not garbage —
    and the newest ``keep`` survive, so the restore point and the
    generation being cut are never touched. Targets of any
    ``*_current.pickle*`` link are protected regardless of age.
    Returns the paths removed."""
    import shutil
    keep = int(keep)
    if keep < 1:
        raise ValueError("keep must be >= 1 (got %d)" % keep)
    protected = set()
    generations = []
    for name in os.listdir(directory):
        path = os.path.join(directory, name)
        if "_current.pickle" in name:
            protected.add(os.path.realpath(path))
            continue
        if name.startswith(".") or name.endswith(".tmp"):
            continue
        if not name.endswith(SHARDED_SUFFIX) or not os.path.isdir(path):
            continue
        if prefix is not None and not name.startswith(prefix):
            continue
        if not os.path.exists(os.path.join(path, MANIFEST_NAME)):
            continue   # torn or in-flight: never retention's business
        generations.append(path)
    generations.sort(key=_candidate_mtime, reverse=True)
    removed = []
    for path in generations[keep:]:
        if os.path.realpath(path) in protected:
            continue
        try:
            shutil.rmtree(path)
        except OSError:
            continue   # racing another pruner / a late reader: skip
        removed.append(path)
    return removed


def latest_snapshot(directory, prefix=None):
    """Newest snapshot in a :class:`SnapshotterToFile` directory.

    Prefers the ``*_current.pickle*`` symlink the exporter maintains
    (resolved to its target); falls back to the most recently modified
    ``*.pickle*`` file on filesystems without symlinks; skips
    in-progress ``.tmp`` staging files. The serving model store
    (``veles_tpu/serving/model_store.py``) points at a snapshot
    directory and gets the freshest checkpoint."""
    candidates = snapshot_candidates(directory, prefix)
    if not candidates:
        raise FileNotFoundError(
            "no snapshots under %s%s" %
            (directory, " with prefix %r" % prefix if prefix else ""))
    return candidates[0]


def restore_latest(directory, prefix=None):
    """Load the newest LOADABLE snapshot: ``(workflow, path)``.

    A truncated or corrupt newest artifact (crash mid-copy, torn
    rsync, disk-full tail) falls back to the previous snapshot with a
    warning instead of crashing the resume — the auto-resume path
    (``Launcher(auto_resume=dir)``) must come back up with the best
    state that actually loads."""
    import logging
    log = logging.getLogger("Snapshotter")
    candidates = snapshot_candidates(directory, prefix)
    if not candidates:
        raise FileNotFoundError(
            "no snapshots under %s%s" %
            (directory, " with prefix %r" % prefix if prefix else ""))
    last_error = None
    for path in candidates:
        try:
            return load_workflow(path), path
        except Exception as e:  # noqa: BLE001 — any load failure
            last_error = e
            log.warning("snapshot %s is unloadable (%s: %s); falling "
                        "back to the previous artifact", path,
                        type(e).__name__, e)
    raise FileNotFoundError(
        "no loadable snapshot under %s (%d candidate(s), last error: "
        "%s)" % (directory, len(candidates), last_error))


class _LauncherCuttingPickler(pickle.Pickler):
    """Pickles a workflow WITHOUT its launcher: the launcher object is
    replaced by a persistent id (restored as ``None``). This replaces
    the old ``workflow._workflow = None``-around-dump dance, which
    mutated shared state — the master-side auto-snapshot hook
    (ISSUE 12) dumps while OTHER threads merge slave updates, and
    those threads' ``is_master`` checks must not go blind mid-dump."""

    def __init__(self, fileobj, launcher):
        super(_LauncherCuttingPickler, self).__init__(
            fileobj, protocol=pickle.HIGHEST_PROTOCOL)
        self._launcher = launcher

    def persistent_id(self, obj):
        if self._launcher is not None and obj is self._launcher:
            return "veles-launcher"
        return None


class _SnapshotUnpickler(pickle.Unpickler):
    def persistent_load(self, pid):
        return None  # the restored workflow re-binds to a new launcher


def dump_workflow(workflow):
    """Serialize a workflow to bytes (header + graph + PRNG registry).

    Thread-safe w.r.t. concurrent unit execution/merges: nothing on
    the workflow is mutated (see :class:`_LauncherCuttingPickler`)."""
    import io
    blob = {
        "format": 1,
        "checksum": workflow.checksum,
        "random": dict(prng._generators),
        "workflow": workflow,
    }
    buf = io.BytesIO()
    _LauncherCuttingPickler(buf, workflow._workflow).dump(blob)
    return buf.getvalue()


def _loads_snapshot(payload):
    import io
    return _SnapshotUnpickler(io.BytesIO(payload)).load()


def load_workflow(path_or_bytes):
    """Inverse of :func:`dump_workflow`; accepts a path or raw bytes.

    A path naming a sharded-generation DIRECTORY (ISSUE 13) loads
    through :func:`load_sharded_generation`: the workflow structure
    from part 0 plus every param/optimizer leaf re-assembled from the
    per-process shard files — so every existing restore surface
    (``restore_latest``, ``SnapshotterToFile.import_``, the serving
    model store) handles sharded checkpoints transparently."""
    if isinstance(path_or_bytes, str) and os.path.isdir(path_or_bytes):
        return load_sharded_generation(path_or_bytes)
    if isinstance(path_or_bytes, bytes):
        blob = _loads_snapshot(path_or_bytes)
    else:
        with _open_for_read(path_or_bytes) as fin:
            blob = _loads_snapshot(fin.read())
    if not isinstance(blob, dict) or "workflow" not in blob:
        # a pickle that loads but is not a snapshot (somebody pointed
        # a restore at an arbitrary .pickle) must fail integrity here,
        # not explode attribute-by-attribute later
        raise pickle.UnpicklingError(
            "not a veles snapshot stream (missing workflow header)")
    for key, gen in blob.get("random", {}).items():
        prng._generators[key] = gen
    workflow = blob["workflow"]
    def mark(container):
        container._restored_from_snapshot_ = True
        for unit in container:
            unit._restored_from_snapshot_ = True
            if hasattr(unit, "__iter__"):  # nested workflows, any depth
                mark(unit)

    mark(workflow)
    if workflow.checksum != blob["checksum"]:
        workflow.warning("restored workflow checksum differs from the "
                         "one recorded at snapshot time")
    return workflow


# -- sharded (multi-controller) checkpoints — ISSUE 13 -----------------------
#
# A distributed SPMD run cannot funnel every parameter through one
# process when leaves are partitioned over the mesh (and should not
# serialize a pod's worth of HBM through process 0 even when it could).
# A *sharded generation* is a directory:
#
#     <prefix><tag>.<epoch>.shards/
#         part0.pickle.gz      # process 0: workflow pickle + its shards
#         part1.pickle.gz      # process k: its addressable shards
#         ...
#         MANIFEST.json        # written LAST by process 0, after the
#                              # cross-process barrier — its presence is
#                              # the completeness marker
#
# Each process writes exactly the shards it owns (``replica_id == 0``
# dedupes replicated leaves to one writer), every record carrying the
# GLOBAL shape + index slices — so a checkpoint taken at world size N
# restores at world size M: the reader assembles full host arrays from
# whatever membership wrote them, and the trainer re-shards via
# ``put_global`` onto the new mesh (Zhuang et al.'s observation that
# redistribution = gather-by-index + re-place, here through host
# memory at checkpoint scale). A missing/corrupt part or incomplete
# coverage raises at load, so ``restore_latest`` falls back to the
# previous complete generation — the same warn-and-fall-back contract
# single-file snapshots have.


def shard_records(value):
    """``(meta, entries)`` for one checkpoint leaf as THIS process
    sees it. ``entries`` is ``[(global_index, ndarray), ...]`` for the
    addressable shards this process is responsible for (first replica
    only); non-jax host values return ``(None, None)`` — the caller
    inlines them on process 0."""
    import jax
    import numpy as _np
    if not isinstance(value, jax.Array):
        return None, None
    entries = []
    for shard in value.addressable_shards:
        if shard.replica_id != 0:
            continue
        entries.append((shard.index, _np.asarray(shard.data)))
    meta = {"shape": tuple(value.shape), "dtype": str(value.dtype)}
    return meta, entries


def _part_name(k, compression="gz"):
    ext = ("." + compression) if compression else ""
    return "part%d.pickle%s" % (k, ext)


def _write_part_file(gen_dir, k, part, compression="gz"):
    """Atomically write one process's part file; returns its size."""
    payload = pickle.dumps(part, protocol=pickle.HIGHEST_PROTOCOL)

    def stage(tmp):
        with CODECS.get(compression, open)(tmp, "wb") as fout:
            fout.write(payload)

    _atomic_write(gen_dir, _part_name(k, compression), stage)
    return len(payload)


def _write_manifest(gen_dir, nparts, epoch, checksum=None,
                    compression="gz", extra=None):
    import json
    manifest = {"format": 1, "kind": "veles-sharded-snapshot",
                "nparts": int(nparts), "epoch": int(epoch),
                "parts": [_part_name(k, compression)
                          for k in range(nparts)],
                "created": time.time(), "checksum": checksum}
    if extra:
        manifest.update(extra)

    def stage(tmp):
        with open(tmp, "w") as fout:
            json.dump(manifest, fout, indent=1)

    _atomic_write(gen_dir, MANIFEST_NAME, stage)


def save_snapshot_sharded(workflow, directory, records, *,
                          process_index=0, process_count=1, tag="",
                          prefix="wf", compression="gz", barrier=None,
                          link_tag=None, manifest_extra=None):
    """Write THIS process's part of one sharded checkpoint generation.

    ``records``: ``[(spec, value)]`` where ``spec`` is a small JSON-able
    dict locating the leaf in the workflow (see :func:`_apply_record`)
    and ``value`` is a ``jax.Array`` (possibly partitioned over a
    multi-process mesh) or a plain host value. Every process calls this
    with the SAME records in the same order; each writes only the
    shards it owns. ``barrier`` (a callable, e.g. wrapping
    ``multihost_utils.sync_global_devices``) runs after the part write;
    process 0 then writes the manifest — so a generation becomes a
    restore candidate only once every part is durably in place.

    Returns ``(generation_dir, bytes_written_by_this_process)``."""
    epoch = wf_epoch(workflow)
    name = "%s%s.%d%s" % (prefix, tag, epoch, SHARDED_SUFFIX)
    gen_dir = os.path.join(directory, name)
    os.makedirs(gen_dir, exist_ok=True)
    import numpy as _np
    out_records = []
    for spec, value in records:
        meta, entries = shard_records(value)
        if meta is None:
            if process_index == 0:
                if isinstance(value, _np.ndarray):
                    # host-master leaves (an offloaded run's params/opt
                    # state, ISSUE 17): encode as one full-coverage
                    # shard so restore validates them like any device
                    # leaf — and the generation restores bit-identically
                    # into EITHER residency mode
                    out_records.append({
                        "spec": spec, "shape": tuple(value.shape),
                        "dtype": str(value.dtype),
                        "shards": [((slice(None),) * value.ndim,
                                    _np.asarray(value))]})
                else:
                    out_records.append({"spec": spec, "value": value})
            continue
        out_records.append({"spec": spec, "shape": meta["shape"],
                            "dtype": meta["dtype"], "shards": entries})
    part = {"format": 1, "part": int(process_index),
            "records": out_records}
    if process_index == 0:
        part["workflow"] = dump_workflow(workflow)
    nbytes = _write_part_file(gen_dir, process_index, part, compression)
    if barrier is not None:
        barrier()
    if process_index == 0:
        _write_manifest(gen_dir, process_count, epoch,
                        checksum=getattr(workflow, "checksum", None),
                        compression=compression, extra=manifest_extra)
        if link_tag is not None:
            link_path = os.path.join(
                directory, "%s%s_current.pickle" % (prefix, link_tag))
            try:
                if os.path.islink(link_path) or os.path.exists(link_path):
                    os.unlink(link_path)
                os.symlink(name, link_path)
            except OSError:
                pass  # filesystems without symlinks
        # retention AFTER the manifest commit: the generation just
        # cut is complete (and newest), so it can never be a victim
        from veles_tpu.envknob import env_knob
        keep = env_knob("VELES_SNAPSHOT_KEEP", None, parse=int,
                        on_error="default")
        if keep is not None and keep >= 1:
            prune_sharded_generations(directory, keep, prefix=prefix)
    return gen_dir, nbytes


def generation_manifest(gen_dir):
    """The manifest dict of a sharded generation directory (ISSUE 15:
    carries ``mesh_axes``/``world_size`` of the SOURCE layout, so a
    restore at a different mesh shape can name the A->B reshard it is
    about to perform). Raises like :func:`load_sharded_generation` on
    a torn generation."""
    import json
    with open(os.path.join(gen_dir, MANIFEST_NAME)) as fin:
        manifest = json.load(fin)
    if manifest.get("kind") != "veles-sharded-snapshot":
        raise pickle.UnpicklingError(
            "not a sharded snapshot manifest: %s" % gen_dir)
    return manifest


def _read_part_file(path):
    with _open_for_read(path) as fin:
        part = pickle.load(fin)
    if not isinstance(part, dict) or "records" not in part:
        raise pickle.UnpicklingError(
            "not a sharded-snapshot part: %s" % path)
    return part


def _apply_record(workflow, spec, value):
    """Install one assembled leaf into the restored workflow.

    Spec kinds (written by ``FusedTrainer.checkpoint_records``):

    * ``{"kind": "param", "forward": i, "name": n}`` — layer weights,
      replacing the unit Array's host buffer;
    * ``{"kind": "opt", "forward": i, "path": [...]}`` — one optimizer
      state leaf of the GD unit attached to forward ``i``.
    """
    kind = spec.get("kind")
    if kind == "param":
        fwd = list(workflow.forwards)[spec["forward"]]
        fwd.param_arrays()[spec["name"]].reset(value)
        return
    if kind == "opt":
        fwd = list(workflow.forwards)[spec["forward"]]
        gd = next((g for g in getattr(workflow, "gds", ())
                   if g.forward is fwd), None)
        if gd is None:
            raise KeyError("no GD unit for forward %d" % spec["forward"])
        path = list(spec["path"])
        if not path:
            gd.opt_state = value
            return
        if not isinstance(gd.opt_state, dict):
            gd.opt_state = {}
        node = gd.opt_state
        for key in path[:-1]:
            nxt = node.get(key)
            if not isinstance(nxt, dict):
                nxt = node[key] = {}
            node = nxt
        node[path[-1]] = value
        return
    raise KeyError("unknown sharded record kind %r" % kind)


def load_sharded_generation(gen_dir):
    """Load one complete sharded generation -> restored workflow.

    Raises when the manifest or ANY part is missing/corrupt, or a
    leaf's shards do not cover its full global shape — the caller
    (:func:`restore_latest`) then falls back to the previous complete
    generation, exactly like a corrupt single-file snapshot."""
    import json
    import numpy as _np
    manifest = generation_manifest(gen_dir)
    parts = [_read_part_file(os.path.join(gen_dir, name))
             for name in manifest["parts"]]
    part0 = next((p for p in parts if "workflow" in p), None)
    if part0 is None:
        raise pickle.UnpicklingError(
            "no part carries the workflow structure: %s" % gen_dir)
    workflow = load_workflow(part0["workflow"])
    # assemble every leaf from the union of all parts' shards
    assembled = {}
    order = []
    for part in parts:
        for rec in part["records"]:
            key = json.dumps(rec["spec"], sort_keys=True)
            if key not in assembled:
                order.append(key)
                if "value" in rec:
                    assembled[key] = {"spec": rec["spec"],
                                      "value": rec["value"]}
                    continue
                assembled[key] = {
                    "spec": rec["spec"],
                    "out": _np.empty(tuple(rec["shape"]),
                                     dtype=rec["dtype"]),
                    "covered": 0}
            slot = assembled[key]
            for index, data in rec.get("shards", ()):
                slot["out"][tuple(index)] = data
                slot["covered"] += int(data.size)
    for key in order:
        slot = assembled[key]
        if "value" in slot:
            _apply_record(workflow, slot["spec"], slot["value"])
            continue
        if slot["covered"] != slot["out"].size:
            raise ValueError(
                "sharded leaf %s covers %d of %d elements in %s — "
                "incomplete generation" %
                (key, slot["covered"], slot["out"].size, gen_dir))
        _apply_record(workflow, slot["spec"], slot["out"])
    return workflow


def unit_sizes(workflow):
    """Per-unit pickled sizes — the reference's size diagnostics
    (``veles/snapshotter.py`` "took too much space" reporting).

    All units are put in stripped mode for the whole measurement:
    cross-unit references (``forward``, attribute links) then pickle as
    near-empty stubs, so each number reflects that unit's own payload.
    """
    sizes = {}
    units = list(workflow)
    for unit in units:
        unit.stripped_pickle = True
    try:
        for unit in units:
            try:
                sizes[unit.name] = len(pickle.dumps(
                    unit, protocol=pickle.HIGHEST_PROTOCOL))
            except Exception:
                sizes[unit.name] = -1
    finally:
        for unit in units:
            unit.stripped_pickle = False
    return sizes
