"""Data-parallel fused training.

The TPU lowering of the reference's master↔slave data parallelism
(SURVEY.md §2.4): instead of pickled per-unit deltas over ZeroMQ with a
compute-free master, the minibatch axis is sharded over the mesh's
``data`` axis and parameters are replicated; XLA's SPMD partitioner
inserts the gradient all-reduce (``lax.psum`` over ICI) inside the
compiled step. A single controller drives every chip — the "master" has
collapsed into the jit.

Optionally combines with tensor parallelism: pass ``param_shardings``
(see :mod:`veles_tpu.parallel.tp`) to shard layer weights over the
``model`` axis; XLA then inserts the activation collectives too.
"""

import jax
import jax.numpy as jnp

from veles_tpu.parallel.mesh import (build_mesh, named_sharding,
                                     put_global)
from veles_tpu.train.step import FusedTrainer


class DataParallelTrainer(FusedTrainer):
    """FusedTrainer whose compiled segments shard the batch over a mesh.

    ``mesh`` must contain the ``axis`` (default "data") axis; the
    minibatch size must divide by its size. Parameters/optimizer state
    are replicated unless ``param_shardings`` overrides per-layer specs.
    """

    def __init__(self, workflow, mesh=None, axis="data",
                 param_shardings=None, **kwargs):
        self.mesh = mesh if mesh is not None else build_mesh()
        self.axis = axis
        self._param_shardings = param_shardings
        n_shards = self.mesh.shape[axis]
        mb = workflow.loader.max_minibatch_size
        if mb % n_shards:
            # fail HERE with the constraint spelled out instead of an
            # opaque sharding error out of jit — this is the check an
            # elastic restart at a NEW world size hits first (ISSUE 13:
            # the re-formed mesh must still divide the minibatch, or
            # the deterministic re-partition of the index matrix
            # cannot keep every minibatch training exactly once)
            raise ValueError(
                "minibatch size %d does not divide over the %r mesh "
                "axis (%d shards); pick a minibatch the pod's every "
                "reachable world size divides, or a smaller mesh"
                % (mb, axis, n_shards))
        # set before super().__init__: _build() compiles the segments,
        # whose in_shardings read this spec
        self._data_spec = named_sharding(self.mesh, axis)
        super(DataParallelTrainer, self).__init__(workflow, **kwargs)
        if self.streaming:
            # out-of-core: shards flow through the prefetch staging
            # ring, placed per-device by _shard_placer — there is no
            # resident dataset to row-shard
            return
        # the loader uploaded the dataset committed to ONE device
        # (memory.py device_put). SHARD it over the data axis — a
        # replicated dataset multiplies HBM by mesh size and cannot fit
        # ImageNet-shaped fullbatch loaders (VERDICT r2 weak #5). The
        # index gather stays on GLOBAL sample ids, so XLA's SPMD
        # partitioner inserts the cross-shard gather collective over
        # ICI; serving order (and therefore the math) is identical to a
        # single device. The sample dim is padded to divide the axis —
        # indices never reach the pad rows.
        import numpy
        # stage through HOST memory: padding on-device would hold a
        # second full-size copy on the loader's device — exactly the
        # 2x HBM peak this sharding exists to avoid. _shard_placer is
        # the ONE pad-and-place implementation (streamed shards use it
        # per shard; here it places the whole dataset once).
        place = self._shard_placer()
        self._data_args = tuple(place(numpy.asarray(a))
                                for a in self._data_args)
        # the loader's Arrays still hold the FULL dataset committed to
        # one device (FusedTrainer.__init__ forced .devmem to build
        # _data_args) — release those buffers so that device holds only
        # its 1/N shard, not full + 1/N
        for arr in (self.loader.original_data,
                    self.loader.original_labels
                    if self.loss_kind == "softmax"
                    else self.loader.original_targets):
            arr.release_devmem()

    def _dataset_device_bytes(self, total_bytes):
        # row-sharded residency: each device holds 1/N of the dataset,
        # so the stream-vs-resident decision compares the SHARD size
        # against one device's budget
        return total_bytes / self.mesh.shape[self.axis]

    def _shard_placer(self):
        """Streamed shards land directly as addressable per-device
        shards of the data-axis ``NamedSharding`` — each device
        receives its row slice of the host shard straight from host
        memory (``put_global``: plain sharded ``device_put``
        single-process, ``make_array_from_callback`` multi-controller).
        No device ever sees the full shard, and there is no
        gather-then-scatter hop. The pad-and-place implementation is
        :func:`veles_tpu.loader.prefetch.sharded_placer` (local shard
        indices never reach the pad rows), routed through the measured
        reshard primitive (ISSUE 15)."""
        from veles_tpu.loader import prefetch
        return prefetch.sharded_placer(self._data_spec,
                                       self.mesh.shape[self.axis])

    def _params_spec(self):
        if self._param_shardings is not None:
            return self._param_shardings
        return named_sharding(self.mesh)  # replicated (prefix pytree)

    def _compile_train(self, fn):
        repl = named_sharding(self.mesh)
        params_spec = self._params_spec()
        # dataset/truth are row-sharded args; the per-minibatch index
        # gather crosses shards via XLA's SPMD collectives
        data_spec = (self._data_spec, self._data_spec)
        # idx_matrix: (n_batches, mb) — shard the per-step batch dim
        idx_spec = named_sharding(self.mesh, None, self.axis)
        # outputs: params, states, losses, metrics (+ grad norms when
        # the flight recorder's tracking is on) — everything after the
        # params stays replicated
        n_extra = 3 + (1 if self.track_grad_norms else 0)
        jitted = jax.jit(
            fn,
            in_shardings=(data_spec, params_spec, repl, idx_spec, repl),
            out_shardings=(params_spec,) + (repl,) * n_extra,
            donate_argnums=(1, 2) if self.donate else ())
        if jax.process_count() == 1:
            return jitted

        def multihost_call(data_args, params, states, idx, keys):
            # host-built idx/keys must be placed explicitly under
            # multi-controller SPMD (implicit device_put would reject
            # the cross-process sharding)
            return jitted(data_args, params, states,
                          put_global(idx, idx_spec),
                          put_global(keys, repl))
        return multihost_call

    def _compile_eval(self, fn):
        repl = named_sharding(self.mesh)
        idx_spec = named_sharding(self.mesh, None, self.axis)
        # out_shardings as a single spec: the eval returns 2 leaves
        # (losses, metrics) or 3 when confusion rides the scan
        jitted = jax.jit(
            fn,
            in_shardings=((self._data_spec, self._data_spec),
                          self._params_spec(), idx_spec),
            out_shardings=repl)
        if jax.process_count() == 1:
            return jitted

        def multihost_call(data_args, params, idx):
            return jitted(data_args, params, put_global(idx, idx_spec))
        return multihost_call

    def pull_params(self):
        """Re-place host-committed params onto the mesh per the declared
        shardings (a committed single-device array would otherwise clash
        with the jit's in_shardings) — through the measured reshard
        primitive (ISSUE 15), so an elastic restore at a NEW mesh shape
        shows its re-placement cost as ``veles_reshard_ms``."""
        from veles_tpu.parallel import reshard
        params, states = super(DataParallelTrainer, self).pull_params()
        spec = self._params_spec()
        if not isinstance(spec, (tuple, list)):
            spec = tuple(spec for _ in params)
        params = tuple(
            {k: reshard.reshard(v, spec[i][k]
                                if isinstance(spec[i], dict)
                                else spec[i])
             for k, v in layer.items()}
            for i, layer in enumerate(params))
        repl = named_sharding(self.mesh)
        states = jax.tree_util.tree_map(
            lambda v: reshard.reshard(v, repl), states)
        return params, states
