"""Data-parallel fused training.

The TPU lowering of the reference's master↔slave data parallelism
(SURVEY.md §2.4): instead of pickled per-unit deltas over ZeroMQ with a
compute-free master, the minibatch axis is sharded over the mesh's
``data`` axis and parameters are replicated; XLA's SPMD partitioner
inserts the gradient all-reduce (``lax.psum`` over ICI) inside the
compiled step. A single controller drives every chip — the "master" has
collapsed into the jit.

Optionally combines with tensor parallelism: pass ``param_shardings``
(see :mod:`veles_tpu.parallel.tp`) to shard layer weights over the
``model`` axis; XLA then inserts the activation collectives too.
"""

import jax

from veles_tpu.parallel.mesh import build_mesh, named_sharding
from veles_tpu.train.step import FusedTrainer


class DataParallelTrainer(FusedTrainer):
    """FusedTrainer whose compiled segments shard the batch over a mesh.

    ``mesh`` must contain the ``axis`` (default "data") axis; the
    minibatch size must divide by its size. Parameters/optimizer state
    are replicated unless ``param_shardings`` overrides per-layer specs.
    """

    def __init__(self, workflow, mesh=None, axis="data",
                 param_shardings=None, **kwargs):
        self.mesh = mesh if mesh is not None else build_mesh()
        self.axis = axis
        self._param_shardings = param_shardings
        super(DataParallelTrainer, self).__init__(workflow, **kwargs)
        # the loader uploaded the dataset committed to ONE device
        # (memory.py device_put); replicate it onto the mesh to match
        # the declared in_shardings — same clash pull_params() resolves
        # for the parameters
        repl = named_sharding(self.mesh)
        self._data_args = tuple(jax.device_put(a, repl)
                                for a in self._data_args)

    def _params_spec(self):
        if self._param_shardings is not None:
            return self._param_shardings
        return named_sharding(self.mesh)  # replicated (prefix pytree)

    def _compile_train(self, fn):
        repl = named_sharding(self.mesh)
        params_spec = self._params_spec()
        # dataset/truth are replicated args (each chip gathers its own
        # shard of every minibatch by index)
        data_spec = (repl, repl)
        # idx_matrix: (n_batches, mb) — shard the per-step batch dim
        idx_spec = named_sharding(self.mesh, None, self.axis)
        return jax.jit(
            fn,
            in_shardings=(data_spec, params_spec, repl, idx_spec, repl),
            out_shardings=(params_spec, repl, repl, repl),
            donate_argnums=(1, 2) if self.donate else ())

    def _compile_eval(self, fn):
        repl = named_sharding(self.mesh)
        idx_spec = named_sharding(self.mesh, None, self.axis)
        # out_shardings as a single spec: the eval returns 2 leaves
        # (losses, metrics) or 3 when confusion rides the scan
        return jax.jit(fn, in_shardings=((repl, repl),
                                         self._params_spec(), idx_spec),
                       out_shardings=repl)

    def pull_params(self):
        """Re-place host-committed params onto the mesh per the declared
        shardings (a committed single-device array would otherwise clash
        with the jit's in_shardings)."""
        params, states = super(DataParallelTrainer, self).pull_params()
        spec = self._params_spec()
        if not isinstance(spec, (tuple, list)):
            spec = tuple(spec for _ in params)
        params = tuple(
            {k: jax.device_put(v, spec[i][k]
                               if isinstance(spec[i], dict)
                               else spec[i])
             for k, v in layer.items()}
            for i, layer in enumerate(params))
        repl = named_sharding(self.mesh)
        states = jax.tree_util.tree_map(
            lambda v: jax.device_put(v, repl), states)
        return params, states
