"""JAX API-drift shims for the parallel layer.

``shard_map`` has moved twice across the JAX versions this repo meets:
``jax.experimental.shard_map.shard_map(..., check_rep=...)`` (the
widest-deployed form), then top-level ``jax.shard_map`` with the
``check_rep`` flag renamed to ``check_vma``. Every call site here uses
:func:`shard_map` from this module with the NEW keyword spelling; the
shim resolves the implementation once at import and translates the
flag, so the parallel layer runs unmodified on either side of the
rename (this is the version drift that failed ~20 tier-1 tests from
PR 4 through PR 6).
"""

import inspect

import jax

__all__ = ["shard_map"]


def _resolve():
    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl
    try:
        params = inspect.signature(impl).parameters
    except (TypeError, ValueError):  # C-accelerated / wrapped: assume new
        return impl, "check_vma"
    if "check_vma" in params:
        return impl, "check_vma"
    if "check_rep" in params:
        return impl, "check_rep"
    return impl, None


_IMPL, _CHECK_KW = _resolve()


def shard_map(f, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` with the modern signature on any JAX.

    ``check_vma`` (new name; ``None`` = library default) maps onto
    whichever replication-check flag this JAX spells; extra kwargs pass
    through untouched.
    """
    if check_vma is not None and _CHECK_KW is not None:
        kwargs[_CHECK_KW] = check_vma
    return _IMPL(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 **kwargs)
