"""Binary payload codec for the distributed control plane.

The reference streamed pickles through ZeroMQ with selectable
gzip/snappy/xz codecs (``veles/txzmq/connection.py:140-143,283-339``).
Round 1 framed cross-host blobs as base64 inside JSON (+33% bytes, no
codec); round 3 restored binary framing (pickle + optional zlib behind
a 1-byte codec tag). This round adds **out-of-band array framing**:
docs/PERF.md r5 measured the flagship 249.5 MB AlexNet-227 parameter
payload at 1.82 s per pickle-encode -> shm memcpy -> decode cycle
(137 MB/s, single core) — the pickle pass copies every array into a
byte-string on encode and back out on decode, twice more than the
transport itself needs. The OOB format pickles only the array-free
*skeleton* of the pytree; array leaves ride after it as raw buffers
described by a tiny JSON table, so:

* :func:`encode_chunks` returns the payload as a scatter/gather list
  whose array parts are zero-copy ``memoryview``s of the original
  arrays — the shm fast path memcpys them straight into the segment,
  never materializing a pickle byte-string;
* :func:`decode` reconstructs array leaves as zero-copy
  ``numpy.frombuffer`` views over the received buffer (read-only; the
  consumers copy into their own unit arrays when applying).

Same-host peers skip compression (the shm fast path moves bytes at
memory speed; zlib would only burn CPU). Cross-host blobs compress
with zlib level 1 — weight deltas are float arrays where even fast
compression wins back far more wire time than it costs.

Decoding is **restricted by default**: control-plane payloads are
numpy arrays plus JSON-shaped primitives, so :func:`decode` refuses to
reconstruct any other class. The reference trusted raw pickles from
the network (``veles/txzmq/connection.py:337``, arbitrary-code
execution for anyone who could reach the port); here a hostile blob
raises :class:`UnsafePayloadError` instead of importing attacker-chosen
callables. The OOB format does not widen that surface: its skeleton
goes through the same :class:`RestrictedUnpickler`, and its raw
buffers only ever become arrays via ``numpy.frombuffer`` with a
validated non-object dtype and bounds-checked offsets. Pass
``trusted=True`` only for blobs that never crossed a network boundary.

On top of the transport, :class:`DeltaEncoder`/:class:`DeltaDecoder`
implement the master->slave parameter-delta exchange: after one full
push, updates carry per-leaf deltas with an exact dirty/epsilon skip
and an opt-in bf16 cast — halving exchange bytes the way the bf16
compute policy halved HBM traffic (docs/PERF.md).
"""

import io
import json
import pickle
import struct
import zlib

import numpy

RAW = b"\x00"
ZLIB = b"\x01"
#: out-of-band array framing (skeleton pickle + raw buffer table)
OOB = b"\x02"

#: magic prefix of an OOB body — lets :func:`decode` recognize an OOB
#: payload after zlib decompression (legacy ZLIB bodies are protocol-4
#: pickles, which always start with ``b"\x80\x04"``)
OOB_MAGIC = b"VOB1"

#: don't compress blobs smaller than this (codec overhead dominates)
MIN_COMPRESS = 4 * 1024

#: array leaves at least this large go out-of-band; smaller ones ride
#: the skeleton pickle (per-leaf table overhead dominates below this)
OOB_MIN_ARRAY = 512

#: leaf buffers are aligned to this inside the data section so decoded
#: views are cacheline-aligned when the containing buffer is
OOB_ALIGN = 64


class UnsafePayloadError(pickle.UnpicklingError):
    """A network payload referenced a class outside the allowlist."""


class _Leaf(object):
    """Skeleton placeholder for an out-of-band array leaf."""

    __slots__ = ("index",)

    def __init__(self, index):
        self.index = index

    def __reduce__(self):
        return (_Leaf, (self.index,))


#: (module, qualname) pairs a control-plane payload may reconstruct.
#: numpy 2 pickles through ``numpy._core``; peers on numpy 1.x emit
#: ``numpy.core`` — both spellings are the same two functions.
SAFE_GLOBALS = {
    ("builtins", "complex"),
    ("builtins", "bytearray"),
    ("builtins", "set"),
    ("builtins", "frozenset"),
    ("builtins", "slice"),
    ("builtins", "range"),
    ("collections", "OrderedDict"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    # the OOB skeleton's array placeholder (data only: one int)
    ("veles_tpu.parallel.wire", "_Leaf"),
    # bf16 arrays/scalars pickle through the ml_dtypes dtype class —
    # plain data, no code execution (the --exchange-dtype bfloat16
    # delta path and any sub-threshold bf16 leaf need it)
    ("ml_dtypes", "bfloat16"),
}


#: numpy cannot spell extension dtypes from a string; these are the
#: names the OOB leaf table may carry beyond ``numpy.dtype(str)``
def _ext_dtypes():
    try:
        import ml_dtypes
    except ImportError:  # pragma: no cover - baked into this image
        return {}
    return {"bfloat16": ml_dtypes.bfloat16}


class RestrictedUnpickler(pickle.Unpickler):
    """Allowlist unpickler: numpy + basic containers, nothing else."""

    def find_class(self, module, name):
        if (module, name) in SAFE_GLOBALS or (
                # numpy 2 moved dtype classes to numpy.dtypes
                # (Float32DType etc.) — plain data, no code execution
                module == "numpy.dtypes" and name.endswith("DType")):
            return super(RestrictedUnpickler, self).find_class(
                module, name)
        raise UnsafePayloadError(
            "payload references forbidden global %s.%s" % (module, name))


def _restricted_loads(payload):
    return RestrictedUnpickler(io.BytesIO(payload)).load()


# -- out-of-band framing -----------------------------------------------------


class Chunks(object):
    """One logical blob as a scatter/gather list of buffers.

    The first part is the codec tag + OOB header; the rest are raw
    array buffers (zero-copy ``memoryview``s of the source arrays) and
    their alignment padding. A transport that can write vectored
    (:meth:`Protocol.send`'s shm/frame paths) streams the parts
    straight to their destination; :meth:`join` materializes one bytes
    object for transports that cannot.
    """

    __slots__ = ("parts", "nbytes")

    def __init__(self, parts):
        self.parts = [self._as_bytes_view(p) for p in parts]
        self.nbytes = sum(len(p) for p in self.parts)

    @staticmethod
    def _as_bytes_view(part):
        if isinstance(part, bytes):
            return part
        if isinstance(part, numpy.ndarray):
            part = numpy.ascontiguousarray(part)
            if part.dtype.kind == "V":
                # extension dtypes (bf16) export no buffer; their bytes
                # are still a plain uint8 view away
                part = part.view(numpy.uint8)
        return memoryview(part).cast("B")

    def join(self):
        return b"".join(self.parts)


def _dtype_token(dtype):
    """Wire name for a dtype, or None if it cannot go out-of-band."""
    if dtype.hasobject:
        return None
    if dtype.kind in "Mm":
        # datetime64/timedelta64 export no buffer (memoryview refuses
        # kind 'M'/'m'); the skeleton pickle handles them as before
        return None
    if dtype.kind == "V":
        # extension dtypes (bf16 & friends) stringify ambiguously
        # ('<V2'); only named ones we can reconstruct may go OOB
        name = dtype.name
        return name if name in _ext_dtypes() else None
    return dtype.str


def _resolve_dtype(token):
    """Wire name -> dtype, refusing anything that could smuggle
    object references past the restricted unpickler."""
    ext = _ext_dtypes()
    if token in ext:
        return numpy.dtype(ext[token])
    try:
        dtype = numpy.dtype(str(token))
    except (TypeError, ValueError) as e:
        raise UnsafePayloadError("bad OOB dtype %r: %s" % (token, e))
    if dtype.hasobject:
        raise UnsafePayloadError("object dtype %r refused" % (token,))
    return dtype


def _extract(value, leaves):
    """Replace extractable array leaves with :class:`_Leaf` markers.

    Only plain dict/list/tuple containers are walked (rebuilt with the
    same type); anything else — including OrderedDicts, sets and
    arrays below :data:`OOB_MIN_ARRAY` — stays in the skeleton pickle
    untouched, so the format degrades gracefully to the legacy one.
    """
    if isinstance(value, numpy.ndarray) and \
            value.nbytes >= OOB_MIN_ARRAY and \
            _dtype_token(value.dtype) is not None:
        leaves.append(numpy.ascontiguousarray(value))
        return _Leaf(len(leaves) - 1)
    if type(value) is dict:
        return {k: _extract(v, leaves) for k, v in value.items()}
    if type(value) is list:
        return [_extract(v, leaves) for v in value]
    if type(value) is tuple:
        return tuple(_extract(v, leaves) for v in value)
    return value


def _substitute(value, leaves):
    if isinstance(value, _Leaf):
        index = value.index
        if not (isinstance(index, int) and 0 <= index < len(leaves)):
            raise UnsafePayloadError(
                "OOB leaf index %r out of range" % (index,))
        return leaves[index]
    if type(value) is dict:
        return {k: _substitute(v, leaves) for k, v in value.items()}
    if type(value) is list:
        return [_substitute(v, leaves) for v in value]
    if type(value) is tuple:
        return tuple(_substitute(v, leaves) for v in value)
    return value


def _oob_parts(obj):
    """obj -> Chunks (tag + header + skeleton, then raw leaf buffers),
    or None when nothing is worth framing out-of-band."""
    leaves = []
    skeleton = _extract(obj, leaves)
    if not leaves:
        return None
    skel = pickle.dumps(skeleton, protocol=4)
    table = []
    offset = 0
    for arr in leaves:
        offset += (-offset) % OOB_ALIGN
        table.append([_dtype_token(arr.dtype), list(arr.shape), offset,
                      arr.nbytes])
        offset += arr.nbytes
    # data_off is provisional: meta's own length shifts it, so compute
    # with a fixed-point — data_off's digit count is nondecreasing and
    # bounded, so this converges (in practice on the second pass).
    # Alignment is computed over the WHOLE blob including the 1-byte
    # codec tag (data_off itself stays relative to the body, i.e. the
    # magic): leaf views decoded from the contiguous blob then sit at
    # OOB_ALIGN boundaries of the blob, not one byte off them.
    head_len = 1 + len(OOB_MAGIC) + 4
    data_off = 0
    while True:
        meta = json.dumps({"skel": len(skel), "data": data_off,
                           "leaves": table},
                          separators=(",", ":")).encode()
        base = head_len + len(meta) + len(skel)
        new_off = base + ((-base) % OOB_ALIGN) - 1
        if new_off == data_off:
            break
        data_off = new_off
    header = b"".join([
        OOB, OOB_MAGIC, struct.pack("<I", len(meta)), meta, skel,
        b"\x00" * (data_off + 1 - (head_len + len(meta) + len(skel)))])
    parts = [header]
    pos = 0
    for arr, entry in zip(leaves, table):
        pad = entry[2] - pos
        if pad:
            parts.append(b"\x00" * pad)
        parts.append(arr)
        pos = entry[2] + arr.nbytes
    return Chunks(parts)


def _decode_oob(body, trusted):
    """OOB body (magic onward, buffer-like) -> object with zero-copy
    ``frombuffer`` array views over ``body``."""
    view = memoryview(body)
    if len(view) < len(OOB_MAGIC) + 4:
        raise UnsafePayloadError("truncated OOB header")
    (meta_len,) = struct.unpack_from("<I", view, len(OOB_MAGIC))
    meta_off = len(OOB_MAGIC) + 4
    if meta_off + meta_len > len(view):
        raise UnsafePayloadError("OOB meta overruns payload")
    try:
        meta = json.loads(bytes(view[meta_off:meta_off + meta_len]))
        skel_len = int(meta["skel"])
        data_off = int(meta["data"])
        entries = list(meta["leaves"])
    except (ValueError, KeyError, TypeError) as e:
        raise UnsafePayloadError("malformed OOB meta: %s" % e)
    skel_off = meta_off + meta_len
    if not (0 <= skel_len and skel_off + skel_len <= len(view) and
            0 <= data_off <= len(view)):
        raise UnsafePayloadError("OOB skeleton overruns payload")
    skel = bytes(view[skel_off:skel_off + skel_len])
    skeleton = pickle.loads(skel) if trusted else _restricted_loads(skel)
    data = view[data_off:]
    leaves = []
    for entry in entries:
        try:
            token, shape, offset, nbytes = entry
            shape = tuple(int(s) for s in shape)
            offset, nbytes = int(offset), int(nbytes)
        except (ValueError, TypeError) as e:
            raise UnsafePayloadError("malformed OOB leaf entry: %s" % e)
        dtype = _resolve_dtype(token)
        count = 1
        for s in shape:
            if s < 0:
                raise UnsafePayloadError("negative OOB dim %d" % s)
            count *= s
        if nbytes != count * dtype.itemsize or offset < 0 or \
                offset + nbytes > len(data):
            raise UnsafePayloadError(
                "OOB leaf out of bounds: off=%d nbytes=%d data=%d"
                % (offset, nbytes, len(data)))
        leaves.append(numpy.frombuffer(
            data[offset:offset + nbytes], dtype=dtype,
            count=count).reshape(shape))
    return _substitute(skeleton, leaves)


# -- public codec ------------------------------------------------------------


def encode_chunks(obj):
    """Object -> :class:`Chunks` for vectored (zero-copy) transports.

    Array leaves are referenced, not copied — the caller must keep the
    source arrays unmodified until the chunks are written out (the
    Protocol writes under its send lock within the same call). Falls
    back to a single legacy-pickle part when nothing is extractable.
    """
    parts = _oob_parts(obj)
    if parts is not None:
        return parts
    return Chunks([RAW + pickle.dumps(obj, protocol=4)])


def encode(obj, compress=True):
    """Object -> tagged bytes."""
    parts = _oob_parts(obj)
    if parts is None:
        payload = RAW + pickle.dumps(obj, protocol=4)
    else:
        payload = parts.join()
    if compress and len(payload) >= MIN_COMPRESS:
        # memoryview slice: don't memcpy a 250 MB payload just to
        # strip the 1-byte tag before zlib
        packed = zlib.compress(memoryview(payload)[1:], 1)
        if len(packed) < len(payload) - 1:
            return ZLIB + packed
    return payload


def decode(blob, trusted=False):
    """Tagged bytes -> object (allowlist-unpickled unless ``trusted``).

    Array leaves of OOB payloads come back as read-only zero-copy
    views over ``blob`` — consumers that need to mutate must copy.
    """
    if isinstance(blob, Chunks):
        blob = blob.join()
    if isinstance(blob, str):
        # a peer that fell back to text framing (or a shm segment read
        # as text) delivers latin-1; recover the raw bytes
        blob = blob.encode("latin-1")
    view = memoryview(blob)
    tag, payload = bytes(view[:1]), view[1:]
    if tag == ZLIB:
        payload = memoryview(zlib.decompress(payload))
    elif tag == OOB:
        return _decode_oob(payload, trusted)
    elif tag != RAW:
        raise ValueError("unknown wire codec tag %r" % tag)
    if bytes(payload[:len(OOB_MAGIC)]) == OOB_MAGIC:
        # zlib-compressed OOB body (cross-host path)
        return _decode_oob(payload, trusted)
    if trusted:
        return pickle.loads(payload)
    return _restricted_loads(payload)


# -- parameter-delta exchange ------------------------------------------------

#: delta wire markers (plain dicts: survive any codec, no new pickle
#: surface); a user dict carrying one of these keys is escaped
_D_KEEP = "__dkeep__"
_D_ADD = "__dadd__"
_D_ESC = "__desc__"
_D_WRAP = "__wire_delta__"


def _is_marker(value):
    return type(value) is dict and (
        (_D_KEEP in value or _D_ADD in value or _D_ESC in value)
        and len(value) == 1)


def _deltable(value):
    """Float arrays are delta-coded; ints (indices/labels) and
    everything else travel verbatim."""
    return isinstance(value, numpy.ndarray) and value.dtype.kind == "f" \
        and value.size > 0


class DeltaEncoder(object):
    """Master-side per-peer parameter-delta codec.

    The first :meth:`encode` sends the tree in full; afterwards every
    float-array leaf whose path/shape/dtype matches the previous push
    is replaced by its delta — skipped entirely when it moved by at
    most ``eps`` (0.0 = exact dirty check), optionally cast to
    ``dtype`` (bf16 halves master->slave bytes).

    The tracked base is always the value the *peer* reconstructs
    (``base + cast(delta)``), never the true local value — so cast
    error stays bounded by one quantization of a single delta instead
    of accumulating across pushes, exactly like the decoder's
    arithmetic (same numpy ops, bit-identical).
    """

    def __init__(self, dtype=None, eps=0.0):
        if dtype is not None and not isinstance(dtype, numpy.dtype):
            dtype = numpy.dtype(_ext_dtypes().get(dtype, dtype))
        self.dtype = dtype
        self.eps = float(eps)
        self.leaves_sent = 0
        self.leaves_skipped = 0
        self._base = None

    def encode(self, tree):
        full = self._base is None
        base = {} if full else self._base
        new_base = {}
        out = self._walk(tree, (), base, new_base, full)
        self._base = new_base
        return {_D_WRAP: 1, "kind": "full" if full else "delta",
                "tree": out}

    def _walk(self, value, path, base, new_base, full):
        if _deltable(value):
            prev = base.get(path)
            if full or prev is None or prev.shape != value.shape or \
                    prev.dtype != value.dtype:
                # the stored base must be immune to later in-place
                # mutation of the caller's array
                new_base[path] = numpy.array(value)
                self.leaves_sent += 1
                return value
            delta = value - prev
            moved = float(numpy.abs(delta).max()) if delta.size else 0.0
            if moved <= self.eps:
                new_base[path] = prev
                self.leaves_skipped += 1
                return {_D_KEEP: 1}
            if self.dtype is not None and self.dtype != value.dtype:
                delta = delta.astype(self.dtype)
            new_base[path] = prev + delta.astype(prev.dtype, copy=False)
            self.leaves_sent += 1
            return {_D_ADD: delta}
        if type(value) is dict:
            out = {k: self._walk(v, path + (k,), base, new_base, full)
                   for k, v in value.items()}
            if _is_marker(value) or _D_WRAP in value:
                return {_D_ESC: out}
            return out
        if type(value) in (list, tuple):
            out = [self._walk(v, path + (i,), base, new_base, full)
                   for i, v in enumerate(value)]
            return out if type(value) is list else tuple(out)
        return value


class DeltaDecoder(object):
    """Peer-side mirror of :class:`DeltaEncoder`.

    Trees that never went through a DeltaEncoder pass through
    unchanged, so a delta-aware slave serves a legacy master.
    """

    def __init__(self):
        self._base = None

    def decode(self, msg):
        if not (type(msg) is dict and msg.get(_D_WRAP) == 1):
            return msg
        full = msg.get("kind") == "full"
        if not full and self._base is None:
            raise ValueError("delta push before any full push")
        base = {} if full else self._base
        new_base = {}
        out = self._walk(msg.get("tree"), (), base, new_base)
        self._base = new_base
        return out

    def _walk(self, value, path, base, new_base):
        if _is_marker(value):
            if _D_ESC in value:
                return {k: self._walk(v, path + (k,), base, new_base)
                        for k, v in value[_D_ESC].items()}
            prev = base.get(path)
            if prev is None:
                raise ValueError("delta for unknown leaf at %r" % (path,))
            if _D_KEEP in value:
                new_base[path] = prev
                return prev
            delta = value[_D_ADD]
            recon = prev + numpy.asarray(delta).astype(prev.dtype,
                                                       copy=False)
            new_base[path] = recon
            return recon
        if _deltable(value):
            new_base[path] = value
            return value
        if type(value) is dict:
            return {k: self._walk(v, path + (k,), base, new_base)
                    for k, v in value.items()}
        if type(value) is list:
            return [self._walk(v, path + (i,), base, new_base)
                    for i, v in enumerate(value)]
        if type(value) is tuple:
            return tuple(self._walk(v, path + (i,), base, new_base)
                         for i, v in enumerate(value))
        return value
