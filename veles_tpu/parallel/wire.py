"""Binary payload codec for the distributed control plane.

The reference streamed pickles through ZeroMQ with selectable
gzip/snappy/xz codecs (``veles/txzmq/connection.py:140-143,283-339``).
Round 1 framed cross-host blobs as base64 inside JSON (+33% bytes, no
codec); this module restores binary framing: payloads are pickled and
optionally zlib-compressed, self-described by a 1-byte codec tag so
the receiver never guesses.

Same-host peers skip compression (the shm fast path moves bytes at
memory speed; zlib would only burn CPU). Cross-host blobs compress
with zlib level 1 — weight deltas are float arrays where even fast
compression wins back far more wire time than it costs.
"""

import pickle
import zlib

RAW = b"\x00"
ZLIB = b"\x01"

#: don't compress blobs smaller than this (codec overhead dominates)
MIN_COMPRESS = 4 * 1024


def encode(obj, compress=True):
    """Object -> tagged bytes."""
    payload = pickle.dumps(obj, protocol=4)
    if compress and len(payload) >= MIN_COMPRESS:
        packed = zlib.compress(payload, 1)
        if len(packed) < len(payload):
            return ZLIB + packed
    return RAW + payload


def decode(blob):
    """Tagged bytes -> object."""
    if isinstance(blob, str):
        # a peer that fell back to text framing (or a shm segment read
        # as text) delivers latin-1; recover the raw bytes
        blob = blob.encode("latin-1")
    tag, payload = blob[:1], blob[1:]
    if tag == ZLIB:
        payload = zlib.decompress(payload)
    elif tag != RAW:
        raise ValueError("unknown wire codec tag %r" % tag)
    return pickle.loads(payload)
