"""Binary payload codec for the distributed control plane.

The reference streamed pickles through ZeroMQ with selectable
gzip/snappy/xz codecs (``veles/txzmq/connection.py:140-143,283-339``).
Round 1 framed cross-host blobs as base64 inside JSON (+33% bytes, no
codec); this module restores binary framing: payloads are pickled and
optionally zlib-compressed, self-described by a 1-byte codec tag so
the receiver never guesses.

Same-host peers skip compression (the shm fast path moves bytes at
memory speed; zlib would only burn CPU). Cross-host blobs compress
with zlib level 1 — weight deltas are float arrays where even fast
compression wins back far more wire time than it costs.

Decoding is **restricted by default**: control-plane payloads are
numpy arrays plus JSON-shaped primitives, so :func:`decode` refuses to
reconstruct any other class. The reference trusted raw pickles from
the network (``veles/txzmq/connection.py:337``, arbitrary-code
execution for anyone who could reach the port); here a hostile blob
raises :class:`UnsafePayloadError` instead of importing attacker-chosen
callables. Pass ``trusted=True`` only for blobs that never crossed a
network boundary.
"""

import pickle
import io
import zlib

RAW = b"\x00"
ZLIB = b"\x01"

#: don't compress blobs smaller than this (codec overhead dominates)
MIN_COMPRESS = 4 * 1024


class UnsafePayloadError(pickle.UnpicklingError):
    """A network payload referenced a class outside the allowlist."""


#: (module, qualname) pairs a control-plane payload may reconstruct.
#: numpy 2 pickles through ``numpy._core``; peers on numpy 1.x emit
#: ``numpy.core`` — both spellings are the same two functions.
SAFE_GLOBALS = {
    ("builtins", "complex"),
    ("builtins", "bytearray"),
    ("builtins", "set"),
    ("builtins", "frozenset"),
    ("builtins", "slice"),
    ("builtins", "range"),
    ("collections", "OrderedDict"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
}


class RestrictedUnpickler(pickle.Unpickler):
    """Allowlist unpickler: numpy + basic containers, nothing else."""

    def find_class(self, module, name):
        if (module, name) in SAFE_GLOBALS or (
                # numpy 2 moved dtype classes to numpy.dtypes
                # (Float32DType etc.) — plain data, no code execution
                module == "numpy.dtypes" and name.endswith("DType")):
            return super(RestrictedUnpickler, self).find_class(
                module, name)
        raise UnsafePayloadError(
            "payload references forbidden global %s.%s" % (module, name))


def _restricted_loads(payload):
    return RestrictedUnpickler(io.BytesIO(payload)).load()


def encode(obj, compress=True):
    """Object -> tagged bytes."""
    payload = pickle.dumps(obj, protocol=4)
    if compress and len(payload) >= MIN_COMPRESS:
        packed = zlib.compress(payload, 1)
        if len(packed) < len(payload):
            return ZLIB + packed
    return RAW + payload


def decode(blob, trusted=False):
    """Tagged bytes -> object (allowlist-unpickled unless ``trusted``)."""
    if isinstance(blob, str):
        # a peer that fell back to text framing (or a shm segment read
        # as text) delivers latin-1; recover the raw bytes
        blob = blob.encode("latin-1")
    tag, payload = blob[:1], blob[1:]
    if tag == ZLIB:
        payload = zlib.decompress(payload)
    elif tag != RAW:
        raise ValueError("unknown wire codec tag %r" % tag)
    if trusted:
        return pickle.loads(payload)
    return _restricted_loads(payload)
