"""Host-side coordination service: the control plane that survives.

On TPU the *data* plane of the reference's distributed runtime became
XLA collectives (see :mod:`veles_tpu.parallel.dp`); what remains is the
*control* plane the reference ran over Twisted TCP JSON lines
(``veles/server.py``, ``veles/client.py``, ``network_common.py:132``):

* handshake with workflow **checksum** verification (a slave running a
  different graph is rejected — ``server.py:484-492``);
* slave registry with per-slave FSM (WAIT→WORK→...), ``computing_power``
  load metric, heartbeats with timeout-based **death detection**;
* a generic **job queue** for task farming (genetics chromosomes,
  ensemble members, dataset shards): jobs held by a dead slave are
  **requeued** (``loader/base.py:679-687`` semantics);
* **chaos injection**: ``death_probability`` makes a slave kill itself
  mid-job (the reference's ``--slave-death-probability``,
  ``client.py:303-307``) so elasticity is testable in-process.

Implementation is stdlib sockets + threads (no Twisted): JSON lines,
one reader thread per connection on the master, a single client thread
on the slave. Job payloads must be JSON-serializable.
"""

import json
import socket
import threading
import time
import uuid

from veles_tpu import prng
from veles_tpu.logger import Logger


class Protocol(object):
    """JSON-lines framing over a socket."""

    def __init__(self, sock):
        self.sock = sock
        self._file = sock.makefile("rwb")
        self._wlock = threading.Lock()

    def send(self, message):
        data = (json.dumps(message) + "\n").encode()
        with self._wlock:
            self._file.write(data)
            self._file.flush()

    def recv(self):
        line = self._file.readline()
        if not line:
            raise ConnectionError("peer closed")
        return json.loads(line)

    def close(self):
        try:
            self._file.close()
            self.sock.close()
        except OSError:
            pass


class SlaveDescription(object):
    """Master-side view of one slave (``veles/server.py:494-511``)."""

    def __init__(self, sid, power, mid, pid):
        self.id = sid
        self.power = power
        self.mid = mid
        self.pid = pid
        self.state = "WAIT"
        self.jobs_done = 0
        self.last_seen = time.time()
        self.current_job = None


class CoordinatorServer(Logger):
    """Master: accepts slaves, verifies checksum, farms jobs out."""

    def __init__(self, address=("127.0.0.1", 0), checksum="",
                 job_timeout=None, heartbeat_timeout=10.0):
        super(CoordinatorServer, self).__init__()
        self.checksum = checksum
        self.job_timeout = job_timeout
        self.heartbeat_timeout = heartbeat_timeout
        self.slaves = {}
        self.jobs = []                 # pending job payloads
        self.results = []
        self.job_times = []            # history for adaptive timeout
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._listener = socket.create_server(address)
        self.address = self._listener.getsockname()
        self._threads = []
        self._accepting = True
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="coordinator-accept")
        t.start()
        self._threads.append(t)
        # independent reaper: death detection must not depend on the
        # master happening to sit in wait()
        r = threading.Thread(target=self._reap_loop, daemon=True,
                             name="coordinator-reaper")
        r.start()
        self._threads.append(r)

    def _reap_loop(self):
        while not self._done.wait(min(self.heartbeat_timeout / 4, 1.0)):
            with self._lock:
                self._reap_dead()

    # -- job management ----------------------------------------------------

    def submit(self, *payloads):
        with self._lock:
            self.jobs.extend(payloads)

    def wait(self, n_results, timeout=60.0):
        """Block until ``n_results`` results arrived (or timeout)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                self._reap_dead()
                if len(self.results) >= n_results:
                    return list(self.results)
            time.sleep(0.05)
        raise TimeoutError("only %d/%d results" %
                           (len(self.results), n_results))

    def _adaptive_timeout(self):
        """max(mean + 3σ of history, job_timeout) — ``server.py:619-629``."""
        if self.job_timeout is None and len(self.job_times) < 3:
            return None
        if self.job_times:
            import statistics
            mean = statistics.mean(self.job_times)
            sd = statistics.pstdev(self.job_times)
            adaptive = mean + 3 * sd
            return max(adaptive, self.job_timeout or 0.0)
        return self.job_timeout

    def _reap_dead(self):
        """Requeue jobs of slaves that stopped heartbeating/overran."""
        now = time.time()
        timeout = self._adaptive_timeout()
        for sid, slave in list(self.slaves.items()):
            dead = now - slave.last_seen > self.heartbeat_timeout
            overrun = (timeout is not None and slave.current_job and
                       now - slave.current_job[1] > timeout)
            if dead or overrun:
                self.warning("dropping slave %s (%s)", sid,
                             "dead" if dead else "job timeout")
                self.drop_slave(sid)

    def drop_slave(self, sid):
        slave = self.slaves.pop(sid, None)
        if slave is not None and slave.current_job is not None:
            self.jobs.insert(0, slave.current_job[0])  # requeue first
            slave.current_job = None

    # -- wire --------------------------------------------------------------

    def _accept_loop(self):
        while self._accepting:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(sock,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, sock):
        proto = Protocol(sock)
        sid = None
        try:
            hello = proto.recv()
            if hello.get("cmd") == "hb_attach":
                # dedicated heartbeat channel: keeps last_seen fresh even
                # while the main channel is busy executing a long job
                self._serve_heartbeats(proto, hello.get("id"))
                return
            if hello.get("cmd") != "handshake":
                proto.send({"error": "expected handshake"})
                return
            if hello.get("checksum") != self.checksum:
                # reject incompatible workflow topology
                proto.send({"error": "checksum mismatch",
                            "expected": self.checksum})
                return
            sid = str(uuid.uuid4())[:8]
            with self._lock:
                self.slaves[sid] = SlaveDescription(
                    sid, hello.get("power", 1.0), hello.get("mid"),
                    hello.get("pid"))
            proto.send({"id": sid, "log_id": sid})
            while not self._done.is_set():
                msg = proto.recv()
                cmd = msg.get("cmd")
                # compute the reply under the lock, send OUTSIDE it — a
                # slow-reading peer must not stall the whole control plane
                with self._lock:
                    slave = self.slaves.get(sid)
                    if slave is None:
                        reply, stop = {"error": "dropped"}, True
                    else:
                        slave.last_seen = time.time()
                        stop = False
                        if cmd == "job":
                            if self.jobs:
                                payload = self.jobs.pop(0)
                                slave.current_job = (payload, time.time())
                                slave.state = "WORK"
                                reply = {"job": payload}
                            else:
                                slave.state = "IDLE"
                                reply = {"job": None}
                        elif cmd == "result":
                            if slave.current_job is not None:
                                self.job_times.append(
                                    time.time() - slave.current_job[1])
                            slave.current_job = None
                            slave.jobs_done += 1
                            slave.state = "WAIT"
                            self.results.append(msg.get("data"))
                            reply = {"ok": True}
                        elif cmd == "heartbeat":
                            slave.power = msg.get("power", slave.power)
                            reply = {"ok": True}
                        else:
                            reply = {"error": "unknown cmd %r" % cmd}
                proto.send(reply)
                if stop:
                    return
        except (ConnectionError, json.JSONDecodeError, OSError):
            pass
        finally:
            if sid is not None:
                with self._lock:
                    self.drop_slave(sid)
            proto.close()

    def _serve_heartbeats(self, proto, sid):
        proto.send({"ok": sid in self.slaves})
        while not self._done.is_set():
            msg = proto.recv()
            with self._lock:
                slave = self.slaves.get(sid)
                if slave is None:
                    reply, stop = {"error": "dropped"}, True
                else:
                    slave.last_seen = time.time()
                    slave.power = msg.get("power", slave.power)
                    reply, stop = {"ok": True}, False
            proto.send(reply)
            if stop:
                return

    def stop(self):
        self._accepting = False
        self._done.set()
        try:
            self._listener.close()
        except OSError:
            pass


class CoordinatorClient(Logger):
    """Slave: pulls jobs, executes a callback, pushes results."""

    def __init__(self, address, checksum="", power=1.0,
                 death_probability=0.0, rand="chaos",
                 heartbeat_interval=2.0):
        super(CoordinatorClient, self).__init__()
        self.address = tuple(address)
        self.checksum = checksum
        self.power = power
        self.death_probability = death_probability
        self.heartbeat_interval = heartbeat_interval
        self._rand = prng.get(rand)
        self.id = None
        self.jobs_done = 0
        self._hb_stop = threading.Event()

    def connect(self):
        sock = socket.create_connection(self.address, timeout=10.0)
        self.proto = Protocol(sock)
        import os
        self.proto.send({"cmd": "handshake", "checksum": self.checksum,
                         "power": self.power,
                         "mid": hex(uuid.getnode()), "pid": os.getpid()})
        reply = self.proto.recv()
        if "error" in reply:
            raise ConnectionError(reply["error"])
        self.id = reply["id"]
        # dedicated heartbeat channel so long handler() runs don't get
        # this slave declared dead mid-job
        hb_sock = socket.create_connection(self.address, timeout=10.0)
        self._hb_proto = Protocol(hb_sock)
        self._hb_proto.send({"cmd": "hb_attach", "id": self.id})
        self._hb_proto.recv()
        t = threading.Thread(target=self._hb_loop, daemon=True,
                             name="slave-heartbeat-%s" % self.id)
        t.start()
        return self

    def _hb_loop(self):
        while not self._hb_stop.wait(self.heartbeat_interval):
            try:
                self._hb_proto.send({"cmd": "heartbeat",
                                     "power": self.power})
                self._hb_proto.recv()
            except (ConnectionError, OSError):
                return

    def serve_forever(self, handler, idle_sleep=0.05, max_idle=None):
        """Pull/execute/push until the queue stays empty (or forever)."""
        idle = 0
        while True:
            self.proto.send({"cmd": "job"})
            reply = self.proto.recv()
            job = reply.get("job")
            if job is None:
                idle += 1
                if max_idle is not None and idle >= max_idle:
                    return self.jobs_done
                time.sleep(idle_sleep)
                continue
            idle = 0
            if self.death_probability and \
                    self._rand.rand() < self.death_probability:
                # chaos: die mid-job without reporting (--slave-death-
                # probability parity) — the master must requeue
                self.proto.close()
                raise RuntimeError("chaos death")
            result = handler(job)
            self.proto.send({"cmd": "result", "data": result})
            self.proto.recv()
            self.jobs_done += 1

    def heartbeat(self):
        self.proto.send({"cmd": "heartbeat", "power": self.power})
        self.proto.recv()

    def close(self):
        self._hb_stop.set()
        self.proto.close()
        if hasattr(self, "_hb_proto"):
            self._hb_proto.close()
