"""Host-side coordination service: the control plane that survives.

On TPU the *data* plane of the reference's distributed runtime became
XLA collectives (see :mod:`veles_tpu.parallel.dp`); what remains is the
*control* plane the reference ran over Twisted TCP JSON lines
(``veles/server.py``, ``veles/client.py``, ``network_common.py:132``):

* handshake with workflow **checksum** verification (a slave running a
  different graph is rejected — ``server.py:484-492``);
* slave registry with per-slave FSM (WAIT→WORK→...), ``computing_power``
  load metric, heartbeats with timeout-based **death detection**;
* a generic **job queue** for task farming (genetics chromosomes,
  ensemble members, dataset shards): jobs held by a dead slave are
  **requeued** (``loader/base.py:679-687`` semantics);
* **chaos injection**: ``death_probability`` makes a slave kill itself
  mid-job (the reference's ``--slave-death-probability``,
  ``client.py:303-307``) so elasticity is testable in-process.

Implementation is stdlib sockets + threads (no Twisted): JSON lines,
one reader thread per connection on the master, a single client thread
on the slave. Job payloads must be JSON-serializable.
"""

import collections
import hmac
import json
import os
import secrets
import socket
import threading
import time
import uuid

from veles_tpu import prng
from veles_tpu.envknob import env_flag, env_knob
from veles_tpu.logger import Logger
from veles_tpu.parallel import wire
from veles_tpu.telemetry import federation, health, tracing
from veles_tpu.telemetry.registry import get_registry


def _blob_len(data):
    """bytes or :class:`wire.Chunks` -> payload length."""
    return data.nbytes if isinstance(data, wire.Chunks) else len(data)


#: shm segment names CREATED by this process (Protocol senders, the
#: same-host challenge). An in-process peer (master+slave in one
#: process: tests, the dryrun) that attaches to one of these must NOT
#: deregister it from the resource tracker — register/unregister is a
#: plain set in the tracker, so the receiver's unregister would erase
#: the OWNER's registration and the owner's later unlink would
#: double-unregister, spraying ``KeyError: '/psm_...'`` tracebacks
#: from the tracker process at teardown (VERDICT r5 weak #2).
_OWNED_SHM = set()
_OWNED_SHM_LOCK = threading.Lock()


def _own_segment(seg):
    with _OWNED_SHM_LOCK:
        _OWNED_SHM.add(seg._name)
    return seg


def _disown_segment(seg):
    with _OWNED_SHM_LOCK:
        _OWNED_SHM.discard(seg._name)


def _unregister_foreign(seg):
    """Drop the tracker registration CPython adds on every attach —
    the sender owns the segment — unless this very process is the
    sender (in-process peer), whose registration must survive for its
    own unlink."""
    with _OWNED_SHM_LOCK:
        if seg._name in _OWNED_SHM:
            return
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:
        pass


class NoMoreJobsError(Exception):
    """Raised by a ``job_source`` when the workflow ran out of work."""


class Protocol(object):
    """JSON control line + length-prefixed binary frames, with an
    optional same-host shared-memory fast path.

    ``bytes`` values anywhere in a message ride AFTER the JSON line as
    raw frames (8-byte big-endian length prefix) — the reference's
    txzmq streamed pickles the same way (``txzmq/connection.py:283-339``)
    instead of inflating them 33% through base64. The JSON line carries
    ``{"__bin__": i}`` placeholders in traversal order.

    When both peers share a machine (``enable_sharedio()`` after the
    handshake's nonce-proven same-host check), large payloads go
    through ONE sender-owned ``multiprocessing.shared_memory`` segment
    — the socket carries only ``{"__shm__": name, "off": o, "size": n}``.
    The segment is reused across messages and regrown on demand: the
    re-design of the reference's ``txzmq/sharedio.py:44-106`` + the
    IOOverflow regrow (``server.py:156-167``). Safe because a segment
    is never rewritten while the peer still reads it (request↔reply,
    or the bounded-pipeline discipline of the slave protocol where the
    reply to the message that carried a ref arrives before reuse).
    """

    #: blobs below this stay inline (shm setup isn't free)
    SHM_THRESHOLD = 64 * 1024
    #: refuse binary frames beyond this (hostile length prefix) —
    #: 256 MiB default; raise per-instance for genuinely huge models
    MAX_FRAME = 1 << 28
    #: refuse messages whose binary frames sum beyond this: a single
    #: JSON line full of placeholders must not buffer unbounded memory
    #: before any authentication ran
    MAX_MESSAGE = 1 << 30
    #: cap on the JSON control line itself (readline would otherwise
    #: buffer a newline-free stream unboundedly); generous because the
    #: legacy path may inline sub-64KB "blob" strings in the JSON
    MAX_LINE = 1 << 24

    def __init__(self, sock, max_frame=None):
        if max_frame is not None:
            # genuinely huge models (a full VGG-scale parameter pickle
            # is >268 MB) raise the cap per-connection; the message cap
            # scales with it
            self.MAX_FRAME = max_frame
            self.MAX_MESSAGE = max(4 * max_frame, Protocol.MAX_MESSAGE)
            self.MAX_LINE = max(max_frame, Protocol.MAX_LINE)
        self.sock = sock
        self._file = sock.makefile("rwb")
        self._wlock = threading.Lock()
        self._rlock = threading.Lock()
        self._shm_tx = False
        self._shm_rx = False
        # double-buffered: with the pipelined slave protocol up to TWO
        # of this sender's messages can be unread at the peer, so
        # consecutive sends must not share a segment (send i+2 reuses
        # send i's slot, which the bounded pipeline guarantees is read)
        self._segments = [None, None]
        self._seg_turn = 0
        self.shm_sends = 0
        self.shm_reads = 0
        self.shm_regrows = 0

    # -- sharedio ----------------------------------------------------------

    def enable_sharedio(self):
        """Opt in after the handshake's same-host proof. Both
        directions: sending offloads blobs, and receiving will
        dereference ``__shm__`` refs — a protocol that never enabled
        sharedio (remote peer, feed sockets) treats such refs as plain
        data, so untrusted input cannot make us attach to arbitrary
        local segments."""
        self._shm_tx = True
        self._shm_rx = True

    def _segment_for(self, size):
        from multiprocessing import shared_memory
        turn = self._seg_turn
        self._seg_turn = (turn + 1) % len(self._segments)
        seg = self._segments[turn]
        if seg is not None and seg.size >= size:
            return seg
        if seg is not None:  # regrow
            seg.close()
            seg.unlink()
            _disown_segment(seg)
            self.shm_regrows += 1
        # 25% slack so payloads whose size oscillates between cycles
        # (delta pushes vs full pushes, varying batch counts) reuse the
        # segment instead of regrowing every other send
        seg = _own_segment(shared_memory.SharedMemory(
            create=True,
            size=max(size + (size >> 2), self.SHM_THRESHOLD)))
        self._segments[turn] = seg
        return seg

    # -- send path ---------------------------------------------------------

    def _pack(self, value, bins, shm_items):
        """Transform a message for the wire: bytes → binary-frame or
        shm markers; legacy big-str ``"blob"`` values → shm (utf-8).
        shm candidates are only *collected* here (two-pass: the segment
        must be sized for ALL of a message's blobs before writing — a
        regrow between writes would unlink bytes an earlier ref still
        points to); the caller fills the placeholder dicts after.

        A user dict that happens to look like one of our markers
        (``{"__bin__": int}`` alone, or containing ``__shm__`` /
        ``__esc__``) is wrapped in ``{"__esc__": ...}`` so the receiver
        never mistakes payload data for a frame/segment reference."""
        if isinstance(value, (bytes, wire.Chunks)):
            # Chunks (scatter/gather array payloads, wire.encode_chunks)
            # behave exactly like bytes on the wire: the shm path
            # memcpys each part straight into the segment and the frame
            # path writes them back-to-back under one length prefix —
            # either way the peer receives one contiguous blob
            if self._shm_tx and _blob_len(value) >= self.SHM_THRESHOLD:
                ref = {}
                shm_items.append((ref, value, "b"))
                return ref
            bins.append(value)
            return {"__bin__": len(bins) - 1}
        if isinstance(value, dict):
            out = {}
            for key, item in value.items():
                if key == "blob" and isinstance(item, str) and \
                        self._shm_tx and len(item) >= self.SHM_THRESHOLD:
                    ref = {}
                    shm_items.append((ref, item.encode("utf-8"), "s"))
                    out[key] = ref
                else:
                    out[key] = self._pack(item, bins, shm_items)
            if self._collides(value):
                return {"__esc__": out}
            return out
        if isinstance(value, (list, tuple)):
            return [self._pack(item, bins, shm_items) for item in value]
        return value

    @staticmethod
    def _collides(value):
        """True if a raw user dict would read back as a wire marker."""
        return ("__shm__" in value or "__esc__" in value or
                ("__bin__" in value and len(value) == 1 and
                 type(value["__bin__"]) is int))

    @staticmethod
    def _is_bin_marker(value):
        return ("__bin__" in value and len(value) == 1 and
                type(value["__bin__"]) is int)

    def send(self, message):
        # pack + write under the write lock: the shared segment must not
        # be overwritten while a previous ref is still in flight
        with self._wlock:
            bins = []
            shm_items = []
            message = self._pack(message, bins, shm_items)
            if shm_items:
                # 64-byte-align every blob so OOB array views decoded
                # straight from the segment land cacheline-aligned
                total = 0
                for _, data, _ in shm_items:
                    total += (-total) % 64 + _blob_len(data)
                seg = self._segment_for(total)
                offset = 0
                for ref, data, kind in shm_items:
                    offset += (-offset) % 64
                    size = _blob_len(data)
                    if isinstance(data, wire.Chunks):
                        pos = offset
                        for part in data.parts:
                            seg.buf[pos:pos + len(part)] = part
                            pos += len(part)
                    else:
                        seg.buf[offset:offset + size] = data
                    ref.update({"__shm__": seg.name, "off": offset,
                                "size": size, "kind": kind})
                    offset += size
                    self.shm_sends += 1
            self._file.write((json.dumps(message) + "\n").encode())
            for data in bins:
                self._file.write(_blob_len(data).to_bytes(8, "big"))
                if isinstance(data, wire.Chunks):
                    for part in data.parts:
                        self._file.write(part)
                else:
                    self._file.write(data)
            self._file.flush()

    # -- receive path ------------------------------------------------------

    def _read_exact(self, n):
        data = self._file.read(n)
        if data is None or len(data) != n:
            raise ConnectionError("peer closed mid-frame")
        return data

    @classmethod
    def _count_bins(cls, value):
        if isinstance(value, dict):
            if cls._is_bin_marker(value):
                return 1
            if "__esc__" in value and len(value) == 1 and \
                    isinstance(value["__esc__"], dict):
                # escaped user dict: its top-level shape is data, but
                # its values may hold genuine markers
                return sum(cls._count_bins(v)
                           for v in value["__esc__"].values())
            return sum(cls._count_bins(v) for v in value.values())
        if isinstance(value, list):
            return sum(cls._count_bins(v) for v in value)
        return 0

    def _unpack(self, value, bins):
        if isinstance(value, dict):
            if self._is_bin_marker(value):
                i = value["__bin__"]
                if not 0 <= i < len(bins):
                    raise ConnectionError(
                        "binary frame index %d out of range" % i)
                return bins[i]
            if "__esc__" in value and len(value) == 1 and \
                    isinstance(value["__esc__"], dict):
                return {k: self._unpack(v, bins)
                        for k, v in value["__esc__"].items()}
            if "__shm__" in value and self._shm_rx:
                self.shm_reads += 1
                return self._read_shm_ref(value)
            return {k: self._unpack(v, bins) for k, v in value.items()}
        if isinstance(value, list):
            return [self._unpack(v, bins) for v in value]
        return value

    @staticmethod
    def _read_shm_ref(value):
        from multiprocessing import shared_memory
        try:
            seg = shared_memory.SharedMemory(name=value["__shm__"])
        except (OSError, ValueError) as e:
            raise ConnectionError("stale sharedio ref: %s" % e)
        # CPython's SharedMemory registers every attach with THIS
        # process's resource tracker, which would unlink the sender's
        # live segment when we exit — deregister (unless this process
        # IS the sender: an in-process peer must not erase the owner's
        # registration)
        _unregister_foreign(seg)
        try:
            off = int(value.get("off", 0))
            size = int(value["size"])
            if off < 0 or size < 0 or off + size > seg.size:
                # stale ref after a regrow, or a hostile peer: a silent
                # slice-truncation would hand a corrupt blob to the
                # decoder instead of failing here
                raise ConnectionError(
                    "sharedio ref out of bounds: off=%d size=%d "
                    "segment=%d" % (off, size, seg.size))
            raw = bytes(seg.buf[off:off + size])
        finally:
            seg.close()  # sender owns the segment; never unlink
        return raw.decode("utf-8") if value.get("kind") == "s" else raw

    def recv(self):
        with self._rlock:
            # bounded readline: an unauthenticated peer streaming an
            # endless newline-free "line" must not buffer unbounded
            # memory before json/auth ever run
            line = self._file.readline(self.MAX_LINE + 1)
            if not line:
                raise ConnectionError("peer closed")
            if not line.endswith(b"\n"):
                if len(line) > self.MAX_LINE:
                    raise ConnectionError(
                        "control line exceeds %d bytes" % self.MAX_LINE)
                raise ConnectionError("peer closed mid-line")
            message = json.loads(line)
            bins = []
            total = 0
            for _ in range(self._count_bins(message)):
                n = int.from_bytes(self._read_exact(8), "big")
                if n > self.MAX_FRAME:
                    raise ConnectionError("oversized frame (%d)" % n)
                total += n
                if total > self.MAX_MESSAGE:
                    raise ConnectionError(
                        "message exceeds %d bytes" % self.MAX_MESSAGE)
                bins.append(self._read_exact(n))
        return self._unpack(message, bins)

    def close(self):
        try:
            self._file.close()
            self.sock.close()
        except OSError:
            pass
        for i, seg in enumerate(self._segments):
            if seg is not None:
                try:
                    seg.close()
                    seg.unlink()
                except (OSError, FileNotFoundError):
                    pass
                _disown_segment(seg)
                self._segments[i] = None


def _prove_same_host(proto):
    """Server side of the same-host challenge.

    The client's machine-id is self-reported (a guessable MAC-derived
    value the server also discloses), so it must never gate the shm
    fast path by itself: a remote peer spoofing it could make the
    master attach to arbitrary named local segments. Instead the master
    writes a random nonce into a segment IT owns and asks the peer to
    echo it — readable only by a process on the same machine.
    """
    from multiprocessing import shared_memory
    raw = secrets.token_bytes(32)
    try:
        seg = _own_segment(shared_memory.SharedMemory(create=True,
                                                      size=64))
    except OSError:
        return False
    try:
        seg.buf[:len(raw)] = raw
        proto.send({"shm_challenge": seg.name, "nonce_len": len(raw)})
        answer = proto.recv()
        proof = answer.get("proof") if isinstance(answer, dict) else None
        expected = hmac.new(raw, b"veles-shm-proof",
                            "sha256").hexdigest()
        return isinstance(proof, str) and \
            hmac.compare_digest(proof, expected)
    except (ConnectionError, OSError):
        return False
    finally:
        try:
            seg.close()
            seg.unlink()
        except OSError:
            pass
        _disown_segment(seg)


def _answer_same_host(proto, challenge):
    """Client side: prove we can read the master's nonce segment.

    The answer is an HMAC keyed by the segment's bytes, never the bytes
    themselves — a fake master naming some OTHER process's segment in
    its challenge must not turn this into an arbitrary-shm-read oracle
    (the server would receive only a keyed digest of that segment's
    prefix, not its contents). A peer that cannot attach (different
    machine, or shm unavailable) answers ``None`` and the fast path
    stays off — plain socket framing still works."""
    from multiprocessing import shared_memory
    name = challenge.get("shm_challenge")
    n = int(challenge.get("nonce_len", 0))
    proof = None
    if isinstance(name, str) and 0 < n <= 64:
        try:
            seg = shared_memory.SharedMemory(name=name)
        except (OSError, ValueError):
            seg = None
        if seg is not None:
            _unregister_foreign(seg)
            try:
                raw = bytes(seg.buf[:min(n, seg.size)])
                proof = hmac.new(raw, b"veles-shm-proof",
                                 "sha256").hexdigest()
            finally:
                seg.close()
    return {"cmd": "shm_proof", "proof": proof}


class SlaveDescription(object):
    """Master-side view of one slave (``veles/server.py:494-511``)."""

    def __init__(self, sid, power, mid, pid):
        self.id = sid
        self.power = power
        self.mid = mid
        self.pid = pid
        self.state = "WAIT"
        self.jobs_done = 0
        self.last_seen = time.time()
        #: jobs handed out and not yet resolved, oldest first — the
        #: pipelined slave protocol keeps up to MAX_IN_FLIGHT open
        #: (the reference's balance counter, ``server.py:377-398``)
        self.jobs_in_flight = []
        #: proven same-host (payload codec decisions read this)
        self.sharedio = False
        # True while result_sink is merging this slave's update: the
        # reaper must not drop/requeue mid-merge (double training)
        self.applying = False
        # clean-exit markers: the server replied done=True, or the
        # client announced a voluntary exit ({"cmd": "bye"}) — a
        # connection dying WITHOUT either mid-run is a crash
        self.done_sent = False
        self.said_bye = False

    @property
    def current_job(self):
        return self.jobs_in_flight[0] if self.jobs_in_flight else None


class CoordinatorServer(Logger):
    """Master: accepts slaves, verifies checksum, farms jobs out.

    A slave may hold up to :attr:`MAX_IN_FLIGHT` unresolved jobs — the
    async pipelining of the reference (``client.py:433-437`` overlaps
    the update upload with the next job fetch; the server's balance
    counter ``server.py:377-398`` bounds the run-ahead)."""

    MAX_IN_FLIGHT = 2

    #: overrun floor for a slave's FIRST jobs: they absorb its XLA
    #: compile (segment shapes it has never seen — e.g. the varied
    #: batch counts a mid-epoch resume replays), which the adaptive
    #: mean+3σ of the WARM fleet's job history knows nothing about.
    #: Without the floor, a master restarted onto a warm history drops
    #: every rejoining slave mid-first-compile and the fleet churns.
    WARMUP_JOBS = 2
    WARMUP_TIMEOUT = 180.0

    def __init__(self, address=("127.0.0.1", 0), checksum="",
                 job_timeout=None, heartbeat_timeout=10.0,
                 job_source=None, result_sink=None, on_drop=None,
                 initial_data_source=None, secret=None, max_frame=None,
                 on_slave_flight=None, straggler_drop_s=None):
        super(CoordinatorServer, self).__init__()
        self.checksum = checksum
        self.max_frame = max_frame
        #: reaction layer on the PR 9 detection substrate: a slave the
        #: HealthScorer has held in ``straggler`` state for this many
        #: seconds is dropped and its in-flight jobs requeued to the
        #: healthy fleet (None = detect-and-alert only). The dropped
        #: slave's connection closes on its NEXT request ({"error":
        #: "dropped"}), after which it may rejoin immediately through
        #: the elastic-join path with a clean health slate — pair the
        #: grace with detection long enough that a still-slow
        #: rejoiner is re-flagged rather than flapping the fleet.
        self.straggler_drop_s = straggler_drop_s
        #: shared secret: when set, every connection (jobs AND
        #: heartbeats) must complete a mutual HMAC challenge before any
        #: payload is accepted — the role of nothing in the reference,
        #: which trusted the network (``veles/server.py:484``)
        self.secret = secret.encode() if isinstance(secret, str) else secret
        self.job_timeout = job_timeout
        self.heartbeat_timeout = heartbeat_timeout
        # dynamic mode (master/slave training): when the static queue is
        # empty, jobs come from job_source(slave) and results go to
        # result_sink(data, slave) — the reference's per-slave
        # generate_data_for_slave / apply_data_from_slave dispatch
        # (``server.py:596-611``, ``server.py:401-414``).
        self.job_source = job_source
        self.result_sink = result_sink
        self.on_drop = on_drop
        # optional: payload delivered in the handshake reply so
        # negotiates_on_connect units get the MASTER's state
        # (``workflow.py:587-594`` generate_initial_data_for_slave)
        self.initial_data_source = initial_data_source
        self.no_more_jobs = False
        #: ONE trace id for the whole distributed run, handed to every
        #: slave in the handshake reply so master and slave spans land
        #: on a single correlated timeline (--trace-out)
        self.trace_id = uuid.uuid4().hex[:16]
        registry = get_registry()
        self._m_rtt_ms = registry.histogram(
            "veles_slave_heartbeat_rtt_ms",
            "Heartbeat round-trip as measured by the slave, "
            "aggregated here", labels=("slave",))
        self._m_job_ms = registry.histogram(
            "veles_slave_job_ms",
            "Per-job wall time from hand-out to result", labels=("slave",))
        self._m_source_ms = registry.histogram(
            "veles_job_source_ms",
            "Master time generating one job payload", labels=("slave",))
        self._m_sink_ms = registry.histogram(
            "veles_result_sink_ms",
            "Master time merging one slave update", labels=("slave",))
        self._m_jobs = registry.counter(
            "veles_jobs_total", "Jobs resolved per slave",
            labels=("slave",))
        self._m_drops = registry.counter(
            "veles_slave_drops_total", "Slaves dropped (death/timeout)")
        #: the recovery plane's own series (ISSUE 12): how many jobs
        #: membership churn forced back onto the queue, how many
        #: slaves (re)joined, and how long the fleet took to make
        #: progress again after a fault
        self._m_requeued = registry.counter(
            "veles_jobs_requeued_total",
            "In-flight jobs requeued after a slave was dropped",
            labels=("reason",))
        self._m_joins = registry.counter(
            "veles_slave_joins_total",
            "Successful slave handshakes", labels=("kind",))
        self._m_recovery_ms = registry.histogram(
            "veles_recovery_ms",
            "Fault detection to training progress resumed",
            labels=("event",))
        #: wall time of the oldest unrecovered requeue (the next
        #: resolved result closes it into veles_recovery_ms)
        self._recovery_mark = None
        self._jobs_handed = False
        self._m_hb_handler_ms = registry.histogram(
            "veles_heartbeat_handler_ms",
            "Master time absorbing one heartbeat's telemetry piggyback")
        self._m_flight_notices = registry.counter(
            "veles_cluster_flight_notices_total",
            "Flight-record notices received from slaves",
            labels=("slave",))
        #: the cluster observability plane (ISSUE 9): slave snapshot
        #: deltas merge here, the scorer rates slaves against peers,
        #: and on_slave_flight(sid, notice) fires when a slave's
        #: flight recorder trips (the launcher dumps a cluster record)
        self.federation = federation.get_federation()
        self.federation.set_run_info(trace_id=self.trace_id)
        self.health = health.get_scorer()
        self.on_slave_flight = on_slave_flight
        self.slaves = {}
        self.jobs = []                 # pending job payloads
        self.results = []
        self.job_times = []            # history for adaptive timeout
        self._lock = threading.Lock()
        self._results_cv = threading.Condition(self._lock)
        self._done = threading.Event()
        self._listener = self._bind_listener(address)
        self.address = self._listener.getsockname()
        self._threads = []
        self._accepting = True
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="coordinator-accept")
        t.start()
        self._threads.append(t)
        # independent reaper: death detection must not depend on the
        # master happening to sit in wait()
        r = threading.Thread(target=self._reap_loop, daemon=True,
                             name="coordinator-reaper")
        r.start()
        self._threads.append(r)

    @staticmethod
    def _bind_listener(address, retry_s=5.0):
        """Bind, riding out a transient EADDRINUSE on an EXPLICIT
        port: a master restarted onto its advertised address races
        its predecessor's dying sockets for a moment (auto-resume,
        ISSUE 12). A random port (0) never conflicts and a port held
        by a genuinely different service still fails within
        ``retry_s``."""
        import errno
        address = tuple(address)
        deadline = time.monotonic() + retry_s
        while True:
            try:
                return socket.create_server(address)
            except OSError as e:
                if e.errno != errno.EADDRINUSE or not address[1] or \
                        time.monotonic() >= deadline:
                    raise
            time.sleep(0.25)

    def _reap_loop(self):
        while not self._done.wait(min(self.heartbeat_timeout / 4, 1.0)):
            with self._lock:
                self._reap_dead()
            # periodic cluster scoring even when no heartbeat arrives
            # (a fully-silent fleet must still be re-scored), and the
            # SLO sweep — both internally throttled and lock-free
            # w.r.t. self._lock
            self.health.evaluate()
            try:
                from veles_tpu.telemetry import alerts
                alerts.get_engine().evaluate()
            except Exception:
                self.warning("alert sweep failed", exc_info=True)

    # -- job management ----------------------------------------------------

    def submit(self, *payloads):
        with self._lock:
            self.jobs.extend(payloads)

    def wait(self, n_results, timeout=60.0):
        """Block until ``n_results`` results arrived (or timeout).

        Sleeps on a condition variable notified by the result path (the
        reaper thread handles death detection independently); the 1 s
        wake cap only bounds clock drift, not latency."""
        deadline = time.time() + timeout
        with self._results_cv:
            while len(self.results) < n_results:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError("only %d/%d results" %
                                       (len(self.results), n_results))
                self._results_cv.wait(min(remaining, 1.0))
            return list(self.results)

    def _adaptive_timeout(self):
        """max(mean + 3σ of history, job_timeout) — ``server.py:619-629``."""
        if self.job_timeout is None and len(self.job_times) < 3:
            return None
        if self.job_times:
            import statistics
            mean = statistics.mean(self.job_times)
            sd = statistics.pstdev(self.job_times)
            adaptive = mean + 3 * sd
            return max(adaptive, self.job_timeout or 0.0)
        return self.job_timeout

    def _reap_dead(self):
        """Requeue jobs of slaves that stopped heartbeating/overran,
        plus (with ``straggler_drop_s``) slaves the health scorer has
        held in ``straggler`` state past the grace window."""
        now = time.time()
        timeout = self._adaptive_timeout()
        for sid, slave in list(self.slaves.items()):
            if slave.applying:
                # its result already arrived and is being merged — a
                # drop now would requeue a minibatch that IS trained
                continue
            dead = now - slave.last_seen > self.heartbeat_timeout
            slave_timeout = timeout
            if slave_timeout is not None and \
                    slave.jobs_done < self.WARMUP_JOBS:
                slave_timeout = max(slave_timeout, self.WARMUP_TIMEOUT)
            overrun = (slave_timeout is not None and slave.current_job and
                       now - slave.current_job[1] > slave_timeout)
            if dead or overrun:
                self._drop_faulted(sid, "dead" if dead else "timeout")
        if self.straggler_drop_s is None:
            return
        for sid, row in self.health.table().items():
            slave = self.slaves.get(sid)
            if slave is None or slave.applying:
                continue
            if row["state"] == "straggler" and \
                    row["state_age_s"] >= self.straggler_drop_s:
                self._drop_faulted(sid, "straggler")

    def _drop_faulted(self, sid, reason):
        """Drop a FAULTED slave (dead/timeout/straggler): counted as a
        drop, its labeled series GC'd, its jobs requeued under
        ``reason``. Clean end-of-run disconnects never come through
        here — they keep their series for the final snapshot."""
        self.warning("dropping slave %s (%s)", sid, reason)
        self._m_drops.inc()
        # a FAULTED slave's labeled series go too (clean disconnects
        # keep theirs — end-of-run snapshots still want them): a
        # churny run replacing slaves for hours must not grow
        # {slave=...} cardinality without bound
        for family in (self._m_rtt_ms, self._m_job_ms,
                       self._m_source_ms, self._m_sink_ms,
                       self._m_jobs, self._m_flight_notices):
            family.remove(slave=sid)
        # the launcher-owned exchange families are slave-labeled too;
        # reach them by name (a static-farming server without a
        # launcher simply has none)
        registry = get_registry()
        for name in ("veles_exchange_bytes_total",
                     "veles_exchange_encode_ms",
                     "veles_exchange_decode_ms"):
            family = registry.get(name)
            if family is not None and "slave" in family.label_names:
                family.remove(slave=sid)
        self.drop_slave(sid, reason=reason)

    def drop_slave(self, sid, reason="disconnect"):
        """Unregister a slave and requeue its in-flight jobs. Caller
        holds ``self._lock`` (the reaper and the serve loop both
        enter here under it)."""
        slave = self.slaves.pop(sid, None)
        if slave is not None:
            # the federated feed and health row describe a LIVE slave:
            # GC them on every drop, clean or not
            self.federation.remove_slave(sid)
            self.health.remove(sid)
            if slave.jobs_in_flight:
                self._m_requeued.labels(reason=reason).inc(
                    len(slave.jobs_in_flight))
                if self._recovery_mark is None:
                    # closed by the next resolved result: the time the
                    # epoch could not make progress because of this
                    # fault (veles_recovery_ms{event="requeue"})
                    self._recovery_mark = time.time()
                if self.on_drop is None:
                    # static job farming: requeue the raw payloads
                    # (oldest first keeps the original order)
                    for payload, _ in reversed(slave.jobs_in_flight):
                        self.jobs.insert(0, payload)
                slave.jobs_in_flight = []
            if self.on_drop is not None:
                # dynamic mode: the workflow owns requeueing (e.g. the
                # Loader moves pending minibatches to failed_minibatches
                # and re-serves them) — re-inserting the stale payload
                # here too would train the minibatch twice
                self.on_drop(slave)

    # -- wire --------------------------------------------------------------

    def _accept_loop(self):
        while self._accepting:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            # reap finished connection threads so long-lived masters
            # with churning slaves don't grow the list unboundedly
            self._threads = [x for x in self._threads if x.is_alive()]
            t = threading.Thread(target=self._serve, args=(sock,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _authenticate(self, proto, hello):
        """Mutual HMAC challenge gating every connection when a shared
        secret is configured.

        The master proves itself FIRST (HMAC over the client's nonce)
        so a slave never answers a rogue master's challenge, then the
        client proves itself over the master's nonce. Without this
        gate, anyone who can reach the port could drive the job/result
        protocol (and pre-restricted-unpickler, execute code)."""
        if self.secret is None:
            return True
        client_nonce = hello.get("nonce")
        if not isinstance(client_nonce, str) or not client_nonce:
            return False
        server_nonce = secrets.token_hex(32)
        proto.send({"auth": server_nonce,
                    "proof": hmac.new(
                        self.secret, ("m" + client_nonce).encode(),
                        "sha256").hexdigest()})
        try:
            answer = proto.recv()
        except (ConnectionError, OSError, json.JSONDecodeError):
            return False
        expected = hmac.new(self.secret, ("s" + server_nonce).encode(),
                            "sha256").hexdigest()
        got = answer.get("proof") if isinstance(answer, dict) else None
        return isinstance(got, str) and hmac.compare_digest(got, expected)

    def _serve(self, sock):
        proto = Protocol(sock, max_frame=self.max_frame)
        sid = None
        try:
            hello = proto.recv()
            if not isinstance(hello, dict) or \
                    hello.get("cmd") not in ("handshake", "hb_attach"):
                proto.send({"error": "expected handshake"})
                return
            if not self._authenticate(proto, hello):
                proto.send({"error": "authentication failed"})
                return
            if hello.get("cmd") == "hb_attach":
                # dedicated heartbeat channel: keeps last_seen fresh even
                # while the main channel is busy executing a long job
                self._serve_heartbeats(proto, hello.get("id"))
                return
            if hello.get("checksum") != self.checksum:
                # reject incompatible workflow topology; the expected
                # value is deliberately NOT echoed (it doubles as a
                # handshake credential for job/result access)
                proto.send({"error": "checksum mismatch"})
                return
            sid = str(uuid.uuid4())[:8]
            with self._lock:
                self.slaves[sid] = SlaveDescription(
                    sid, hello.get("power", 1.0), hello.get("mid"),
                    hello.get("pid"))
                slave_desc = self.slaves[sid]
            # same machine → job/update blobs ride shared memory, only
            # the refs cross the socket (endpoint-by-locality, the
            # reference's server.py:721-732 inproc/ipc/tcp choice).
            # The self-reported mid only *nominates* the fast path; it
            # is proven with an unforgeable challenge: a random nonce
            # written to a master-owned shm segment that only a genuine
            # same-host peer can read back.
            sharedio = False
            if hello.get("mid") == hex(uuid.getnode()):
                sharedio = _prove_same_host(proto)
            slave_desc.sharedio = sharedio
            reply = {"id": sid, "log_id": sid, "sharedio": sharedio,
                     "mid": hex(uuid.getnode()), "trace": self.trace_id}
            if self.initial_data_source is not None:
                reply["data"] = self.initial_data_source(slave_desc)
            proto.send(reply)
            # a join after the first job was handed out is an ELASTIC
            # join: the slave entered a run already in progress (and,
            # via initial_data, received the full-push resync)
            self._m_joins.labels(
                kind="mid_run" if self._jobs_handed else "initial").inc()
            if sharedio:
                # only AFTER the handshake reply is on the wire: the
                # client enables its rx side when it parses that reply,
                # so a large initial_data blob must still go inline —
                # enabling tx first would send it as a __shm__ ref the
                # client cannot yet dereference
                proto.enable_sharedio()
            while not self._done.is_set():
                msg = proto.recv()
                reply, stop = self._handle(sid, msg)
                proto.send(reply)
                if stop:
                    return
        except (ConnectionError, json.JSONDecodeError, OSError):
            pass
        finally:
            if sid is not None:
                with self._lock:
                    slave = self.slaves.get(sid)
                    if slave is not None and not slave.said_bye and \
                            not slave.done_sent and \
                            not self._done.is_set():
                        # the connection died mid-run with neither a
                        # goodbye nor a done reply: that is a crash
                        # (SIGKILL'd slave's kernel-closed socket —
                        # the common death, far faster than the
                        # heartbeat reaper; also covers a kill landing
                        # on an IDLE instant), not a clean end-of-run
                        # exit — count it as a death so slave_dead
                        # fires and the series GC runs
                        self._drop_faulted(sid, "dead")
                    else:
                        self.drop_slave(sid)
            proto.close()

    def _handle(self, sid, msg):
        """One request → (reply, stop).

        Registry/queue state changes run under ``_lock``; the callbacks
        into the workflow (``job_source``/``result_sink``) run OUTSIDE
        it — with pod-scale payloads (full weight sets) their
        pickle/merge time would otherwise starve the heartbeat path and
        the reaper would drop live slaves mid-job. The workflow's own
        per-unit data locks (``distributable.py``) protect its state.
        """
        cmd = msg.get("cmd")
        action = None
        with self._lock:
            slave = self.slaves.get(sid)
            if slave is None:
                return {"error": "dropped"}, True
            slave.last_seen = time.time()
            if cmd == "job":
                if len(slave.jobs_in_flight) >= self.MAX_IN_FLIGHT:
                    # run-ahead bound: the pipeline may keep at most
                    # MAX_IN_FLIGHT jobs open (balance counter parity)
                    return {"job": None, "done": False,
                            "backoff": True}, False
                if self.jobs:
                    payload = self.jobs.pop(0)
                    slave.jobs_in_flight.append((payload, time.time()))
                    slave.state = "WORK"
                    self._jobs_handed = True
                    return self._job_reply(payload), False
                if self.job_source is None or self.no_more_jobs:
                    if not slave.jobs_in_flight:
                        slave.state = "IDLE"
                    if self.no_more_jobs:
                        slave.done_sent = True
                    return {"job": None, "done": self.no_more_jobs}, False
                action = "source"
            elif cmd == "result":
                if slave.jobs_in_flight:
                    # results resolve oldest-first (replies are ordered
                    # per connection, so this matches the slave's view)
                    payload, started = slave.jobs_in_flight.pop(0)
                    job_elapsed = time.time() - started
                    self.job_times.append(job_elapsed)
                    self._m_job_ms.labels(slave=sid).observe(
                        job_elapsed * 1e3)
                    self.health.observe(sid, job_ms=job_elapsed * 1e3)
                    if slave.jobs_in_flight:
                        # the prefetched job only STARTS computing now:
                        # restart its clock so the adaptive timeout and
                        # job_times measure compute, not pipeline wait
                        nxt_payload, _ = slave.jobs_in_flight[0]
                        slave.jobs_in_flight[0] = (nxt_payload,
                                                   time.time())
                slave.jobs_done += 1
                self._m_jobs.labels(slave=sid).inc()
                if self._recovery_mark is not None:
                    # first resolved result since a fault requeued
                    # jobs: training is making progress again
                    self._m_recovery_ms.labels(event="requeue").observe(
                        (time.time() - self._recovery_mark) * 1e3)
                    self._recovery_mark = None
                if not slave.jobs_in_flight:
                    slave.state = "WAIT"
                if self.result_sink is None:
                    self.results.append(msg.get("data"))
                    self._results_cv.notify_all()
                    return {"ok": True}, False
                slave.applying = True
                action = "sink"
            elif cmd == "heartbeat":
                slave.power = msg.get("power", slave.power)
                self._record_rtt(sid, msg)
                action = "heartbeat"
            elif cmd == "bye":
                # voluntary exit (max_idle, client shutdown): without
                # this goodbye a slave dying IDLE mid-run would be
                # indistinguishable from one exiting on purpose
                slave.said_bye = True
                return {"ok": True}, True
            else:
                return {"error": "unknown cmd %r" % cmd}, False

        if action == "heartbeat":
            reply = {"ok": True}
            reply.update(self._absorb_telemetry(sid, msg))
            return reply, False

        if action == "source":
            payload = None
            t0 = time.perf_counter()
            try:
                payload = self.job_source(slave)
            except NoMoreJobsError:
                self.no_more_jobs = True
            source_ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                if payload is not None and sid in self.slaves:
                    # recorded under the liveness check: job_source
                    # ran outside _lock, and observing after a reap
                    # would re-mint the just-GC'd labeled child
                    self._m_source_ms.labels(slave=sid).observe(
                        source_ms)
                if sid not in self.slaves:
                    # the reaper dropped this slave while the job was
                    # being generated: the workflow registered the
                    # payload as pending for it — run the drop path once
                    # more so that registration is requeued, not lost
                    if payload is not None and self.on_drop is not None:
                        self.on_drop(slave)
                    return {"error": "dropped"}, True
                if payload is not None:
                    slave.jobs_in_flight.append((payload, time.time()))
                    slave.state = "WORK"
                    self._jobs_handed = True
                    return self._job_reply(payload), False
                if not slave.jobs_in_flight:
                    slave.state = "IDLE"
                if self.no_more_jobs:
                    slave.done_sent = True
                return {"job": None, "done": self.no_more_jobs}, False
        # action == "sink"
        t0 = time.perf_counter()
        try:
            self.result_sink(msg.get("data"), slave)
        finally:
            elapsed = time.perf_counter() - t0
            self._m_sink_ms.labels(slave=sid).observe(elapsed * 1e3)
            if tracing.enabled():
                # the master half of the exchange span: the slave half
                # (exchange:job) carries the same span_id
                trace = msg.get("trace") or {}
                tracing.add_complete(
                    "exchange:result", t0, elapsed, slave=sid,
                    trace_id=trace.get("trace_id", self.trace_id),
                    span_id=trace.get("span_id"))
            with self._lock:
                slave.applying = False
        return {"ok": True}, False

    def _job_reply(self, payload):
        """Job replies carry the run's trace id plus a per-job span id
        the slave echoes on its result — the correlation handle for
        the exchange legs."""
        return {"job": payload,
                "trace": {"trace_id": self.trace_id,
                          "span_id": uuid.uuid4().hex[:8]}}

    def _record_rtt(self, sid, msg):
        rtt = msg.get("rtt_ms")
        if isinstance(rtt, (int, float)):
            self._m_rtt_ms.labels(slave=sid).observe(float(rtt))
            self.health.observe(sid, rtt_ms=float(rtt), beat=True)
        else:
            self.health.observe(sid, beat=True)

    def _absorb_telemetry(self, sid, msg):
        """The master half of the heartbeat piggyback (runs OUTSIDE
        ``_lock``): merge the registry delta, surface flight notices,
        re-score the fleet. Returns ack hints for the reply (e.g.
        ``{"resync": True}``)."""
        t0 = time.perf_counter()
        hints = {}
        delta = msg.get("telemetry")
        if isinstance(delta, dict):
            try:
                hints = self.federation.apply(sid, delta) or {}
            except Exception:
                self.warning("federation merge failed for slave %s",
                             sid, exc_info=True)
        if isinstance(delta, dict):
            # re-check liveness AFTER the merge: this runs outside
            # _lock, so the reaper (or a clean disconnect) may have
            # dropped the slave between the handler's liveness check
            # and apply() — which would re-create the just-GC'd feed
            # as a permanent phantom
            with self._lock:
                alive = sid in self.slaves
            if not alive:
                self.federation.remove_slave(sid)
                self.health.remove(sid)
                hints = {}
        notices = msg.get("flight")
        if isinstance(notices, list):
            for notice in notices[:8]:
                if not isinstance(notice, dict):
                    continue
                reason = str(notice.get("reason") or "")
                if reason.startswith("cluster_"):
                    # never re-federate a cluster record (an in-process
                    # master+slave test shares ONE recorder — this is
                    # the recursion guard)
                    continue
                self._m_flight_notices.labels(slave=sid).inc()
                if self.on_slave_flight is not None:
                    try:
                        self.on_slave_flight(sid, notice)
                    except Exception:
                        self.warning("on_slave_flight failed for %s",
                                     sid, exc_info=True)
        self.health.evaluate()
        self._m_hb_handler_ms.observe((time.perf_counter() - t0) * 1e3)
        return hints

    def snapshot_slaves(self):
        """Consistent copy of the slave registry for outside readers."""
        with self._lock:
            return list(self.slaves.values())

    def _serve_heartbeats(self, proto, sid):
        proto.send({"ok": sid in self.slaves})
        while not self._done.is_set():
            msg = proto.recv()
            with self._lock:
                slave = self.slaves.get(sid)
                if slave is None:
                    reply, stop = {"error": "dropped"}, True
                else:
                    slave.last_seen = time.time()
                    slave.power = msg.get("power", slave.power)
                    self._record_rtt(sid, msg)
                    reply, stop = {"ok": True}, False
            if not stop:
                # federation merge / flight fan-out / health scoring
                # run OUTSIDE the registry lock so a big delta can
                # never starve the job path or the reaper
                reply.update(self._absorb_telemetry(sid, msg))
            proto.send(reply)
            if stop:
                return

    def stop(self):
        self._accepting = False
        self._done.set()
        try:
            self._listener.close()
        except OSError:
            pass


class CoordinatorClient(Logger):
    """Slave: pulls jobs, executes a callback, pushes results."""

    def __init__(self, address, checksum="", power=1.0,
                 death_probability=0.0, rand="chaos",
                 heartbeat_interval=2.0, pipeline=True, secret=None,
                 max_frame=None, federate=None, reconnect_s=None,
                 connect_retry_s=None):
        super(CoordinatorClient, self).__init__()
        self.address = tuple(address)
        self.checksum = checksum
        #: auto-resume support (ISSUE 12): when the master vanishes
        #: MID-RUN, retry a full re-handshake for up to this many
        #: seconds (exponential backoff with jitter) instead of giving
        #: up — the window a restarted master needs to restore from
        #: its latest snapshot and re-bind. 0/None = die like before.
        if reconnect_s is None:
            reconnect_s = env_knob("VELES_RECONNECT_S", 0.0,
                                   parse=float)
        self.reconnect_s = reconnect_s
        #: same budget for the INITIAL connect: a slave started before
        #: its master must not die on ConnectionRefused
        if connect_retry_s is None:
            connect_retry_s = env_knob("VELES_CONNECT_RETRY_S", 0.0,
                                       parse=float)
        self.connect_retry_s = connect_retry_s
        #: backoff shape: base * 2^n, each sleep jittered to 50-150%
        #: so a whole fleet reconnecting to a restarted master does
        #: not dial in lockstep
        self.backoff_base_s = env_knob("VELES_RECONNECT_BASE_S", 0.25,
                                       parse=float)
        #: called with this client after every successful MID-RUN
        #: reconnect (the launcher re-applies the master's initial
        #: data / resync state through it)
        self.on_reconnect = None
        self.reconnects = 0
        self._closed = False
        self.secret = secret.encode() if isinstance(secret, str) else secret
        self.max_frame = max_frame
        self.power = power
        self.death_probability = death_probability
        self.heartbeat_interval = heartbeat_interval
        #: piggyback delta-encoded registry snapshots on heartbeats so
        #: the master can serve ONE federated /metrics for the cluster
        #: (VELES_FEDERATION=0 turns the piggyback off fleet-wide)
        if federate is None:
            federate = env_flag("VELES_FEDERATION", True)
        self.federate = federate
        self._snapshot_encoder = None
        #: flight-record notices queued for the next beat (bounded: an
        #: incident storm must not balloon the heartbeat message)
        self._flight_notices = collections.deque(maxlen=16)
        self._hb_wake = threading.Event()
        #: prefetch the next job while the current one computes.
        #: Overlap costs one job of weight staleness (async SGD — the
        #: reference's balance-2 protocol had the same property);
        #: False = strict request→reply, bit-exact with standalone
        self.pipeline = pipeline
        self._rand = prng.get(rand)
        self.id = None
        #: the master's run-wide trace id (handshake reply); spans on
        #: this slave adopt it so --trace-out dumps from master and
        #: slave processes merge into one correlated timeline
        self.trace_id = None
        self.jobs_done = 0
        self._hb_stop = threading.Event()

    def _answer_auth(self, proto, reply, my_nonce):
        """Verify the master's proof over OUR nonce, then answer its
        challenge — mutual authentication, master-first (see
        ``CoordinatorServer._authenticate``)."""
        if not (isinstance(reply, dict) and "auth" in reply):
            if self.secret is not None:
                # fail closed: a slave configured with a secret must
                # never downgrade to an unauthenticated master (a rogue
                # process on the master's port would otherwise feed us
                # jobs with zero authentication)
                raise ConnectionError(
                    "master did not authenticate (reply: %s)"
                    % (reply.get("error", "no auth challenge")
                       if isinstance(reply, dict) else "malformed"))
            return reply
        if self.secret is None:
            raise ConnectionError(
                "master requires a shared secret (--secret-file)")
        expected = hmac.new(self.secret, ("m" + my_nonce).encode(),
                            "sha256").hexdigest()
        if not (isinstance(reply.get("proof"), str) and
                hmac.compare_digest(reply["proof"], expected)):
            raise ConnectionError("master failed mutual authentication")
        proto.send({"cmd": "auth", "proof": hmac.new(
            self.secret, ("s" + str(reply["auth"])).encode(),
            "sha256").hexdigest()})
        return proto.recv()

    def _retry_with_backoff(self, budget_s, attempt_fn):
        """Run ``attempt_fn`` until it succeeds, retrying socket-level
        failures with exponential backoff inside a bounded budget —
        the shared :func:`veles_tpu.parallel.retry.retry_with_backoff`
        shape (base * 2^n capped at 10 s, 50-150% jitter), used for
        both the initial dial (:meth:`_dial`) and the mid-run
        re-handshake (:meth:`reconnect`). Raises
        :class:`ConnectionError` when the budget is exhausted (or the
        client was closed)."""
        from veles_tpu.parallel.retry import retry_with_backoff
        return retry_with_backoff(
            attempt_fn, budget_s, base_s=self.backoff_base_s,
            give_up=lambda e: self._closed,
            describe="could not reach master at %s:%d" % (
                self.address[0], self.address[1]))

    def _dial(self, budget_s):
        """TCP connect with backoff inside a bounded budget. Only
        SOCKET-level failures retry — protocol rejections (checksum,
        auth) happen after the dial and propagate immediately."""
        return self._retry_with_backoff(
            budget_s,
            lambda: socket.create_connection(self.address, timeout=10.0))

    def connect(self, retry_s=None):
        sock = self._dial(self.connect_retry_s if retry_s is None
                          else retry_s)
        self.proto = Protocol(sock, max_frame=self.max_frame)
        nonce = secrets.token_hex(32)
        self.proto.send({"cmd": "handshake", "checksum": self.checksum,
                         "power": self.power, "nonce": nonce,
                         "mid": hex(uuid.getnode()), "pid": os.getpid()})
        reply = self._answer_auth(self.proto, self.proto.recv(), nonce)
        if isinstance(reply, dict) and "shm_challenge" in reply:
            # master asks for proof we really share its machine (see
            # _prove_same_host); answer and read the actual handshake
            # reply that follows
            self.proto.send(_answer_same_host(self.proto, reply))
            reply = self.proto.recv()
        if "error" in reply:
            raise ConnectionError(reply["error"])
        self.id = reply["id"]
        self.trace_id = reply.get("trace")
        self.initial_data = reply.get("data")
        if reply.get("sharedio"):
            # same machine as the master, proven by the nonce exchange:
            # updates ride shared memory
            self.proto.enable_sharedio()
        # dedicated heartbeat channel so long handler() runs don't get
        # this slave declared dead mid-job
        hb_sock = socket.create_connection(self.address, timeout=10.0)
        self._hb_proto = Protocol(hb_sock, max_frame=self.max_frame)
        hb_nonce = secrets.token_hex(32)
        self._hb_proto.send({"cmd": "hb_attach", "id": self.id,
                             "nonce": hb_nonce})
        self._answer_auth(self._hb_proto, self._hb_proto.recv(), hb_nonce)
        if self.federate:
            from veles_tpu.telemetry.federation import SnapshotEncoder
            self._snapshot_encoder = SnapshotEncoder()
        # the proto is passed BY VALUE into the loop: after a mid-run
        # reconnect the old thread keeps beating its own (now dead)
        # channel and exits on its ConnectionError, while the new
        # thread owns the new channel — two threads must never share
        # one protocol object
        t = threading.Thread(target=self._hb_loop,
                             args=(self._hb_proto,), daemon=True,
                             name="slave-heartbeat-%s" % self.id)
        t.start()
        return self

    def reconnect(self):
        """Full re-handshake after the master vanished mid-run: tear
        down both channels, then redial with backoff for up to
        ``reconnect_s`` seconds. The restored/restarted master assigns
        a NEW slave id; jobs lost with the old master are requeued by
        its recovery plane, never replayed from here. Returns True on
        success."""
        if not self.reconnect_s or self._closed:
            return False
        self.warning("master at %s:%d lost mid-run; retrying for up "
                     "to %.0fs", self.address[0], self.address[1],
                     self.reconnect_s)

        def attempt():
            for proto in (getattr(self, "proto", None),
                          getattr(self, "_hb_proto", None)):
                if proto is not None:
                    proto.close()
            # single-shot dial (retry_s=0): the WHOLE handshake is the
            # retried unit, because a dying master can accept the TCP
            # connect and even answer the main handshake before its
            # listener closes — the failure can land anywhere in the
            # sequence, not just the dial
            self.connect(retry_s=0)

        try:
            self._retry_with_backoff(self.reconnect_s, attempt)
        except (ConnectionError, OSError) as e:
            self.warning("reconnect failed: %s", e)
            return False
        self.reconnects += 1
        self.info("reconnected to master as slave %s", self.id)
        if self.on_reconnect is not None:
            try:
                self.on_reconnect(self)
            except Exception:
                self.warning("on_reconnect callback failed",
                             exc_info=True)
        return True

    def notify_flight(self, reason, path=None, context=None):
        """Queue a flight-record notice for the next heartbeat and
        wake the beat loop so the master learns promptly (the
        FlightRecorder dump-listener hook calls this)."""
        notice = {"reason": str(reason), "path": path,
                  "t": time.time(), "trace_id": self.trace_id}
        if isinstance(context, dict):
            # the notice rides a JSON control line: stringify anything
            # a detector stuffed in that json.dumps would choke on
            notice["context"] = {
                str(k): v if isinstance(v, (int, float, str, bool,
                                            type(None))) else str(v)
                for k, v in context.items()}
        self._flight_notices.append(notice)
        self._hb_wake.set()

    def _hb_loop(self, proto):
        # each beat reports the round-trip the PREVIOUS beat measured;
        # the master aggregates them per slave (heartbeat RTT series).
        # Since ISSUE 9 a beat also carries the registry snapshot
        # delta and any queued flight notices (notify_flight wakes the
        # loop early so incident news never waits a full interval).
        rtt_ms = None
        while True:
            self._hb_wake.wait(self.heartbeat_interval)
            self._hb_wake.clear()
            if self._hb_stop.is_set():
                return
            msg = {"cmd": "heartbeat", "power": self.power,
                   "rtt_ms": rtt_ms}
            if self._snapshot_encoder is not None:
                try:  # telemetry must never kill the beat
                    delta = self._snapshot_encoder.encode()
                except Exception:
                    delta = None
                if delta is not None:
                    msg["telemetry"] = delta
            notices = []
            while self._flight_notices:
                try:
                    notices.append(self._flight_notices.popleft())
                except IndexError:
                    break
            if notices:
                msg["flight"] = notices
            try:
                t0 = time.perf_counter()
                proto.send(msg)
                reply = proto.recv()
                rtt_ms = (time.perf_counter() - t0) * 1e3
            except (ConnectionError, OSError, ValueError):
                # ValueError: close() raced this beat mid-send ("write
                # to closed file" from the buffered pair) — same
                # meaning as the connection dropping
                return
            if isinstance(reply, dict) and reply.get("resync") and \
                    self._snapshot_encoder is not None:
                # the master saw a sequence gap: its view may hold
                # stale series — push everything next beat
                self._snapshot_encoder.mark_resync()

    def serve_forever(self, handler, idle_sleep=0.05, max_idle=None,
                      pipeline=None):
        """Pull/execute/push until the queue stays empty (or forever).

        With ``pipeline`` (default) the next-job request goes out
        BEFORE the current job is computed, so the master's job
        generation and this slave's compute overlap — the reference's
        async protocol (``client.py:433-437``), bounded by the
        server's MAX_IN_FLIGHT. The prefetched job reply is READ
        before the result is written: with multi-MB payloads, writing
        the result while the server is still blocked writing the job
        reply would fill both TCP buffers and deadlock both peers
        (write-write deadlock) — draining first guarantees the server
        is free to read."""
        if pipeline is None:
            pipeline = self.pipeline
        idle = 0
        pending_job = None
        while True:
            if pending_job is not None:
                job, job_trace = pending_job
                pending_job = None
            else:
                try:
                    self.proto.send({"cmd": "job"})
                    reply = self.proto.recv()
                except (ConnectionError, OSError):
                    # master went away mid-run: with a reconnect
                    # budget, re-handshake (a restarted master may be
                    # restoring from its snapshot right now) and keep
                    # serving; otherwise nothing more for this slave
                    if not self.reconnect():
                        return self.jobs_done
                    idle = 0
                    continue
                if reply.get("job") is None:
                    if reply.get("done"):
                        return self.jobs_done
                    idle += 1
                    if max_idle is not None and idle >= max_idle:
                        # voluntary exit: say goodbye so the master
                        # records a clean disconnect, not a death
                        self._say_goodbye()
                        return self.jobs_done
                    time.sleep(idle_sleep)
                    continue
                job = reply["job"]
                job_trace = reply.get("trace")
            idle = 0
            if self.death_probability and \
                    self._rand.rand() < self.death_probability:
                # chaos: die mid-job without reporting (--slave-death-
                # probability parity) — the master must requeue
                self.proto.close()
                raise RuntimeError("chaos death")
            prefetched = False
            if pipeline:
                try:
                    self.proto.send({"cmd": "job"})
                    prefetched = True
                except (ConnectionError, OSError):
                    prefetched = False
            trace = job_trace if isinstance(job_trace, dict) else {}
            # the slave half of the exchange span: job execution under
            # the master's trace id, labeled with the job's span id
            with tracing.request_span("exchange:job",
                                      trace_id=trace.get("trace_id",
                                                         self.trace_id),
                                      span_id=trace.get("span_id"),
                                      slave=self.id):
                result = handler(job)
            try:
                if prefetched:
                    # drain the job reply BEFORE writing the result:
                    # see the write-write deadlock note above
                    next_reply = self.proto.recv()
                self.proto.send({"cmd": "result", "data": result,
                                 "trace": job_trace})
                self.proto.recv()  # result ack
            except (ConnectionError, OSError):
                # master shut down while we were computing — either a
                # normal end-of-run (the result is lost, but the
                # master only closes once it has all it needs) or a
                # crash: with a reconnect budget, rejoin — the result
                # is discarded, the restored master requeues the job
                # itself (exactly-once stays with the master's
                # accounting, never with a stale slave-side replay)
                if not self.reconnect():
                    return self.jobs_done
                pending_job = None
                idle = 0
                continue
            self.jobs_done += 1
            if prefetched:
                nxt = next_reply.get("job")
                if nxt is None and next_reply.get("done"):
                    return self.jobs_done
                pending_job = None if nxt is None else \
                    (nxt, next_reply.get("trace"))

    def heartbeat(self):
        self.proto.send({"cmd": "heartbeat", "power": self.power})
        self.proto.recv()

    def _say_goodbye(self):
        """Best-effort voluntary-exit notice ({"cmd": "bye"}): lets
        the master classify this disconnect as clean instead of a
        death (which would count a drop and GC the series)."""
        try:
            self.proto.send({"cmd": "bye"})
            self.proto.recv()
        except Exception:
            pass  # the master may already be gone; exiting anyway

    def close(self):
        was_closed = self._closed
        self._closed = True  # no reconnect attempts past this point
        self._hb_stop.set()
        self._hb_wake.set()  # unblock a beat loop mid-wait
        if not was_closed:
            # send-only (no recv: a racing serve thread owns the read
            # side) — tells the master this teardown is deliberate
            try:
                self.proto.send({"cmd": "bye"})
            except Exception:
                pass
        self.proto.close()
        if hasattr(self, "_hb_proto"):
            self._hb_proto.close()
