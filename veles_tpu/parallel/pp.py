"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

Each device along ``pipe`` owns one stage's parameters (stacked on the
leading axis and sharded). Microbatches stream through: every clock
tick, activations hop to the next stage via ``lax.ppermute`` while each
stage applies its layer — the canonical collective-pipeline pattern.
Total ticks = n_microbatches + n_stages - 1 (bubble included).

The stage function must be shape-preserving (x -> x), the usual
residual-block contract.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def pipeline_apply(stage_fn, stacked_params, x_microbatches, mesh,
                   axis="pipe"):
    """Run microbatches through a pipeline of stages.

    * ``stage_fn(params, x) -> x`` — one stage's computation;
    * ``stacked_params`` — pytree whose leaves have leading dim
      n_stages (sharded over ``axis``);
    * ``x_microbatches`` — (n_micro, mb, ...) batch, replicated.

    Returns (n_micro, mb, ...) outputs (replicated).
    """
    n_stages = mesh.shape[axis]
    n_micro = x_microbatches.shape[0]
    total_ticks = n_micro + n_stages - 1

    params_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(params_spec, P()), out_specs=P(),
        check_vma=False)
    def run(params, xs):
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        state = jnp.zeros_like(xs[0])          # in-flight activation
        outputs = jnp.zeros_like(xs)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outputs = carry
            # stage 0 injects microbatch t (if any left)
            inject = jnp.where(t < n_micro,
                               xs[jnp.minimum(t, n_micro - 1)],
                               jnp.zeros_like(state))
            state = jnp.where(stage == 0, inject, state)
            state = stage_fn(my_params, state)
            # last stage emits microbatch t - (n_stages - 1)
            out_idx = t - (n_stages - 1)
            emit = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            outputs = jax.lax.cond(
                emit,
                lambda o: o.at[jnp.maximum(out_idx, 0)].set(state),
                lambda o: o,
                outputs)
            # rotate activations to the next stage
            state = jax.lax.ppermute(state, axis, fwd_perm)
            return state, outputs

        _, outputs = jax.lax.fori_loop(0, total_ticks, tick,
                                       (state, outputs))
        # outputs accumulated on the last stage; broadcast to all
        keep = (stage == n_stages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * keep, axis)

    return run(stacked_params, x_microbatches)
