"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

Each device along ``pipe`` owns one stage's parameters (stacked on the
leading axis and sharded). Microbatches stream through: every clock
tick, activations hop to the next stage via ``lax.ppermute`` while each
stage applies its layer — the canonical collective-pipeline pattern.
Total ticks = n_microbatches + n_stages - 1 (bubble included).

TRAINABLE (VERDICT r2 weak #3): the clock loop is a ``lax.scan``, so
reverse-mode AD flows through the whole pipeline — ``ppermute``'s
transpose is the inverse permute, giving the backward pipeline (grads
hopping stage-to-stage in reverse) for free, and microbatch gradient
ACCUMULATION falls out of differentiating the mean loss.
:func:`pipeline_train_step` packages one SGD step on a pipelined
stack. Scope (docs/PARITY.md): stages must be shape-preserving (the
residual-block contract); heterogeneous stacks like the conv flagship
scale with dp x tp instead.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def pipeline_apply(stage_fn, stacked_params, x_microbatches, mesh,
                   axis="pipe"):
    """Run microbatches through a pipeline of stages.

    * ``stage_fn(params, x) -> x`` — one stage's computation;
    * ``stacked_params`` — pytree whose leaves have leading dim
      n_stages (sharded over ``axis``);
    * ``x_microbatches`` — (n_micro, mb, ...) batch, replicated.

    Returns (n_micro, mb, ...) outputs (replicated). Differentiable in
    ``stacked_params`` and ``x_microbatches``.
    """
    n_stages = mesh.shape[axis]
    n_micro = x_microbatches.shape[0]
    total_ticks = n_micro + n_stages - 1

    params_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(params_spec, P()), out_specs=P(),
        check_vma=False)
    def run(params, xs):
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        state0 = jnp.zeros_like(xs[0])         # in-flight activation
        outputs0 = jnp.zeros_like(xs)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 injects microbatch t (if any left)
            inject = jnp.where(t < n_micro,
                               xs[jnp.minimum(t, n_micro - 1)],
                               jnp.zeros_like(state))
            state = jnp.where(stage == 0, inject, state)
            state = stage_fn(my_params, state)
            # last stage emits microbatch t - (n_stages - 1); masked
            # .at[].add keeps the update differentiable (a cond with
            # dynamic .set would be too, but where-select scans better)
            out_idx = t - (n_stages - 1)
            emit = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            delta = jnp.where(emit, 1.0, 0.0).astype(outputs.dtype)
            outputs = outputs.at[jnp.maximum(out_idx, 0)].add(
                state * delta)
            # rotate activations to the next stage
            state = jax.lax.ppermute(state, axis, fwd_perm)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (state0, outputs0), jnp.arange(total_ticks))
        # outputs accumulated on the last stage; broadcast to all
        keep = (stage == n_stages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * keep, axis)

    return run(stacked_params, x_microbatches)


def pipeline_train_step(stage_fn, stacked_params, x_microbatches,
                        y_microbatches, loss_fn, mesh, axis="pipe",
                        learning_rate=0.05):
    """One SGD step through the pipeline with microbatch gradient
    accumulation.

    ``loss_fn(outputs, targets) -> scalar`` is averaged over ALL
    microbatches; differentiating it through :func:`pipeline_apply`
    runs the backward pipeline (grads ppermute stage-to-stage in
    reverse) and sums each stage's gradient over every microbatch —
    the GPipe schedule's accumulate-then-step semantics.

    Returns ``(new_stacked_params, loss)``.
    """
    def total_loss(params):
        outs = pipeline_apply(stage_fn, params, x_microbatches, mesh,
                              axis)
        losses = jax.vmap(loss_fn)(outs, y_microbatches)
        return jnp.mean(losses)

    loss, grads = jax.value_and_grad(total_loss)(stacked_params)
    new_params = jax.tree_util.tree_map(
        lambda p, g: p - learning_rate * g, stacked_params, grads)
    return new_params, loss
