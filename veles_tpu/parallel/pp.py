"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

Each device along ``pipe`` owns one stage's parameters (stacked on the
leading axis and sharded). Microbatches stream through: every clock
tick, activations hop to the next stage via ``lax.ppermute`` while each
stage applies its layer — the canonical collective-pipeline pattern.
Total ticks = n_microbatches + n_stages - 1 (bubble included).

TRAINABLE (VERDICT r2 weak #3): the clock loop is a ``lax.scan``, so
reverse-mode AD flows through the whole pipeline — ``ppermute``'s
transpose is the inverse permute, giving the backward pipeline (grads
hopping stage-to-stage in reverse) for free, and microbatch gradient
ACCUMULATION falls out of differentiating the mean loss.
:func:`pipeline_train_step` packages one SGD step on a pipelined
stack of shape-preserving stages (the residual-block contract).

HETEROGENEOUS stages (r4): :func:`hetero_pipeline_apply` /
:func:`hetero_pipeline_train_step` lift that restriction — per-stage
activation shapes and per-stage parameter pytrees (padded-flat over
the pipe axis, ``lax.switch`` dispatch), so the conv flagship's
conv->pool->fc trunk pipelines too, optionally pp x dp in one
shard_map.
"""

import functools

import jax
import jax.numpy as jnp
import numpy
from jax.sharding import PartitionSpec as P

from veles_tpu.parallel.compat import shard_map


def pipeline_apply(stage_fn, stacked_params, x_microbatches, mesh,
                   axis="pipe"):
    """Run microbatches through a pipeline of stages.

    * ``stage_fn(params, x) -> x`` — one stage's computation;
    * ``stacked_params`` — pytree whose leaves have leading dim
      n_stages (sharded over ``axis``);
    * ``x_microbatches`` — (n_micro, mb, ...) batch, replicated.

    Returns (n_micro, mb, ...) outputs (replicated). Differentiable in
    ``stacked_params`` and ``x_microbatches``.
    """
    n_stages = mesh.shape[axis]
    n_micro = x_microbatches.shape[0]
    total_ticks = n_micro + n_stages - 1

    params_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(params_spec, P()), out_specs=P(),
        check_vma=False)
    def run(params, xs):
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        state0 = jnp.zeros_like(xs[0])         # in-flight activation
        outputs0 = jnp.zeros_like(xs)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 injects microbatch t (if any left)
            inject = jnp.where(t < n_micro,
                               xs[jnp.minimum(t, n_micro - 1)],
                               jnp.zeros_like(state))
            state = jnp.where(stage == 0, inject, state)
            state = stage_fn(my_params, state)
            # last stage emits microbatch t - (n_stages - 1); masked
            # .at[].add keeps the update differentiable (a cond with
            # dynamic .set would be too, but where-select scans better)
            out_idx = t - (n_stages - 1)
            emit = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            delta = jnp.where(emit, 1.0, 0.0).astype(outputs.dtype)
            outputs = outputs.at[jnp.maximum(out_idx, 0)].add(
                state * delta)
            # rotate activations to the next stage
            state = jax.lax.ppermute(state, axis, fwd_perm)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (state0, outputs0), jnp.arange(total_ticks))
        # outputs accumulated on the last stage; broadcast to all
        keep = (stage == n_stages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * keep, axis)

    return run(stacked_params, x_microbatches)


def pipeline_train_step(stage_fn, stacked_params, x_microbatches,
                        y_microbatches, loss_fn, mesh, axis="pipe",
                        learning_rate=0.05):
    """One SGD step through the pipeline with microbatch gradient
    accumulation.

    ``loss_fn(outputs, targets) -> scalar`` is averaged over ALL
    microbatches; differentiating it through :func:`pipeline_apply`
    runs the backward pipeline (grads ppermute stage-to-stage in
    reverse) and sums each stage's gradient over every microbatch —
    the GPipe schedule's accumulate-then-step semantics.

    Returns ``(new_stacked_params, loss)``.
    """
    def total_loss(params):
        outs = pipeline_apply(stage_fn, params, x_microbatches, mesh,
                              axis)
        losses = jax.vmap(loss_fn)(outs, y_microbatches)
        return jnp.mean(losses)

    loss, grads = jax.value_and_grad(total_loss)(stacked_params)
    new_params = jax.tree_util.tree_map(
        lambda p, g: p - learning_rate * g, stacked_params, grads)
    return new_params, loss


# -- heterogeneous stages (VERDICT r3 weak #3) ---------------------------


def _flatten_stage(params):
    """Stage pytree -> (f32 vector, size, unflatten(vec)->pytree)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    sizes = [int(jnp.size(l)) for l in leaves]
    total = sum(sizes)

    def unflatten(vec):
        out, off = [], 0
        for shape, dtype, size in zip(shapes, dtypes, sizes):
            out.append(vec[off:off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree_util.tree_unflatten(treedef, out)

    if leaves:
        vec = jnp.concatenate([jnp.ravel(l).astype(jnp.float32)
                               for l in leaves])
    else:
        vec = jnp.zeros((0,), jnp.float32)
    return vec, total, unflatten


def stack_stage_params(stage_params):
    """Per-stage pytrees (ARBITRARY, different shapes) -> one
    (n_stages, max_size) f32 array shardable over the pipe axis, plus
    the per-stage unflatten closures. The padding is what lets a
    HETEROGENEOUS pipeline ride SPMD collectives: every device holds
    the same-shaped parameter block, interpreted per-stage."""
    flat = [_flatten_stage(p) for p in stage_params]
    max_size = max(1, max(total for _, total, _ in flat))
    stacked = jnp.stack([
        jnp.pad(vec, (0, max_size - total))
        for vec, total, _ in flat])
    return stacked, [u for _, _, u in flat]


def hetero_pipeline_apply(stage_fns, stage_params, stacked, unflattens,
                          x_microbatches, mesh, axis="pipe",
                          data_axis=None, rng_key=None):
    """GPipe microbatch pipeline over stages with DIFFERENT activation
    shapes (the conv flagship's conv->pool->fc trunk, not just
    shape-preserving residual blocks).

    Per-boundary activation shapes are computed at trace time
    (``jax.eval_shape`` chain); activations travel between stages in a
    single max-size rotating buffer (``ppermute``), and each device
    dispatches its own stage's unpack-compute-repack via ``lax.switch``
    on its pipe-axis index — one SPMD program, per-stage shapes.

    * ``stage_fns[i](params_i, x_i) -> x_{i+1}``;
    * ``stage_params`` — per-stage pytrees (shape templates only);
    * ``stacked``/``unflattens`` — from :func:`stack_stage_params`
      (``stacked`` is the differentiable argument);
    * ``data_axis`` — optional mesh axis to shard the microbatch dim
      over: pp x dp in one shard_map.
    * ``rng_key`` — optional PRNG key for stochastic stages (dropout:
      VERDICT r4 weak #4). When given, every ``stage_fns[i]`` is called
      as ``fn(params_i, x, key)``. The key stream folds the data-axis
      index FIRST (under pp x dp; each data shard draws an independent
      mask for its local examples), then stage, then microbatch:
      ``fold_in(fold_in(fold_in(rng_key, d), i), m)`` (no ``d`` fold
      without a data axis). A sequential reference reproduces the
      exact stream by folding in that order.

    Returns (n_micro, mb, ...) outputs. Differentiable in ``stacked``
    (the ppermute transposes run the backward pipeline).
    """
    n_stages = mesh.shape[axis]
    if len(stage_fns) != n_stages:
        raise ValueError("%d stage fns for a %d-wide pipe axis" %
                         (len(stage_fns), n_stages))
    n_micro = x_microbatches.shape[0]
    total_ticks = n_micro + n_stages - 1
    batch_spec = P(None, data_axis) if data_axis else P()
    use_rng = rng_key is not None
    # the key input exists ONLY when rng is on: the no-rng signature
    # stays exactly 2 inputs, preserving the eager (unjitted) grad
    # path; with rng, call the train step under jit — the eager
    # shard_map transpose mis-matches out-shardings for this program
    # shape (JAX impl-path limitation, see the tick comment)
    in_specs = ((P(axis), batch_spec, P()) if use_rng
                else (P(axis), batch_spec))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=in_specs, out_specs=batch_spec,
        check_vma=False)
    def run(params, xs, *maybe_key):
        my_flat = params[0]                     # (max_size,)
        stage = jax.lax.axis_index(axis)
        key = maybe_key[0] if use_rng else None
        if use_rng and data_axis:
            key = jax.random.fold_in(key, jax.lax.axis_index(data_axis))
        # trace-time boundary shapes from the LOCAL microbatch block
        bounds = [jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype)]
        for fn, template in zip(stage_fns, stage_params):
            struct = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
                template)
            if use_rng:
                bounds.append(jax.eval_shape(fn, struct, bounds[-1],
                                             key))
            else:
                bounds.append(jax.eval_shape(fn, struct, bounds[-1]))
        out_struct = bounds[-1]
        buf_size = max(int(numpy.prod(b.shape)) for b in bounds[:-1])

        def branch(i):
            def apply_stage(flat_vec, buffer, *tick_key):
                p = unflattens[i](flat_vec)
                size = int(numpy.prod(bounds[i].shape))
                x = buffer[:size].reshape(bounds[i].shape).astype(
                    bounds[i].dtype)
                if use_rng:
                    y = stage_fns[i](p, x, tick_key[0])
                else:
                    y = stage_fns[i](p, x)
                y_flat = jnp.ravel(y).astype(jnp.float32)
                new_buf = jnp.zeros((buf_size,), jnp.float32)
                if i < n_stages - 1:
                    new_buf = new_buf.at[:y_flat.size].set(y_flat)
                    emit = jnp.zeros(out_struct.shape, out_struct.dtype)
                else:
                    emit = y
                return new_buf, emit
            return apply_stage

        branches = [branch(i) for i in range(n_stages)]
        outputs0 = jnp.zeros((n_micro,) + tuple(out_struct.shape),
                             out_struct.dtype)
        buf0 = jnp.zeros((buf_size,), jnp.float32)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        in_size = int(numpy.prod(bounds[0].shape))

        def tick(carry, t):
            buffer, outputs = carry
            inject = jnp.where(
                t < n_micro,
                jnp.ravel(xs[jnp.minimum(t, n_micro - 1)]).astype(
                    jnp.float32),
                jnp.zeros((in_size,), jnp.float32))
            inject = jnp.zeros((buf_size,), jnp.float32).at[
                :in_size].set(inject)
            buffer = jnp.where(stage == 0, inject, buffer)
            ops = (my_flat, buffer)
            if use_rng:
                # per-(stage, microbatch) key, folded OUTSIDE the
                # switch: threefry folding a scan-iterated value inside
                # a switch branch breaks shard_map's transpose (JAX
                # partial-eval assertion), so the branches receive the
                # READY key as an operand. m clipped — bubble-tick
                # outputs are masked.
                ops = ops + (jax.random.fold_in(
                    jax.random.fold_in(key, stage),
                    jnp.maximum(t - stage, 0)),)
            buffer, emit = jax.lax.switch(stage, branches, *ops)
            out_idx = t - (n_stages - 1)
            is_emit = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            delta = jnp.where(is_emit, 1.0, 0.0).astype(outputs.dtype)
            outputs = outputs.at[jnp.maximum(out_idx, 0)].add(
                emit * delta)
            buffer = jax.lax.ppermute(buffer, axis, fwd_perm)
            return (buffer, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (buf0, outputs0), jnp.arange(total_ticks))
        keep = (stage == n_stages - 1).astype(outputs.dtype)
        outputs = jax.lax.psum(outputs * keep, axis)
        return outputs

    if use_rng:
        return run(stacked, x_microbatches, rng_key)
    return run(stacked, x_microbatches)


def hetero_pipeline_train_step(stage_fns, stage_params, stacked,
                               unflattens, x_microbatches,
                               y_microbatches, loss_fn, mesh,
                               axis="pipe", data_axis=None,
                               learning_rate=0.05, rng_key=None):
    """One SGD step through the heterogeneous pipeline (microbatch
    gradient accumulation falls out of differentiating the mean loss;
    with ``data_axis`` set, the batch-dim sharding makes it pp x dp and
    the parameter-gradient psum over data rides the transpose).
    ``rng_key`` enables stochastic stages — see
    :func:`hetero_pipeline_apply`; dropout masks are constants of the
    step, so the backward pipeline reuses the forward's masks exactly
    (the reference stored ``last_mask`` for the same reason,
    ``veles/znicz dropout`` semantics). Returns ``(new_stacked, loss)``."""
    def total_loss(flat_stack):
        outs = hetero_pipeline_apply(
            stage_fns, stage_params, flat_stack, unflattens,
            x_microbatches, mesh, axis, data_axis, rng_key=rng_key)
        losses = jax.vmap(loss_fn)(outs, y_microbatches)
        return jnp.mean(losses)

    loss, grads = jax.value_and_grad(total_loss)(stacked)
    return stacked - learning_rate * grads, loss
