"""THE jittered exponential-backoff retry shape (ISSUE 13 satellite).

One helper behind every reconnection loop in the package — the
coordinator client's initial dial and mid-run re-handshake
(:mod:`veles_tpu.parallel.coordinator`), the multi-host
``jax.distributed`` coordinator dial (:func:`mesh.init_multihost`),
and the elastic supervisor's rendezvous dial
(:mod:`veles_tpu.parallel.elastic`). Shared on purpose: the fleet-wide
properties (exponential growth so a dead endpoint is not hammered,
50–150 % jitter so a restarting fleet never retries in lockstep, a
bounded budget so failure is eventually reported) must not drift
between callers.
"""

import random
import time


def backoff_delay(attempt, base_s=0.25, cap_s=10.0):
    """The fleet-wide backoff shape as a single number: the jittered
    sleep before retry ``attempt`` (0-based) — ``base_s * 2^attempt``
    capped at ``cap_s``, scaled to 50-150 % so a restarting fleet
    never retries in lockstep."""
    return min(base_s * 2 ** attempt, cap_s) * (0.5 + random.random())


def retry_with_backoff(attempt_fn, budget_s, *, base_s=0.25, cap_s=10.0,
                       retry_on=(ConnectionError, OSError),
                       give_up=None, describe="operation"):
    """Run ``attempt_fn`` until it succeeds, retrying ``retry_on``
    failures with exponential backoff (``base_s * 2^n`` capped at
    ``cap_s``, each sleep jittered to 50–150 %) inside a bounded
    ``budget_s``.

    ``give_up`` (optional callable ``exc -> bool``): a failure it
    answers True for aborts immediately instead of retrying (e.g. the
    caller was closed, or the error is a protocol rejection rather
    than a transport hiccup). Raises :class:`ConnectionError` naming
    ``describe`` when the budget is exhausted.
    """
    deadline = time.monotonic() + max(budget_s, 0.0)
    attempt = 0
    while True:
        try:
            return attempt_fn()
        except retry_on as e:
            attempt += 1
            remaining = deadline - time.monotonic()
            if remaining <= 0 or (give_up is not None and give_up(e)):
                raise ConnectionError(
                    "%s after %d attempt(s): %s"
                    % (describe, attempt, e)) from e
        sleep = backoff_delay(attempt - 1, base_s, cap_s)
        time.sleep(min(sleep, max(remaining, 0.0)))
