"""Sequence/context parallelism: ring attention.

Long sequences are sharded over the mesh's ``seq`` axis; each device
holds a Q/K/V block. K/V blocks rotate around the ring via
``lax.ppermute`` while each device accumulates its Q block's attention
with the streaming-softmax (flash) recurrence — max ``m``, denominator
``l`` and weighted sum carried across hops — so the full sequence is
never materialized on any chip and compute overlaps the ICI transfer.

This is the veles_tpu long-context primitive (the 2015 reference has no
attention at all — SURVEY.md §5 records it as absent; here it is a
first-class capability, designed per the task brief).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from veles_tpu.parallel.compat import shard_map


def _block_attention(q, k, v, q_off, k_off, scale, causal, m, l, acc):
    """One streaming-softmax update of (m, l, acc) with a new K/V block.

    q: (B, H, Sq, D); k/v: (B, H, Sk, D); offsets are the blocks' global
    sequence positions (for causal masking).
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_off + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 2)
        k_pos = k_off + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 3)
        scores = jnp.where(q_pos >= k_pos, scores, -jnp.inf)
    blk_max = jnp.max(scores, axis=-1)               # (B,H,Sq)
    new_m = jnp.maximum(m, blk_max)
    # guard -inf rows (fully masked block): exp(-inf - -inf) -> use safe m
    safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    correction = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf,
                                   m - safe_m))
    correction = jnp.where(jnp.isneginf(m), 0.0, correction)
    new_l = l * correction + jnp.sum(p, axis=-1)
    new_acc = acc * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return new_m, new_l, new_acc


def ring_attention(q, k, v, mesh, axis="seq", causal=False, scale=None):
    """Attention over a sequence sharded on ``axis`` (dim 2 of BHSD).

    Returns the attention output with the same sharding as ``q``.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n_shards = mesh.shape[axis]
    spec = P(None, None, axis, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False)
    def inner(q_blk, k_blk, v_blk):
        seq_shard = q_blk.shape[2]
        my_idx = jax.lax.axis_index(axis)
        q_off = my_idx * seq_shard
        m = jnp.full(q_blk.shape[:3], -jnp.inf, jnp.float32)
        l = jnp.zeros(q_blk.shape[:3], jnp.float32)
        acc = jnp.zeros(q_blk.shape[:3] + (q_blk.shape[3],), jnp.float32)
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

        def hop(carry, h):
            k_cur, v_cur, m, l, acc = carry
            src_idx = (my_idx - h) % n_shards
            k_off = src_idx * seq_shard
            m, l, acc = _block_attention(q_blk, k_cur, v_cur, q_off,
                                         k_off, scale, causal, m, l, acc)
            # rotate K/V to the next device while nothing depends on it
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (k_nxt, v_nxt, m, l, acc), None

        # lax.scan (not fori_loop): reverse-mode AD flows through the
        # ring — ppermute's transpose is the inverse rotation, so
        # training THROUGH ring attention needs nothing special
        carry = (k_blk, v_blk, m, l, acc)
        carry, _ = jax.lax.scan(hop, carry, jnp.arange(n_shards))
        _, _, m, l, acc = carry
        l = jnp.maximum(l, 1e-30)
        return (acc / l[..., None]).astype(q_blk.dtype)

    return inner(q, k, v)


def local_attention(q, k, v, causal=False, scale=None):
    """Single-device oracle with identical math (for parity tests)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 2)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 3)
        scores = jnp.where(q_pos >= k_pos, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w,
                      v.astype(jnp.float32)).astype(q.dtype)


def ulysses_attention(q, k, v, mesh, axis="seq", causal=False,
                      scale=None):
    """All-to-all sequence parallelism (the DeepSpeed-Ulysses
    schedule): the complement to :func:`ring_attention`.

    Q/K/V arrive sequence-sharded (dim 2 of BHSD). One
    ``lax.all_to_all`` per tensor swaps the sequence sharding for a
    HEAD sharding, so each device computes exact full-sequence
    attention for ``H / n_shards`` of the heads with a single dense
    kernel (no streaming recurrence, better MXU shapes); the inverse
    all_to_all restores sequence sharding on the output. Costs two
    all_to_alls of the activations vs the ring's n_shards ppermute
    hops — the better trade when heads divide evenly and the ICI
    bisection is wide; ring wins when H < n_shards or memory for the
    full-sequence scores is tight. Requires H % n_shards == 0.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n_shards = mesh.shape[axis]
    if q.shape[1] % n_shards:
        raise ValueError(
            "ulysses needs heads (%d) divisible by the %r axis (%d) — "
            "use ring_attention for head counts below the mesh" %
            (q.shape[1], axis, n_shards))
    spec = P(None, None, axis, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False)
    def inner(q_blk, k_blk, v_blk):
        # (B, H, S/n, D) -> (B, H/n, S, D): split heads, gather seq
        def to_heads(t):
            return jax.lax.all_to_all(t, axis, split_axis=1,
                                      concat_axis=2, tiled=True)

        qh, kh, vh = to_heads(q_blk), to_heads(k_blk), to_heads(v_blk)
        out = local_attention(qh, kh, vh, causal=causal, scale=scale)
        # (B, H/n, S, D) -> (B, H, S/n, D)
        return jax.lax.all_to_all(out, axis, split_axis=2,
                                  concat_axis=1, tiled=True)

    return inner(q, k, v)
