"""Warm evaluator processes for ensemble/genetics job farming.

The reference re-exec'd ``python -m veles`` for every ensemble member
and every chromosome fitness run
(``veles/ensemble/model_workflow.py:96-135``,
``veles/genetics/optimization_workflow.py:186-221``) — on TPU a cold
process pays the JAX import plus backend init (~5-10 s) before any
useful work, dwarfing a small model's training time (VERDICT r2 weak
#6). A :class:`WarmPool` keeps N evaluator processes ALIVE: each
imports veles_tpu once, then loops running ``veles_tpu.__main__.main``
IN-PROCESS per job streamed over stdin/stdout JSON lines. The XLA
persistent compile cache makes repeat compilations of the same
workflow shapes near-free, so the second evaluation onward pays
neither import nor compile.

Config residue: jobs override the SAME dotted config paths every run
(ensemble's ``model_index``/``size``, genetics' tuned leaves) and
re-seed via ``-s``, so successive jobs in one process fully overwrite
each other's state — the contract that makes in-process reuse sound.

The worker redirects stray stdout into stderr at startup and keeps a
private dup of the real stdout for the protocol, so a workflow that
prints cannot corrupt the job stream.
"""

import json
import os
import subprocess
import sys
import threading

from veles_tpu.envknob import env_knob
from veles_tpu.logger import Logger


def _worker_main():
    """Loop: one JSON job per stdin line -> one JSON reply line."""
    proto_out = os.fdopen(os.dup(sys.stdout.fileno()), "w", buffering=1)
    os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    sys.stdout = sys.stderr
    if env_knob("VELES_TPU_BACKEND") in ("cpu", "numpy"):
        # flip the platform BEFORE anything touches jax: sitecustomize
        # may pin a TPU-relay platform that the env var alone cannot
        # undo, and initializing it here would block the worker behind
        # whatever currently holds the chip
        import jax
        jax.config.update("jax_platforms", "cpu")
    from veles_tpu.__main__ import main
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            job = json.loads(line)
            if job.get("cmd") == "exit":
                break
            argv = list(job["argv"])
            result_file = job.get("result_file")
            code = main(argv)
            reply = {"ok": code == 0, "code": code, "pid": os.getpid()}
            if code == 0 and result_file:
                with open(result_file) as fin:
                    reply["result"] = json.load(fin)
        except SystemExit as e:
            reply = {"ok": (e.code or 0) == 0, "code": e.code,
                     "pid": os.getpid()}
        except Exception as e:  # noqa: BLE001 — report, keep serving
            reply = {"ok": False, "error": "%s: %s" % (
                type(e).__name__, e), "pid": os.getpid()}
        finally:
            rf = None
            try:
                rf = job.get("result_file")
            except Exception:
                pass
            if rf:
                try:
                    os.unlink(rf)
                except OSError:
                    pass
        proto_out.write(json.dumps(reply) + "\n")
        proto_out.flush()


class WarmWorker(object):
    """One persistent evaluator process."""

    def __init__(self, env=None):
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "veles_tpu.parallel.warm_pool"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            env=env, text=True, bufsize=1)
        self.jobs_done = 0

    @property
    def pid(self):
        return self.proc.pid

    def run(self, argv, result_file=None):
        """Execute one job; blocks until the reply line arrives."""
        job = {"argv": list(argv)}
        if result_file:
            job["result_file"] = result_file
        self.proc.stdin.write(json.dumps(job) + "\n")
        self.proc.stdin.flush()
        line = self.proc.stdout.readline()
        if not line:
            raise RuntimeError(
                "warm evaluator died (rc=%s)" % self.proc.poll())
        self.jobs_done += 1
        return json.loads(line)

    def close(self):
        try:
            self.proc.stdin.write('{"cmd": "exit"}\n')
            self.proc.stdin.flush()
            self.proc.stdin.close()
            self.proc.wait(timeout=10)
        except Exception:
            self.proc.kill()


class WarmPool(Logger):
    """N warm workers with a simple checkout discipline.

    With one local accelerator the sensible N is 1 (evaluations
    contend for the chip) — the point is WARMTH, not parallelism;
    multi-worker mode serves CPU meshes and pure-host fitness runs.
    """

    def __init__(self, workers=1, env=None):
        super(WarmPool, self).__init__()
        self._env = env
        self._workers = [WarmWorker(env) for _ in range(workers)]
        self._free = list(self._workers)
        self._cv = threading.Condition()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def pids(self):
        return [w.pid for w in self._workers]

    def run(self, argv, result_file=None):
        with self._cv:
            while not self._free:
                self._cv.wait()
            worker = self._free.pop()
        try:
            reply = worker.run(argv, result_file)
        except (RuntimeError, OSError, ValueError):
            # the worker died (BrokenPipeError on write, empty/corrupt
            # reply): replace it so the pool keeps serving, surface the
            # failure — a narrower catch would leak the checked-out
            # worker and deadlock every later run() at workers=1
            try:
                worker.close()
            except Exception:
                pass
            with self._cv:
                self._workers.remove(worker)
                replacement = WarmWorker(self._env)
                self._workers.append(replacement)
                self._free.append(replacement)
                self._cv.notify()
            raise
        with self._cv:
            self._free.append(worker)
            self._cv.notify()
        return reply

    def close(self):
        # empty the pool under the lock and WAKE waiters (a run()
        # blocked on an empty free list would otherwise sleep forever);
        # worker shutdown happens outside it — close() blocks up to
        # 10 s per worker
        with self._cv:
            workers, self._workers = self._workers, []
            self._free = []
            self._cv.notify_all()
        for worker in workers:
            worker.close()


if __name__ == "__main__":
    _worker_main()
