"""Tensor parallelism: layer weight sharding rules.

Megatron-style column→row sharding for stacked linear layers: the first
layer's weights split over ``model`` on the output dim (each device
computes a slice of the hidden activation), the next layer splits on
the input dim (partial sums psum'd). With ``jax.jit`` + NamedSharding
annotations XLA's SPMD partitioner inserts exactly those collectives —
we only declare the layout. ``tp_param_shardings`` builds the per-layer
pytree for :class:`~veles_tpu.parallel.dp.DataParallelTrainer`'s
``param_shardings``; ``shard_map_linear`` is the explicit-collective
version for kernels that need manual control.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from veles_tpu.parallel.compat import shard_map

from veles_tpu.parallel.mesh import named_sharding


def tp_param_shardings(forwards, mesh, axis="model"):
    """Alternating column/row sharding specs for a stack of layers —
    dense AND conv (VERDICT r2 weak #4: conv fell to replicated, so the
    flagship AlexNet ran DP-only).

    * dense (fin, fout): column = split fout, row = split fin;
    * conv HWIO (ky, kx, cin, cout): column = split cout (each device
      computes a slice of the output channels — the Megatron column
      analog), row = split cin (partial sums; the partitioner inserts
      the psum). Channel-mixing layers between convs (LRN's +-2 window,
      the conv->fc flatten) reshard via SPMD collectives the
      partitioner derives — we only declare parameter layouts.

    A layer whose sharded dim would not divide the axis stays
    replicated (and the alternation phase is not consumed). The LAST
    layer is kept replicated (its output feeds the loss, usually tiny).
    """
    n_shards = mesh.shape[axis]
    specs = []
    column = True  # first sharded layer: split output features
    n = len(forwards)
    for i, fwd in enumerate(forwards):
        params = fwd.param_arrays() if hasattr(fwd, "param_arrays") else {}
        wshape = tuple(fwd.weights.shape) if "weights" in params else ()
        if not params or i == n - 1 or len(wshape) not in (2, 4):
            specs.append(
                {k: named_sharding(mesh) for k in params} or {})
            continue
        fan_in, fan_out = wshape[-2], wshape[-1]
        lead = (None,) * (len(wshape) - 2)   # (ky, kx) for conv
        if column and fan_out % n_shards == 0:
            spec = {"weights": named_sharding(mesh, *lead + (None, axis)),
                    "bias": named_sharding(mesh, axis)}
        elif not column and fan_in % n_shards == 0:
            spec = {"weights": named_sharding(mesh, *lead + (axis, None)),
                    "bias": named_sharding(mesh)}
        else:
            specs.append({k: named_sharding(mesh) for k in params})
            continue
        specs.append({k: spec[k] for k in params})
        column = not column
    return tuple(specs)


def shard_map_linear(x, w_col, w_row, mesh, axis="model",
                     activation=None):
    """Explicit two-layer TP block: y = (act(x @ Wcol)) @ Wrow with a
    single psum — the hand-written equivalent of what the partitioner
    derives from :func:`tp_param_shardings`."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(None, axis), P(axis, None)),
        out_specs=P(), check_vma=False)
    def block(x, wc, wr):
        h = jnp.dot(x, wc, preferred_element_type=jnp.float32)
        if activation is not None:
            h = activation(h)
        partial_y = jnp.dot(h, wr, preferred_element_type=jnp.float32)
        return jax.lax.psum(partial_y, axis)

    return block(x, w_col, w_row)
