"""Tensor parallelism: layer weight sharding rules.

Megatron-style column→row sharding for stacked linear layers: the first
layer's weights split over ``model`` on the output dim (each device
computes a slice of the hidden activation), the next layer splits on
the input dim (partial sums psum'd). With ``jax.jit`` + NamedSharding
annotations XLA's SPMD partitioner inserts exactly those collectives —
we only declare the layout. ``tp_param_shardings`` builds the per-layer
pytree for :class:`~veles_tpu.parallel.dp.DataParallelTrainer`'s
``param_shardings``; ``shard_map_linear`` is the explicit-collective
version for kernels that need manual control.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from veles_tpu.parallel.mesh import named_sharding


def tp_param_shardings(forwards, mesh, axis="model"):
    """Alternating column/row sharding specs for a stack of layers.

    Returns a tuple (one entry per forward unit) of dicts mapping
    parameter names to NamedShardings, suitable for
    ``DataParallelTrainer(param_shardings=...)``. Layers without
    parameters get empty dicts. The LAST layer is kept replicated (its
    output feeds the loss, usually tiny — e.g. 10 classes)."""
    specs = []
    column = True  # first sharded layer: split output features
    n = len(forwards)
    for i, fwd in enumerate(forwards):
        params = fwd.param_arrays() if hasattr(fwd, "param_arrays") else {}
        if not params or i == n - 1:
            specs.append(
                {k: named_sharding(mesh) for k in params} or {})
            continue
        if column:
            spec = {"weights": named_sharding(mesh, None, axis),
                    "bias": named_sharding(mesh, axis)}
        else:
            spec = {"weights": named_sharding(mesh, axis, None),
                    "bias": named_sharding(mesh)}
        specs.append({k: spec[k] for k in params})
        column = not column
    return tuple(specs)


def shard_map_linear(x, w_col, w_row, mesh, axis="model",
                     activation=None):
    """Explicit two-layer TP block: y = (act(x @ Wcol)) @ Wrow with a
    single psum — the hand-written equivalent of what the partitioner
    derives from :func:`tp_param_shardings`."""

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(None, axis), P(axis, None)),
        out_specs=P(), check_vma=False)
    def block(x, wc, wr):
        h = jnp.dot(x, wc, preferred_element_type=jnp.float32)
        if activation is not None:
            h = activation(h)
        partial_y = jnp.dot(h, wr, preferred_element_type=jnp.float32)
        return jax.lax.psum(partial_y, axis)

    return block(x, w_col, w_row)
