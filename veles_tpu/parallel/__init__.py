"""Distributed execution over TPU meshes.

The reference's only tensor-level parallelism is master↔slave data
parallelism over ZeroMQ (SURVEY.md §2.4): master holds canonical state,
slaves compute, updates merge point-to-point. On TPU that entire data
plane becomes XLA collectives over ICI/DCN under a single controller:

* :mod:`mesh`        — device mesh construction + multi-host init;
* :mod:`dp`          — data-parallel fused training (batch sharded over
  the ``data`` axis; XLA inserts the gradient all-reduce — the
  ``lax.psum`` that replaces the ZeroMQ update merge);
* :mod:`tp`          — tensor-parallel layer sharding rules;
* :mod:`pp`          — GPipe-style pipeline over a ``pipe`` axis;
* :mod:`sequence`    — ring attention / context parallelism over a
  ``seq`` axis (K/V blocks rotate via ppermute with streaming-softmax
  accumulation) — first-class here even though the 2015 reference
  predates attention (SURVEY.md §5 "long-context: absent");
* :mod:`coordinator` — the surviving *control* plane: master/slave
  handshake with topology checksum, heartbeats, elastic requeue and
  chaos injection for task farming (genetics/ensemble) and multi-host
  bring-up. Data never flows through it;
* :mod:`gspmd`       — the pod-scale launcher-SPMD tier (ISSUE 15):
  one ``jit`` over a named ``batch``×``model`` mesh unifying dp's
  batch placement and tp's model rules into the sharding specs of a
  single compiled step, loss curve bit-identical to the coordinator
  path by construction;
* :mod:`reshard`     — the measured array-redistribution primitive
  (Zhuang et al. recipe): checkpoint restore at a different mesh
  shape and train→serve layout moves, all under
  ``veles_reshard_ms{src,dst}``;
* :mod:`elastic`     — the SPMD recovery plane (ISSUE 13):
  generation-numbered rendezvous, per-host worker supervisors, and
  sharded checkpoint-restart so a ``jax.distributed`` pod that loses
  a participant re-forms at the surviving world size instead of
  wedging (docs/FAULT_TOLERANCE.md §SPMD mesh recovery);
* :mod:`retry`       — THE shared jittered-backoff retry helper
  behind every reconnection loop (coordinator dial/re-handshake,
  ``init_multihost``, rendezvous).
"""

from veles_tpu.parallel.mesh import (build_mesh, local_device_count,  # noqa
                                     named_sharding)
from veles_tpu.parallel.dp import DataParallelTrainer  # noqa: F401
from veles_tpu.parallel.gspmd import (GSPMDTrainer,  # noqa: F401
                                      gspmd_mesh)
from veles_tpu.parallel.ep import moe_ffn  # noqa: F401
from veles_tpu.parallel.sequence import (ring_attention,  # noqa: F401
                                         ulysses_attention)
