"""Expert parallelism: mixture-of-experts FFN over an ``expert`` axis.

The classic Switch/GShard schedule, TPU-native: tokens are sharded
over the mesh's ``expert`` axis (each device owns one shard of tokens
AND one expert's FFN weights); a top-1 router picks an expert per
token; tokens travel to their expert's device and back via
``lax.all_to_all`` over ICI; static shapes throughout (fixed per-expert
capacity, overflow dropped — the standard Switch contract, which is
what keeps the whole thing one compiled SPMD program).

The 2015 reference predates MoE entirely; this is a first-class
capability of the dp/tp/pp/sp/ep sharding family, designed per the
task brief rather than ported.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from veles_tpu.parallel.compat import shard_map


def moe_ffn(x, router_w, w_up, w_down, mesh, axis="expert",
            capacity_factor=1.25, activation=jax.nn.relu):
    """Top-1 mixture-of-experts FFN.

    * ``x`` — (tokens, d), sharded over ``axis`` on dim 0 (or
      replicated: the shard_map in_spec shards it);
    * ``router_w`` — (d, n_experts), replicated;
    * ``w_up`` — (n_experts, d, hidden), sharded over ``axis`` dim 0;
    * ``w_down`` — (n_experts, hidden, d), sharded over ``axis`` dim 0.

    Returns (tokens, d): each token's chosen expert's
    ``down(act(up(x)))`` scaled by its router probability — zero for
    tokens dropped by the capacity limit (Switch semantics).
    Differentiable in everything, router included (the probability
    scale carries the gradient).
    """
    n_experts = mesh.shape[axis]
    if router_w.shape[1] != n_experts:
        raise ValueError("router has %d experts, mesh axis %r is %d" %
                         (router_w.shape[1], axis, n_experts))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis)),
        out_specs=P(axis), check_vma=False)
    def run(xs, rw, up, down):
        t, d = xs.shape                      # local token shard
        up, down = up[0], down[0]            # this device's expert
        capacity = max(1, int(-(-t * capacity_factor // n_experts)))
        logits = xs @ rw                     # (t, E)
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)            # (t,)
        gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
        # position of each token within its expert's capacity window
        onehot = jax.nn.one_hot(expert, n_experts)     # (t, E)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (t, E)
        pos = jnp.sum(pos, axis=-1).astype(jnp.int32)  # (t,)
        keep = pos < capacity
        # dispatch buffer: (E, C, d) — slot [e, c] holds the token this
        # shard routes to expert e at capacity slot c (zeros elsewhere)
        slot = jnp.where(keep, expert * capacity + pos, -1)
        dispatch = jnp.zeros((n_experts * capacity, d), xs.dtype)
        dispatch = dispatch.at[jnp.maximum(slot, 0)].add(
            xs * keep[:, None].astype(xs.dtype))
        dispatch = dispatch.reshape(n_experts, capacity, d)
        # all_to_all: dim0 switches meaning source-shard <-> expert;
        # after it, THIS device holds every shard's tokens for ITS
        # expert: (n_shards, C, d)
        inbound = jax.lax.all_to_all(dispatch, axis, 0, 0, tiled=False)
        h = activation(jnp.einsum(
            "scd,dh->sch", inbound, up,
            preferred_element_type=jnp.float32).astype(xs.dtype))
        out = jnp.einsum("sch,hd->scd", h, down,
                         preferred_element_type=jnp.float32).astype(
            xs.dtype)
        # route results back to their source shards
        outbound = jax.lax.all_to_all(out, axis, 0, 0, tiled=False)
        flat = outbound.reshape(n_experts * capacity, d)
        gathered = flat[jnp.maximum(slot, 0)]
        return gathered * (gate * keep)[:, None].astype(xs.dtype)

    return run(x, router_w, w_up, w_down)


def moe_ffn_reference(x, router_w, w_up, w_down, n_experts,
                      capacity_factor=1.25, activation=jax.nn.relu,
                      n_shards=None):
    """Dense single-device reference with IDENTICAL semantics
    (per-shard capacity, same drop order) for parity tests."""
    n_shards = n_experts if n_shards is None else n_shards
    t_total, d = x.shape
    if t_total % n_shards:
        # the sharded path would reject this too (shard_map needs the
        # token dim divisible); a silent zero-tail here would be a
        # wrong "reference"
        raise ValueError("%d tokens not divisible by %d shards" %
                         (t_total, n_shards))
    t = t_total // n_shards
    out = jnp.zeros_like(x)
    for s in range(n_shards):
        xs = x[s * t:(s + 1) * t]
        capacity = max(1, int(-(-t * capacity_factor // n_experts)))
        probs = jax.nn.softmax(xs @ router_w, axis=-1)
        expert = jnp.argmax(probs, axis=-1)
        gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
        onehot = jax.nn.one_hot(expert, n_experts)
        pos = jnp.sum((jnp.cumsum(onehot, axis=0) - 1.0) * onehot,
                      axis=-1).astype(jnp.int32)
        keep = pos < capacity
        h = activation(jnp.einsum("td,edh->teh", xs, w_up,
                                  preferred_element_type=jnp.float32)
                       .astype(x.dtype))
        y = jnp.einsum("teh,ehd->ted", h, w_down,
                       preferred_element_type=jnp.float32).astype(
            x.dtype)
        picked = y[jnp.arange(t), expert]
        out = out.at[s * t:(s + 1) * t].set(
            picked * (gate * keep)[:, None].astype(x.dtype))
    return out


def load_balance_loss(probs, weights=None):
    """Switch-style load-balancing auxiliary loss.

    ``probs`` — (tokens, E) router softmax. With ``f_e`` the fraction
    of tokens whose top-1 choice is expert e and ``P_e`` the mean
    router probability of e, returns ``E * sum_e f_e * P_e`` —
    minimized (=1) at uniform routing; the gradient flows through
    ``P`` (``f`` is piecewise constant), nudging the router away from
    collapse onto a few experts (observed here: a 1-epoch run
    concentrating 96 tokens onto 2 of 4 experts).

    ``weights`` (tokens,) optionally masks/weights tokens — the fused
    trainer passes the padded-row validity mask so a short tail batch
    (whose padding rows are all-zero and would all tie onto expert 0)
    cannot distort the balance statistics.
    """
    n_experts = probs.shape[-1]
    assignment = jax.nn.one_hot(jnp.argmax(probs, axis=-1), n_experts)
    if weights is None:
        f = jnp.mean(assignment, axis=0)
        p = jnp.mean(probs, axis=0)
    else:
        w = weights.astype(probs.dtype)
        w = w / jnp.maximum(jnp.sum(w), 1.0)
        f = jnp.sum(assignment * w[:, None], axis=0)
        p = jnp.sum(probs * w[:, None], axis=0)
    return n_experts * jnp.sum(f * p)
