"""Device mesh construction and multi-host initialization.

Axis naming convention (used across the package):

* ``data``  — data parallelism (gradient psum rides ICI),
* ``model`` — tensor parallelism (activation collectives),
* ``pipe``  — pipeline stages (ppermute),
* ``seq``   — sequence/context parallelism (ring attention).

``build_mesh`` lays axes out so the fastest-varying axis maps to
physically adjacent devices (JAX mesh_utils handles the torus topology
when available), which keeps ``model``/``seq`` collectives on short ICI
paths and pushes ``data`` onto the remaining links — the scaling-book
recipe.
"""

import jax
import numpy


def local_device_count(platform=None):
    try:
        return len(jax.devices(platform) if platform else jax.devices())
    except RuntimeError:
        return 0


def build_mesh(axes=None, devices=None):
    """Build a ``jax.sharding.Mesh``.

    ``axes``: ordered dict/list of (name, size); sizes must multiply to
    the device count, a single -1 size is inferred. Default: pure data
    parallelism over all visible devices.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    if isinstance(axes, dict):
        axes = list(axes.items())
    names = [a[0] for a in axes]
    sizes = [a[1] for a in axes]
    if sizes.count(-1) > 1:
        raise ValueError("at most one inferred (-1) axis")
    if -1 in sizes:
        known = int(numpy.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError("cannot infer axis: %d %% %d" % (n, known))
        sizes[sizes.index(-1)] = n // known
    if int(numpy.prod(sizes)) != n:
        raise ValueError("mesh %r needs %d devices, have %d" %
                         (dict(zip(names, sizes)),
                          int(numpy.prod(sizes)), n))
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(tuple(sizes),
                                                  devices=devices)
    except Exception:
        dev_array = numpy.asarray(devices).reshape(sizes)
    return jax.sharding.Mesh(dev_array, tuple(names))


def put_global(host_array, sharding):
    """``device_put`` that also works under multi-controller SPMD.

    In a multi-host runtime a plain ``device_put`` onto a sharding
    whose devices span processes is rejected (non-addressable);
    ``make_array_from_callback`` lets every process contribute just its
    addressable shards, sliced from the same full host array (every
    controller holds identical data — same seeds, same loader)."""
    if jax.process_count() == 1:
        return jax.device_put(host_array, sharding)
    host_array = numpy.asarray(host_array)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx])


def named_sharding(mesh, *spec):
    """Shorthand for NamedSharding(mesh, PartitionSpec(*spec))."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(*spec))


def replicated(mesh):
    return named_sharding(mesh)


#: the one-shot jax.distributed spec this process initialized with —
#: the runtime cannot re-initialize in-process, so the guard turns a
#: same-spec double init into a no-op and a different-spec one into a
#: clear error (the elastic supervisor restarts the PROCESS to change
#: membership; see :mod:`veles_tpu.parallel.elastic`)
_MULTIHOST = {"spec": None}


def _runtime_initialized():
    """Best-effort: was jax.distributed initialized behind our back?"""
    try:
        from jax._src import distributed as _dist
        state = _dist.global_state
        return (getattr(state, "coordinator_address", None) is not None
                or getattr(state, "client", None) is not None)
    except Exception:
        return False


def multihost_initialized():
    """True when this process is part of a live multi-host runtime."""
    return _MULTIHOST["spec"] is not None or _runtime_initialized()


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None, retry_budget_s=None):
    """Initialize jax.distributed for multi-host pods (DCN).

    The reference's SSH slave spawning (``launcher.py:808-842``) maps to
    the cluster scheduler starting one process per host; this call wires
    them into one JAX runtime. No-op when standalone.

    Idempotent (ISSUE 13 satellite): a second call with the SAME
    (address, world, rank) spec returns True without touching the
    runtime; a DIFFERENT spec raises — jax.distributed cannot re-form
    a membership in-process, which is exactly why the elastic
    supervisor owns the worker lifecycle. The coordinator dial runs
    through the shared jittered-backoff retry helper
    (:func:`veles_tpu.parallel.retry.retry_with_backoff`,
    ``retry_budget_s`` / ``VELES_MESH_INIT_RETRY_S``, default 60 s) so
    a restarting worker cannot lose the race against a rendezvous
    window where the generation's coordinator is not listening yet.
    """
    import logging
    log = logging.getLogger("mesh")
    if num_processes in (None, 1):
        return False
    # None stays None: jax.distributed auto-detects coordinator and
    # process_id on TPU pods/GKE, and that invocation must keep working
    spec = (coordinator_address, int(num_processes),
            None if process_id is None else int(process_id))
    if _MULTIHOST["spec"] is not None:
        if _MULTIHOST["spec"] == spec:
            log.debug("init_multihost: already initialized as %r", spec)
            return True
        raise RuntimeError(
            "jax.distributed is already initialized as %r; re-forming "
            "the mesh as %r needs a fresh process (the elastic "
            "supervisor restarts workers for exactly this reason) or "
            "an explicit shutdown_multihost() first"
            % (_MULTIHOST["spec"], spec))
    if _runtime_initialized():
        # initialized outside this helper (user code / test harness):
        # trust it rather than crash a running pod
        log.warning("init_multihost: jax.distributed was initialized "
                    "outside init_multihost; leaving the runtime as-is")
        _MULTIHOST["spec"] = spec
        return True
    # the CPU backend runs multiprocess computations only through the
    # gloo collectives plugin; without this the post-init computation
    # dies with "Multiprocess computations aren't implemented on the
    # CPU backend" (the loopback tests + any CPU-pod rehearsal). Set
    # unconditionally: the flag only governs the CPU backend's
    # collectives, so it is inert on TPU deployments — and sniffing
    # JAX_PLATFORMS here would miss the default CPU-only host where
    # neither the env var nor jax_platforms is set.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older jaxlib: single-platform behavior unchanged
    if retry_budget_s is None:
        from veles_tpu.envknob import env_knob
        retry_budget_s = env_knob("VELES_MESH_INIT_RETRY_S", 60.0,
                                  parse=float)

    def non_retryable(e):
        # non-transport failures can never succeed on retry: an
        # already-initialized runtime, or a backend that some earlier
        # code initialized (computations before distributed init)
        return ("already initialized" in str(e) or
                "before any JAX computations" in str(e))

    def attempt():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id)
        except Exception as e:
            # a half-failed init (dial timed out mid-handshake) can
            # leave partial global state behind; reset it so the next
            # attempt is a clean first init. NEVER on the non-retryable
            # failures: "already initialized" means a LIVE runtime this
            # helper's best-effort probe missed — shutting it down
            # would crash every collective of a running pod.
            if not non_retryable(e):
                try:
                    jax.distributed.shutdown()
                except Exception:
                    pass
            raise

    from veles_tpu.parallel.retry import retry_with_backoff
    try:
        retry_with_backoff(
            attempt, retry_budget_s,
            retry_on=(RuntimeError, OSError, ConnectionError,
                      TimeoutError),
            give_up=non_retryable,
            describe="could not join the jax.distributed coordinator "
                     "at %s (world=%s rank=%s)" % spec)
    except ConnectionError as e:
        # a give-up failure is NOT a connectivity problem: surface the
        # original error ("already initialized", "computations before
        # init") instead of a ConnectionError blaming the network
        cause = e.__cause__
        if cause is not None and non_retryable(cause):
            raise cause
        raise
    _MULTIHOST["spec"] = spec
    return True


def shutdown_multihost():
    """Tear down the multi-host runtime this process initialized.

    Returns True when a runtime was actually shut down. Safe to call
    unconditionally (no-op when standalone); after it, a FRESH
    ``init_multihost`` spec is accepted again — but note that live
    backends/devices from the old runtime stay unusable, which is why
    production re-formation goes through a process restart (the
    elastic supervisor), not this helper. This exists for clean
    teardown at worker exit and for tests."""
    import logging
    if _MULTIHOST["spec"] is None and not _runtime_initialized():
        return False
    try:
        jax.distributed.shutdown()
    except Exception as e:
        logging.getLogger("mesh").warning(
            "jax.distributed.shutdown failed: %s", e)
    _MULTIHOST["spec"] = None
    return True
