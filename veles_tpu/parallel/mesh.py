"""Device mesh construction and multi-host initialization.

Axis naming convention (used across the package):

* ``data``  — data parallelism (gradient psum rides ICI),
* ``model`` — tensor parallelism (activation collectives),
* ``pipe``  — pipeline stages (ppermute),
* ``seq``   — sequence/context parallelism (ring attention).

``build_mesh`` lays axes out so the fastest-varying axis maps to
physically adjacent devices (JAX mesh_utils handles the torus topology
when available), which keeps ``model``/``seq`` collectives on short ICI
paths and pushes ``data`` onto the remaining links — the scaling-book
recipe.
"""

import jax
import numpy


def local_device_count(platform=None):
    try:
        return len(jax.devices(platform) if platform else jax.devices())
    except RuntimeError:
        return 0


def build_mesh(axes=None, devices=None):
    """Build a ``jax.sharding.Mesh``.

    ``axes``: ordered dict/list of (name, size); sizes must multiply to
    the device count, a single -1 size is inferred. Default: pure data
    parallelism over all visible devices.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    if isinstance(axes, dict):
        axes = list(axes.items())
    names = [a[0] for a in axes]
    sizes = [a[1] for a in axes]
    if sizes.count(-1) > 1:
        raise ValueError("at most one inferred (-1) axis")
    if -1 in sizes:
        known = int(numpy.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError("cannot infer axis: %d %% %d" % (n, known))
        sizes[sizes.index(-1)] = n // known
    if int(numpy.prod(sizes)) != n:
        raise ValueError("mesh %r needs %d devices, have %d" %
                         (dict(zip(names, sizes)),
                          int(numpy.prod(sizes)), n))
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(tuple(sizes),
                                                  devices=devices)
    except Exception:
        dev_array = numpy.asarray(devices).reshape(sizes)
    return jax.sharding.Mesh(dev_array, tuple(names))


def put_global(host_array, sharding):
    """``device_put`` that also works under multi-controller SPMD.

    In a multi-host runtime a plain ``device_put`` onto a sharding
    whose devices span processes is rejected (non-addressable);
    ``make_array_from_callback`` lets every process contribute just its
    addressable shards, sliced from the same full host array (every
    controller holds identical data — same seeds, same loader)."""
    if jax.process_count() == 1:
        return jax.device_put(host_array, sharding)
    host_array = numpy.asarray(host_array)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx])


def named_sharding(mesh, *spec):
    """Shorthand for NamedSharding(mesh, PartitionSpec(*spec))."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(*spec))


def replicated(mesh):
    return named_sharding(mesh)


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None):
    """Initialize jax.distributed for multi-host pods (DCN).

    The reference's SSH slave spawning (``launcher.py:808-842``) maps to
    the cluster scheduler starting one process per host; this call wires
    them into one JAX runtime. No-op when standalone.
    """
    if num_processes in (None, 1):
        return False
    # the CPU backend runs multiprocess computations only through the
    # gloo collectives plugin; without this the post-init computation
    # dies with "Multiprocess computations aren't implemented on the
    # CPU backend" (the loopback tests + any CPU-pod rehearsal). Set
    # unconditionally: the flag only governs the CPU backend's
    # collectives, so it is inert on TPU deployments — and sniffing
    # JAX_PLATFORMS here would miss the default CPU-only host where
    # neither the env var nor jax_platforms is set.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older jaxlib: single-platform behavior unchanged
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    return True
