"""Remote slave process spawning (re-designs ``veles/launcher.py``
``_launch_nodes``/``launch_remote_progs`` :617-660,808-842 and the
master-side ``--respawn`` backoff, ``veles/server.py:637-655``).

The reference used paramiko; here it is plain ``ssh`` via subprocess
(key-based auth assumed, like any cluster launcher), with
``localhost`` nodes exec'd directly so the path is testable without a
network. Node specs: ``host`` or ``host*N`` for N slaves per host.
"""

import shlex
import subprocess
import threading
import time

from veles_tpu.logger import Logger


def parse_nodes(spec):
    """``"a,b*2,c"`` → [("a",1),("b",2),("c",1)]."""
    nodes = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        host, _, count = part.partition("*")
        nodes.append((host, int(count) if count else 1))
    return nodes


class NodeLauncher(Logger):
    """Spawns and babysits slave processes on a set of nodes.

    ``command`` is the slave command line with an optional ``{master}``
    placeholder (filled with host:port) and ``{index}`` (slave ordinal
    on that node).
    """

    def __init__(self, nodes, command, master_address=None, respawn=False,
                 max_respawns=5, ssh_binary="ssh", ssh_options=()):
        super(NodeLauncher, self).__init__()
        self.nodes = parse_nodes(nodes) if isinstance(nodes, str) \
            else list(nodes)
        self.command = command
        self.master_address = master_address
        self.respawn = respawn
        self.max_respawns = max_respawns
        self.ssh_binary = ssh_binary
        self.ssh_options = list(ssh_options)
        self._procs = []       # (host, index, Popen)
        self._stopping = False
        self._monitor = None

    def _render(self, index):
        command = self.command
        if self.master_address is not None:
            command = command.replace(
                "{master}", "%s:%d" % tuple(self.master_address))
        return command.replace("{index}", str(index))

    def _spawn(self, host, index):
        command = self._render(index)
        if host in ("localhost", "127.0.0.1"):
            proc = subprocess.Popen(command, shell=True)
        else:
            proc = subprocess.Popen(
                [self.ssh_binary] + self.ssh_options + [host, command])
        self.info("spawned slave %d on %s (pid %d)", index, host,
                  proc.pid)
        return proc

    def start(self):
        index = 0
        for host, count in self.nodes:
            for _ in range(count):
                self._procs.append([host, index, self._spawn(host, index),
                                    0])
                index += 1
        if self.respawn:
            self._monitor = threading.Thread(
                target=self._respawn_loop, daemon=True,
                name="node-respawn")
            self._monitor.start()
        return self

    def _respawn_loop(self):
        # per-entry next-respawn timestamps: one slave's backoff must
        # not serialize death detection/relaunch of the others
        due = {}
        while not self._stopping:
            time.sleep(0.2)
            now = time.time()
            for entry in self._procs:
                host, index, proc, respawns = entry
                if proc.poll() is None or self._stopping:
                    due.pop(index, None)
                    continue
                if respawns >= self.max_respawns:
                    continue
                if index not in due:
                    # exponential backoff like the reference's _respawn
                    delay = min(2.0 ** respawns * 0.1, 30.0)
                    self.warning("slave %d on %s died (rc %s); respawn "
                                 "in %.1fs", index, host, proc.returncode,
                                 delay)
                    due[index] = now + delay
                    continue
                if now >= due.pop(index):
                    entry[2] = self._spawn(host, index)
                    entry[3] = respawns + 1

    @property
    def alive(self):
        return sum(1 for _, _, proc, _ in self._procs
                   if proc.poll() is None)

    def wait(self, timeout=None):
        deadline = None if timeout is None else time.time() + timeout
        for _, _, proc, _ in self._procs:
            remaining = None if deadline is None \
                else max(0.0, deadline - time.time())
            try:
                proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                return False
        return True

    def stop(self):
        self._stopping = True
        for _, _, proc, _ in self._procs:
            if proc.poll() is None:
                proc.terminate()
        for _, _, proc, _ in self._procs:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None


def slave_command_from_argv(argv, master_address):
    """Build the remote slave command from this master's argv
    (the reference's ``filter_argv`` idea, ``launcher.py:75-96``):
    strip master-only flags, add ``-m host:port``."""
    import sys
    drop_with_value = {"-l", "--listen", "-n", "--nodes", "-d", "--device",
                       # master-side exchange policy: the slave's
                       # DeltaDecoder auto-detects delta pushes, so the
                       # flags would only be parsed and ignored
                       "--exchange-dtype", "--exchange-eps"}
    drop_bare = {"--respawn", "--web-status"}
    out = [sys.executable, "-m", "veles_tpu"]
    i = 0
    args = list(argv)
    while i < len(args):
        arg = args[i]
        if arg in drop_with_value:
            i += 2
            continue
        if arg.split("=")[0] in drop_with_value:
            i += 1
            continue
        if arg in drop_bare:
            i += 1
            continue
        out.append(arg)
        i += 1
    out += ["-m", "%s:%d" % tuple(master_address)]
    return " ".join(shlex.quote(a) for a in out)
