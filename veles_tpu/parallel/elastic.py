"""Elastic SPMD recovery plane (ISSUE 13, ROADMAP item 5 remainder).

The coordinator (master↔slave) tier survives membership churn since
PR 12, but a ``jax.distributed`` SPMD pod (:mod:`mesh` / :mod:`dp`)
dies permanently when ANY participant is lost: one SIGKILL wedges every
survivor inside a collective, and the runtime cannot re-initialize at a
new world size in-process. This module is the orchestration layer that
turns that into a bounded hiccup:

* :class:`RendezvousServer` — a tiny generation-numbered membership
  service (JSON lines over TCP, one persistent connection per host
  supervisor). A *generation* is one agreed membership: it assigns
  ``(generation, world_size, rank)``, distributes the per-generation
  ``jax.distributed`` coordinator address, and detects participant
  death through connection EOF (a SIGKILLed supervisor's kernel closes
  the socket) with heartbeat age as the partition backstop. Any death
  *breaks* the generation; survivors re-rendezvous and a new one forms
  at the surviving world size after a settle window.

* :class:`ElasticSupervisor` — the per-host process that OWNS the
  worker lifecycle. It spawns the SPMD worker with the generation's
  membership in ``VELES_ELASTIC_*`` env, watches both the worker (a
  local death is reported within one poll tick) and the rendezvous
  (a remote death arrives as a ``restart`` verdict), SIGKILLs the
  wedged worker on a break, and re-enters rendezvous — since
  ``jax.distributed`` cannot re-init in-process, restart-the-process
  IS the mesh re-formation primitive.

* :func:`run_elastic_training` — the worker-side harness: joins the
  runtime (``mesh.init_multihost`` through the shared backoff dial),
  restores the newest complete sharded checkpoint generation
  (``snapshotter.restore_latest`` — a world-size-N checkpoint
  re-assembles and re-shards at world size M), rewinds the loader to
  the last complete step boundary (``decision.prepare_resume`` +
  ``loader.reset_to_epoch_start``), and trains with a per-epoch
  sharded checkpoint cut on the trainer's ``epoch_callback`` seam.

**The determinism contract** (the loss-parity proof in
``tests/test_elastic.py``): every process derives the SAME global index
matrix from the checkpointed PRNG streams, and the mesh sharding — not
per-process bookkeeping — partitions it over the membership. So the
re-partition at a new world size is deterministic by construction,
every minibatch of a replayed epoch trains exactly once, and a killed
run restarted from its last complete checkpoint produces a loss curve
*bit-identical* to an uninterrupted run of the same mesh shape.

CLI (also the chaos harness's building blocks)::

    # membership service (one per pod; typically beside the scheduler)
    python -m veles_tpu.parallel.elastic rendezvous --port 4710 \\
        --expected 2

    # one per host: supervise the training process
    python -m veles_tpu.parallel.elastic supervise \\
        --rdzv 10.0.0.1:4710 --snapshots /ckpt/run17 -- \\
        python train_my_pod.py

    # the built-in loopback demo worker (tests / chaos legs)
    python -m veles_tpu.parallel.elastic worker-demo --out hist.json
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

from veles_tpu.envknob import env_flag, env_knob
from veles_tpu.logger import Logger
from veles_tpu.parallel.retry import retry_with_backoff

#: env contract between supervisor and worker
ENV_GEN = "VELES_ELASTIC_GEN"
ENV_WORLD = "VELES_ELASTIC_WORLD"
ENV_RANK = "VELES_ELASTIC_RANK"
ENV_COORD = "VELES_ELASTIC_COORD"
ENV_SNAPSHOTS = "VELES_ELASTIC_SNAPSHOTS"
#: job identity (ISSUE 19): the scheduler mints ONE trace id per job
#: and carries it here, so worker spans, flight records from a dying
#: gang and preempt/resume events all correlate under the job's id
ENV_TRACE = "VELES_ELASTIC_TRACE"
ENV_JOB = "VELES_ELASTIC_JOB"
ENV_TENANT = "VELES_ELASTIC_TENANT"
#: test/chaos hook: ``"<rank>:<epochs_done>"`` — the matching worker
#: SIGKILLs itself at that epoch boundary BEFORE the checkpoint is cut
#: (the deterministic mid-epoch death, like PR 12's death-on-job-8)
ENV_TEST_DIE = "VELES_ELASTIC_TEST_DIE"
#: like ENV_TEST_DIE but the worker RAISES instead of SIGKILLing
#: itself — the death leaves a flight record behind, which the trace-
#: correlation tests read back (a SIGKILL leaves only the scheduler's
#: own record)
ENV_TEST_FAIL = "VELES_ELASTIC_TEST_FAIL"


def _metrics():
    from veles_tpu.telemetry.registry import get_registry
    r = get_registry()
    return {
        "generation": r.gauge(
            "veles_mesh_generation",
            "Current elastic SPMD mesh generation number"),
        "world": r.gauge(
            "veles_spmd_world_size",
            "World size of the current SPMD generation"),
        "lost": r.counter(
            "veles_spmd_participants_lost_total",
            "SPMD participants lost (worker crash, supervisor death, "
            "heartbeat silence)", labels=("reason",)),
        "recovery": r.histogram(
            "veles_spmd_recovery_ms",
            "SPMD recovery latencies (reform: break -> new generation "
            "formed; respawn: break verdict -> replacement worker "
            "spawned; restore: checkpoint load + rewind)",
            labels=("event",)),
    }


def _free_port(host="127.0.0.1"):
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# rendezvous service
# ---------------------------------------------------------------------------


class RendezvousServer(Logger):
    """Generation-numbered membership for elastic SPMD supervisors.

    Protocol: newline-delimited JSON over one persistent TCP
    connection per supervisor. Commands: ``join`` (register / poll for
    an assignment), ``hb`` (liveness + the break verdict), ``set_coord``
    / ``coord`` (per-generation jax.distributed coordinator address,
    published by the generation's rank 0), ``worker_exit`` (local
    worker ended), ``leave`` (give up for good).

    Formation policy: generation 0 waits for ``expected`` members when
    given (the scheduler's initial pod must assemble whole); later
    generations form with whatever membership is present once it has
    been stable for ``settle_s`` (and ≥ ``min_workers``) — that is the
    world-size shrink on failure, and the grow-back when a replaced
    host rejoins. Membership loss is detected by connection EOF
    immediately, or ``heartbeat_timeout_s`` of silence as the
    partition backstop.

    The server is the pod's rendezvous anchor; its own host failing is
    out of scope here (run it under the cluster scheduler beside the
    job — the same place the pod would be rescheduled from anyway).
    """

    def __init__(self, port=0, host="127.0.0.1", min_workers=1,
                 expected=None, settle_s=1.0, heartbeat_timeout_s=5.0,
                 absorb_joins=False):
        super(RendezvousServer, self).__init__()
        self.min_workers = int(min_workers)
        self.expected = int(expected) if expected else None
        self.settle_s = float(settle_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.absorb_joins = bool(absorb_joins)
        self._lock = threading.RLock()
        self._members = {}  # token -> state dict
        self.generation = 0
        self.phase = "forming"  # forming | running | done
        self.world_size = 0
        self._coords = {}  # generation -> "host:port"
        self._last_change = time.monotonic()
        self._break_at = None
        self.lost_total = 0
        self.last_recovery_s = None
        self._metrics = _metrics()
        #: federated member telemetry (ISSUE 19): heartbeats carry
        #: SnapshotEncoder deltas, absorbed here with the SAME
        #: resync/GC/cardinality semantics as the coordinator path —
        #: created on the first beat that actually carries telemetry
        self._federation = None
        self._federation_lock = threading.Lock()
        self._stop = threading.Event()
        self._conns = set()
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(32)
        self.address = self._listener.getsockname()
        self._threads = []

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        for target, name in ((self._accept_loop, "rdzv-accept"),
                             (self._reap_loop, "rdzv-reaper")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        self.info("rendezvous serving on %s:%d (expected=%s "
                  "min_workers=%d settle=%.1fs)", self.address[0],
                  self.address[1], self.expected, self.min_workers,
                  self.settle_s)
        return self

    def stop(self):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # unwind the per-connection serving threads too: a long-lived
        # embedder (perf-gate probe, bench orchestrator, tests) must
        # not accumulate one parked readline() thread + open fd per
        # supervisor per server instance
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    # -- connection handling -----------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="rdzv-conn")
            t.start()

    def _serve_conn(self, conn):
        member = None
        with self._lock:
            self._conns.add(conn)
        try:
            fin = conn.makefile("rb")
            fout = conn.makefile("wb")
            while not self._stop.is_set():
                line = fin.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except ValueError:
                    break
                member = msg.get("member", member)
                reply = self._handle(msg)
                telemetry = msg.get("telemetry")
                if telemetry is not None and member is not None:
                    # absorbed OUTSIDE self._lock (the coordinator's
                    # _absorb_telemetry pattern): merging a delta must
                    # not serialize against membership dispatch
                    try:
                        reply.update(
                            self._absorb_telemetry(member, telemetry))
                    except Exception:
                        pass  # telemetry must never kill the beat
                with self._lock:
                    # this conn is now the member's CURRENT lifeline
                    state = self._members.get(member)
                    if state is not None:
                        state["conn_"] = conn
                fout.write(json.dumps(reply).encode() + b"\n")
                fout.flush()
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._conns.discard(conn)
                state = self._members.get(member)
                # a client that RECONNECTED under the same token owns
                # a newer lifeline: this connection's EOF is stale and
                # must not evict the rejoined member (that would break
                # a healthy re-formed generation over a TCP blip)
                stale = (state is not None and
                         state.get("conn_") is not conn)
            if member is not None and not stale and \
                    not self._stop.is_set():
                # the supervisor's lifeline died: a SIGKILLed host's
                # kernel closes this socket — the FAST death-detection
                # path (the heartbeat age check is only the partition
                # backstop). Never on server stop(): that close is
                # ours, not a death.
                self._remove_member(member, reason="connection_lost")

    # -- federated telemetry -----------------------------------------------

    def federation(self):
        """The server's :class:`FederatedRegistry` (created on first
        use — a pod that never piggybacks telemetry never pays for
        one)."""
        with self._federation_lock:
            if self._federation is None:
                from veles_tpu.telemetry.federation import \
                    FederatedRegistry
                self._federation = FederatedRegistry()
            return self._federation

    def _absorb_telemetry(self, member, delta):
        """Merge one beat-carried delta; returns ack hints for the
        reply (``{"resync": True}`` after a sequence gap)."""
        hints = self.federation().apply(member, delta)
        with self._lock:
            live = member in self._members
        if not live:
            # reaped between dispatch and merge: the feed must not
            # outlive the membership (same liveness re-check the
            # coordinator does after its out-of-lock merge)
            self._federation.remove_slave(member)
            return {}
        return hints or {}

    # -- protocol ----------------------------------------------------------

    def _handle(self, msg):
        cmd = msg.get("cmd")
        member = msg.get("member")
        with self._lock:
            state = self._members.get(member)
            if state is not None:
                # EVERY command is proof of life — a supervisor parked
                # in a long coord wait must not be reaped for silence
                state["last_seen"] = time.monotonic()
            if cmd == "join":
                return self._join(member)
            if cmd == "hb":
                return self._heartbeat(member, msg.get("gen"))
            if cmd == "set_coord":
                self._coords[int(msg["gen"])] = msg["addr"]
                return {"status": "ok"}
            if cmd == "coord":
                gen = int(msg["gen"])
                return {"status": "ok", "addr": self._coords.get(gen),
                        "current_gen": self.generation,
                        "phase": self.phase}
            if cmd == "worker_exit":
                return self._worker_exit(member, msg.get("gen"),
                                         int(msg.get("code", 1)))
            if cmd == "leave":
                self._remove_member(member, reason="leave")
                return {"status": "ok"}
        return {"status": "error", "error": "unknown cmd %r" % cmd}

    def _join(self, member):
        """Register/refresh a member. Caller holds ``self._lock``
        (every ``_handle`` dispatch runs under it)."""
        if self.phase == "done":
            return {"status": "done"}
        state = self._members.get(member)
        if state is None:
            state = self._members[member] = {
                "state": "waiting", "rank": None, "gen": None,
                "last_seen": time.monotonic()}
            self._last_change = time.monotonic()
            self.info("member %s joined (now %d waiting)", member,
                      sum(1 for m in self._members.values()
                          if m["state"] == "waiting"))
            if self.phase == "running" and self.absorb_joins:
                self._break_generation("absorb_join", lost=False)
        state["last_seen"] = time.monotonic()
        if self.phase == "running" and state["gen"] == self.generation:
            return {"status": "assigned", "gen": self.generation,
                    "world": self.world_size, "rank": state["rank"]}
        if state["state"] != "waiting":
            state["state"] = "waiting"
            state["gen"] = None
        self._maybe_form()
        if self.phase == "running" and state["gen"] == self.generation:
            return {"status": "assigned", "gen": self.generation,
                    "world": self.world_size, "rank": state["rank"]}
        return {"status": "wait"}

    def _heartbeat(self, member, gen):
        state = self._members.get(member)
        if self.phase == "done":
            return {"status": "done"}
        if state is None:
            return {"status": "restart"}  # reaped: re-join from scratch
        state["last_seen"] = time.monotonic()
        if self.phase == "running" and state["gen"] == self.generation \
                and gen == self.generation:
            return {"status": "ok"}
        return {"status": "restart"}

    def _worker_exit(self, member, gen, code):
        state = self._members.get(member)
        if state is None:
            # reaped while the worker was dying: whatever killed the
            # membership is the root cause, not this worker
            return {"status": "restart", "stale": True}
        state["last_seen"] = time.monotonic()
        if gen != self.generation or self.phase != "running":
            # the generation was ALREADY broken when this worker died:
            # its death is collateral (a peer loss aborted its
            # collective), not a crash of its own — the supervisor
            # must not charge it against the crash budget
            return {"status": "restart", "stale": True}
        if code == 0:
            state["state"] = "done"
            current = [m for m in self._members.values()
                       if m["gen"] == self.generation]
            if current and all(m["state"] == "done" for m in current):
                self.phase = "done"
                self.info("generation %d complete (world %d)",
                          self.generation, self.world_size)
            return {"status": "done" if self.phase == "done" else "ok"}
        self._break_generation("worker_crash(%s, rc=%s)"
                               % (member, code))
        return {"status": "restart"}

    # -- membership state machine ------------------------------------------

    def _remove_member(self, member, reason):
        with self._lock:
            state = self._members.pop(member, None)
            if state is None:
                return
            self._last_change = time.monotonic()
            in_current = (self.phase == "running" and
                          state["gen"] == self.generation)
            self.info("member %s removed (%s)%s", member, reason,
                      " — breaking generation %d" % self.generation
                      if in_current else "")
            if in_current:
                self._break_generation("%s(%s)" % (reason, member))
        with self._federation_lock:
            federation = self._federation
        if federation is not None:
            # GC the dead member's federated feed with the membership
            federation.remove_slave(member)

    def _break_generation(self, reason, lost=True):
        """A participant of the RUNNING generation is gone (or a join
        must be absorbed): bump the generation and send every
        survivor back through rendezvous. Caller holds
        ``self._lock``."""
        if self.phase != "running":
            return
        if lost:
            self.lost_total += 1
            self._metrics["lost"].labels(
                reason=reason.split("(")[0]).inc()
        self.warning("generation %d broken: %s — re-forming at the "
                     "surviving world size", self.generation, reason)
        self.generation += 1
        self.phase = "forming"
        self._break_at = time.monotonic()
        self._last_change = time.monotonic()
        for state in self._members.values():
            state["state"] = "waiting"
            state["gen"] = None
            state["rank"] = None

    def _maybe_form(self):
        if self.phase != "forming":
            return
        waiting = sorted(token for token, m in self._members.items()
                         if m["state"] == "waiting")
        if not waiting:
            return
        now = time.monotonic()
        if self.generation == 0 and self.expected:
            # the initial pod assembles WHOLE: a slow-starting host
            # must not get raced into a shrunken first generation
            if len(waiting) < self.expected:
                return
        else:
            if len(waiting) < self.min_workers:
                return
            full = self.expected is not None and \
                len(waiting) >= self.expected
            if not full and now - self._last_change < self.settle_s:
                return
        for rank, token in enumerate(waiting):
            state = self._members[token]
            state["state"] = "running"
            state["gen"] = self.generation
            state["rank"] = rank
        self.world_size = len(waiting)
        self.phase = "running"
        self._metrics["generation"].set(self.generation)
        self._metrics["world"].set(self.world_size)
        if self._break_at is not None:
            self.last_recovery_s = now - self._break_at
            self._metrics["recovery"].labels(event="reform").observe(
                self.last_recovery_s * 1e3)
            self._break_at = None
        self.info("generation %d formed: world=%d members=%s",
                  self.generation, self.world_size, waiting)

    def _reap_loop(self):
        while not self._stop.is_set():
            time.sleep(0.25)
            with self._lock:
                if self.phase == "done":
                    continue
                now = time.monotonic()
                stale = [token for token, m in self._members.items()
                         if now - m["last_seen"] >
                         self.heartbeat_timeout_s]
            for token in stale:
                self._remove_member(token, reason="heartbeat_timeout")
            with self._lock:
                self._maybe_form()


class RendezvousClient(object):
    """The supervisor's side of the protocol (one persistent
    connection; the dial and any reconnect go through the shared
    jittered-backoff helper)."""

    def __init__(self, address, member, dial_budget_s=60.0):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host or "127.0.0.1", int(port))
        self.address = tuple(address)
        self.member = member
        self.dial_budget_s = dial_budget_s
        self._lock = threading.Lock()
        self._sock = None
        self._fin = self._fout = None
        self._closed = False
        self._connect(dial_budget_s)

    def _connect(self, budget_s):
        def attempt():
            sock = socket.create_connection(self.address, timeout=10.0)
            self._sock = sock
            self._fin = sock.makefile("rb")
            self._fout = sock.makefile("wb")

        retry_with_backoff(
            attempt, budget_s,
            give_up=lambda e: self._closed,
            describe="could not reach the rendezvous at %s:%d"
                     % self.address)

    def _request(self, msg, reconnect_budget_s=10.0):
        msg = dict(msg, member=self.member)

        def attempt():
            if self._sock is None:
                self._connect(reconnect_budget_s)
            try:
                self._fout.write(json.dumps(msg).encode() + b"\n")
                self._fout.flush()
                line = self._fin.readline()
                if not line:
                    raise ConnectionError("rendezvous closed the "
                                          "connection")
                return json.loads(line)
            except (OSError, ValueError) as e:
                self._teardown()
                raise ConnectionError(str(e))

        with self._lock:
            return retry_with_backoff(
                attempt, reconnect_budget_s, base_s=0.1,
                give_up=lambda e: self._closed,
                describe="rendezvous request to %s:%d failed"
                         % self.address)

    def _teardown(self):
        for f in (self._fin, self._fout, self._sock):
            try:
                if f is not None:
                    f.close()
            except OSError:
                pass
        self._sock = self._fin = self._fout = None

    # -- commands ----------------------------------------------------------

    def join_wait(self, poll_s=0.2, timeout_s=None):
        """Block until this member is assigned into a generation.
        Returns the assignment dict, or ``None`` when the whole run
        completed while we waited."""
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        while True:
            reply = self._request({"cmd": "join"})
            status = reply.get("status")
            if status == "assigned":
                return reply
            if status == "done":
                return None
            if deadline and time.monotonic() > deadline:
                raise TimeoutError("rendezvous did not form a "
                                   "generation in %.0fs" % timeout_s)
            time.sleep(poll_s)

    def heartbeat(self, gen):
        return self.heartbeat_full(gen).get("status")

    def heartbeat_full(self, gen, telemetry=None):
        """Full heartbeat reply dict; ``telemetry`` (a SnapshotEncoder
        delta) piggybacks on the beat — the reply may carry a
        ``resync`` hint the caller must feed back to its encoder."""
        msg = {"cmd": "hb", "gen": gen}
        if telemetry is not None:
            msg["telemetry"] = telemetry
        return self._request(msg)

    def set_coord(self, gen, addr):
        self._request({"cmd": "set_coord", "gen": gen, "addr": addr})

    def get_coord_wait(self, gen, poll_s=0.1, timeout_s=60.0):
        """The generation's jax.distributed coordinator address, or
        ``None`` when the generation was superseded while waiting."""
        deadline = time.monotonic() + timeout_s
        while True:
            reply = self._request({"cmd": "coord", "gen": gen})
            if reply.get("addr"):
                return reply["addr"]
            if reply.get("current_gen", gen) != gen or \
                    reply.get("phase") == "done":
                return None
            if time.monotonic() > deadline:
                return None
            time.sleep(poll_s)

    def worker_exit(self, gen, code):
        """Full reply dict: ``status`` plus ``stale`` when the
        generation had already broken before this report."""
        return self._request({"cmd": "worker_exit", "gen": gen,
                              "code": code})

    def leave(self):
        try:
            self._request({"cmd": "leave"})
        except ConnectionError:
            pass

    def close(self):
        self._closed = True
        self._teardown()


# ---------------------------------------------------------------------------
# the per-host supervisor
# ---------------------------------------------------------------------------


class ElasticSupervisor(Logger):
    """Owns one SPMD worker process through membership churn.

    Lifecycle per generation: rendezvous -> (rank 0 publishes a fresh
    ``jax.distributed`` coordinator port) -> spawn the worker with the
    membership in env -> watch. A ``restart`` verdict (someone else
    died, or a join was absorbed) SIGKILLs the worker — it is wedged
    in a collective or about to be — and re-enters rendezvous; a local
    worker death is reported and counts against ``max_restarts``
    (regroup restarts do not: they are the recovery working, not a
    crash loop). Workers run in their own session so the kill takes
    the whole worker process group.
    """

    def __init__(self, rdzv_address, worker_argv, snapshot_dir=None,
                 member=None, max_restarts=3, worker_env=None,
                 poll_s=0.2, coord_host="127.0.0.1",
                 dial_budget_s=60.0, announce=False):
        super(ElasticSupervisor, self).__init__()
        self.rdzv_address = rdzv_address
        self.worker_argv = list(worker_argv)
        self.snapshot_dir = snapshot_dir
        self.member = member or ("%s-%d" % (socket.gethostname(),
                                            os.getpid()))
        self.max_restarts = int(max_restarts)
        self.worker_env = dict(worker_env or {})
        self.poll_s = float(poll_s)
        self.coord_host = coord_host
        self.dial_budget_s = dial_budget_s
        self.announce = announce
        self.worker = None  # current subprocess.Popen
        self.generation = None
        self._metrics = _metrics()
        self._detect_t = None
        # ISSUE 19: the job trace id rides VELES_ELASTIC_TRACE from
        # the scheduler through this supervisor into the worker env
        # (os.environ is copied into every spawn) — our own spans and
        # flight records correlate under it too
        self.trace_id = env_knob(ENV_TRACE)
        if self.trace_id:
            from veles_tpu.telemetry import tracing
            tracing.set_default_trace_id(self.trace_id)
        # heartbeat-piggybacked telemetry (same flag as the
        # coordinator tier: VELES_FEDERATION=0 turns it off fleet-wide)
        self._encoder = None
        if env_flag("VELES_FEDERATION", True):
            from veles_tpu.telemetry.federation import SnapshotEncoder
            self._encoder = SnapshotEncoder()

    def _announce(self, name, **fields):
        if not self.announce:
            return
        print("EVENT %s t=%.6f %s"
              % (name, time.time(),
                 " ".join("%s=%s" % kv for kv in sorted(fields.items()))),
              file=sys.stderr, flush=True)

    # -- worker lifecycle --------------------------------------------------

    def _spawn_worker(self, gen, world, rank, coord):
        env = dict(os.environ)
        env.update(self.worker_env)
        env[ENV_GEN] = str(gen)
        env[ENV_WORLD] = str(world)
        env[ENV_RANK] = str(rank)
        if coord:
            env[ENV_COORD] = coord
        else:
            env.pop(ENV_COORD, None)
        if self.snapshot_dir:
            env[ENV_SNAPSHOTS] = self.snapshot_dir
        proc = subprocess.Popen(self.worker_argv, env=env,
                                start_new_session=True)
        if self._detect_t is not None:
            self._metrics["recovery"].labels(event="respawn").observe(
                (time.monotonic() - self._detect_t) * 1e3)
            self._detect_t = None
        self.info("gen %d: spawned worker pid %d (world=%d rank=%d "
                  "coord=%s)", gen, proc.pid, world, rank, coord)
        self._announce("spmd_worker", pid=proc.pid, gen=gen,
                       world=world, rank=rank)
        return proc

    def _kill_worker(self):
        proc = self.worker
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.kill()
            except OSError:
                pass
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass

    # -- the loop ----------------------------------------------------------

    def run(self):
        """Supervise until the pod completes (returns 0) or this host
        gives up (crash budget exhausted / rendezvous unreachable:
        returns 1)."""
        client = RendezvousClient(self.rdzv_address, self.member,
                                  dial_budget_s=self.dial_budget_s)
        crashes = 0
        try:
            while True:
                assignment = client.join_wait()
                if assignment is None:
                    return 0  # pod completed while we waited
                gen = assignment["gen"]
                world = assignment["world"]
                rank = assignment["rank"]
                self.generation = gen
                self._metrics["generation"].set(gen)
                self._announce("spmd_gen", gen=gen, world=world,
                               rank=rank)
                coord = None
                if world > 1:
                    if rank == 0:
                        coord = "%s:%d" % (self.coord_host,
                                           _free_port(self.coord_host))
                        client.set_coord(gen, coord)
                    else:
                        coord = client.get_coord_wait(gen)
                        if coord is None:  # superseded while waiting
                            continue
                self.worker = self._spawn_worker(gen, world, rank,
                                                 coord)
                verdict = self._watch(client, gen)
                if verdict == "restart":
                    self._detect_t = time.monotonic()
                    self._kill_worker()
                    self._announce("spmd_restart", gen=gen)
                    continue
                if verdict == "done":
                    return 0
                code = self.worker.returncode
                reply = client.worker_exit(gen, code)
                status = reply.get("status")
                if code == 0:
                    if status == "done":
                        return 0
                    # our worker finished but the pod has not: ride
                    # along until it completes or a late break pulls
                    # us back in (a restored-complete worker then
                    # serves its done state instantly)
                    while status not in ("done", "restart"):
                        time.sleep(self.poll_s)
                        status = client.heartbeat(gen)
                    if status == "done":
                        return 0
                    continue
                if reply.get("stale"):
                    # the generation had ALREADY broken when our
                    # worker aborted its collective — a regroup, not
                    # an own crash; it stays off the crash budget
                    self._detect_t = time.monotonic()
                    self._announce("spmd_restart", gen=gen,
                                   collateral=1)
                    continue
                crashes += 1
                self._detect_t = time.monotonic()
                self.warning("gen %d: worker died rc=%s (crash %d/%d)",
                             gen, code, crashes, self.max_restarts)
                self._announce("spmd_worker_died", gen=gen, code=code,
                               crashes=crashes)
                try:
                    # the supervisor's link in the correlated flight
                    # chain: worker record -> THIS -> the scheduler's
                    # sched_job_failed, all under the job's trace id
                    from veles_tpu.telemetry import flight
                    flight.get_recorder().dump(
                        "elastic_worker_died", gen=gen, rank=rank,
                        code=code, member=self.member,
                        crashes=crashes, trace_id=self.trace_id)
                except Exception:
                    pass  # the black box must never kill recovery
                if crashes > self.max_restarts:
                    self.error("crash budget exhausted — leaving the "
                               "pod")
                    client.leave()
                    return 1
        except (ConnectionError, TimeoutError) as e:
            self.error("rendezvous lost: %s", e)
            return 1
        finally:
            self._kill_worker()
            client.close()

    def _watch(self, client, gen):
        """Poll worker + rendezvous until one of them moves. Returns
        ``"exited"`` (local worker ended), ``"restart"`` (the
        generation broke elsewhere) or ``"done"``. Every beat carries
        this process's metric delta for the rendezvous anchor's
        federated view; encoding failures never break the beat."""
        while True:
            if self.worker.poll() is not None:
                return "exited"
            telemetry = None
            if self._encoder is not None:
                try:
                    telemetry = self._encoder.encode()
                except Exception:
                    telemetry = None
            reply = client.heartbeat_full(gen, telemetry=telemetry)
            if reply.get("resync") and self._encoder is not None:
                self._encoder.mark_resync()
            status = reply.get("status")
            if status == "restart":
                return "restart"
            if status == "done":
                return "done"
            time.sleep(self.poll_s)


# ---------------------------------------------------------------------------
# worker-side harness
# ---------------------------------------------------------------------------


class ElasticContext(object):
    """The membership a supervisor handed this worker process."""

    def __init__(self, generation, world_size, rank, coordinator=None,
                 snapshot_dir=None):
        self.generation = int(generation)
        self.world_size = int(world_size)
        self.rank = int(rank)
        self.coordinator = coordinator
        self.snapshot_dir = snapshot_dir

    def __repr__(self):
        return ("ElasticContext(gen=%d, world=%d, rank=%d, coord=%r)"
                % (self.generation, self.world_size, self.rank,
                   self.coordinator))


def worker_context():
    """The :class:`ElasticContext` from ``VELES_ELASTIC_*`` env, or
    ``None`` when this process is not supervised (plain standalone
    training — every elastic code path degrades to a no-op)."""
    world = env_knob(ENV_WORLD)
    if not world:
        return None
    return ElasticContext(
        generation=env_knob(ENV_GEN, 0),
        world_size=world,
        rank=env_knob(ENV_RANK, 0),
        coordinator=env_knob(ENV_COORD),
        snapshot_dir=env_knob(ENV_SNAPSHOTS))


def init_distributed(ctx):
    """Join this generation's ``jax.distributed`` runtime (no-op at
    world size 1). The dial rides the shared jittered-backoff helper,
    so a worker restarted a beat before its generation's coordinator
    is listening does not lose the race."""
    from veles_tpu.parallel.mesh import init_multihost
    ok = init_multihost(ctx.coordinator, num_processes=ctx.world_size,
                        process_id=ctx.rank)
    metrics = _metrics()
    metrics["generation"].set(ctx.generation)
    metrics["world"].set(ctx.world_size)
    return ok


def _test_die_hook(ctx, trainer):
    spec = env_knob(ENV_TEST_DIE)
    if not spec or ctx is None:
        return
    rank, _, epochs = spec.partition(":")
    if int(rank) == ctx.rank and \
            int(epochs) == len(trainer.decision.epoch_history):
        # deterministic mid-epoch death for the chaos/parity tests:
        # the epoch just computed is NOT yet checkpointed, so the
        # restart must rewind and replay it
        os.kill(os.getpid(), signal.SIGKILL)


def _test_fail_hook(ctx, trainer):
    spec = env_knob(ENV_TEST_FAIL)
    if not spec or ctx is None:
        return
    rank, _, epochs = spec.partition(":")
    if int(rank) == ctx.rank and \
            int(epochs) == len(trainer.decision.epoch_history):
        # the RAISING twin of _test_die_hook: the worker dies through
        # the exception path, so its flight record (carrying the job
        # trace id) exists for the correlation tests to read back
        raise RuntimeError(
            "induced worker failure (%s=%s)" % (ENV_TEST_FAIL, spec))


class _MetricsPusher(object):
    """Rank 0's scheduler rollup feed (ISSUE 19): delta-encode the
    local registry and POST it to the scheduler's loopback control
    endpoint (``VELES_SCHED_METRICS_URL``, set by the scheduler in
    the gang env) every ``VELES_SCHED_METRICS_S`` seconds. Every
    failure is swallowed — the scheduler being down must never stall
    or kill training.

    The feed survives a scheduler RESTART (ISSUE 20): consecutive
    push failures back off with the fleet-wide jittered exponential
    shape (never give up, never hot-spin a refused connection), and
    the first successful push after an outage is a full resync — a
    recovered scheduler has an empty federated view, and waiting for
    its gap-detect ``{"resync": True}`` ack would heal one push later
    than marking the resync ourselves."""

    #: failure backoff bounds: base = one interval (min 0.25 s so a
    #: very fast test interval still decays), cap well under the
    #: scheduler's restart time scale
    BACKOFF_CAP_S = 10.0

    def __init__(self, url, job, interval_s):
        from veles_tpu.telemetry.federation import SnapshotEncoder
        self.url = url
        self.job = job
        self.interval_s = interval_s
        self._encoder = SnapshotEncoder()
        self._failures = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sched-metrics-push")
        self._thread.start()

    def _push(self):
        import urllib.request
        delta = self._encoder.encode()
        if delta is None:
            return False
        body = json.dumps({"job": self.job,
                           "telemetry": delta}).encode("utf-8")
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5.0) as resp:
            reply = json.loads(resp.read().decode("utf-8"))
        if reply.get("resync"):
            self._encoder.mark_resync()
        return True

    def _loop(self):
        from veles_tpu.parallel.retry import backoff_delay
        wait = self.interval_s
        while not self._stop.wait(wait):
            try:
                pushed = self._push()
            except Exception:
                # bounded jittered retry: exponent capped so the wait
                # can't overflow, sleep capped at BACKOFF_CAP_S
                self._failures += 1
                wait = backoff_delay(
                    min(self._failures - 1, 16),
                    base_s=max(self.interval_s, 0.25),
                    cap_s=self.BACKOFF_CAP_S)
            else:
                if pushed and self._failures:
                    # back from an outage: the scheduler may have
                    # restarted with an empty federated view — make
                    # the next delta a full snapshot
                    self._failures = 0
                    self._encoder.mark_resync()
                wait = self.interval_s

    def stop(self):
        self._stop.set()
        try:
            # one final flush so the last epoch's loss reaches the
            # scheduler even when the job exits between intervals
            self._push()
        except Exception:
            pass
        self._thread.join(timeout=5)


def _start_metrics_pusher(ctx):
    """The pusher when this process should feed the scheduler: a
    ``VELES_SCHED_METRICS_URL`` is present and this is the gang's
    rank 0 (or an unsupervised standalone run)."""
    url = env_knob("VELES_SCHED_METRICS_URL")
    if not url or (ctx is not None and ctx.rank != 0):
        return None
    if not env_flag("VELES_FEDERATION", True):
        return None
    interval_s = env_knob("VELES_SCHED_METRICS_S", 0.5, parse=float,
                          on_error="default")
    job = env_knob(ENV_JOB, "")
    try:
        return _MetricsPusher(url, job, interval_s)
    except Exception:
        return None


def save_elastic_checkpoint(trainer, ctx, params, states):
    """Cut one sharded checkpoint generation at a complete step
    boundary: every process writes its own shards, a cross-process
    barrier orders the writes before rank 0's manifest commit."""
    import jax
    from veles_tpu import snapshotter
    records = trainer.checkpoint_records(params, states)
    epoch = snapshotter.wf_epoch(trainer.workflow)
    barrier = None
    if ctx.world_size > 1:
        def barrier():
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                "veles-elastic-ckpt-g%d-e%d" % (ctx.generation, epoch))
    return snapshotter.save_snapshot_sharded(
        trainer.workflow, ctx.snapshot_dir, records,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        tag="_g%d" % ctx.generation, barrier=barrier, link_tag="",
        manifest_extra={"world_size": ctx.world_size,
                        "generation": ctx.generation,
                        # the SOURCE mesh shape, so a restore at a new
                        # world size can log/verify the A->B reshard
                        "mesh_axes": {str(k): int(v) for k, v in
                                      dict(trainer.mesh.shape).items()}
                        if getattr(trainer, "mesh", None) is not None
                        else None})


def run_elastic_training(build_workflow, device=None, mesh=None,
                         trainer_cls=None, trainer_kwargs=None,
                         on_epoch=None, max_epochs=None):
    """Train under the elastic supervisor: restore -> rewind -> train
    with per-epoch sharded checkpoints. Returns the epoch history.

    ``build_workflow()`` must return an INITIALIZED workflow built
    from fixed seeds — on a fresh start every SPMD process derives
    identical initial state from it. On a restart the newest COMPLETE
    checkpoint generation is restored instead (re-assembled and
    re-sharded whatever world size wrote it), the loader rewinds to
    the last complete step boundary, and the PRNG registry restored
    with the snapshot makes the replayed index matrix — and therefore
    its deterministic re-partition over the new membership — identical
    to the lost run's. Without a supervisor (no ``VELES_ELASTIC_*``
    env) this is plain standalone training."""
    import logging
    log = logging.getLogger("elastic")
    ctx = worker_context()
    trace_id = env_knob(ENV_TRACE)
    if trace_id:
        from veles_tpu.telemetry import tracing
        tracing.set_default_trace_id(trace_id)
    if ctx is not None:
        init_distributed(ctx)
    pusher = _start_metrics_pusher(ctx)
    try:
        return _run_elastic_training(
            log, ctx, build_workflow, device=device, mesh=mesh,
            trainer_cls=trainer_cls, trainer_kwargs=trainer_kwargs,
            on_epoch=on_epoch, max_epochs=max_epochs)
    except Exception as e:
        try:
            # the worker's link in the correlated flight chain: its
            # record names the generation/rank AND the job trace id,
            # so an operator can walk worker -> supervisor ->
            # scheduler records of one incident
            from veles_tpu.telemetry import flight
            flight.get_recorder().dump(
                "elastic_worker_failed",
                error="%s: %s" % (type(e).__name__, e),
                generation=ctx.generation if ctx else None,
                rank=ctx.rank if ctx else None,
                job=env_knob(ENV_JOB), trace_id=trace_id)
        except Exception:
            pass
        raise
    finally:
        if pusher is not None:
            pusher.stop()


def _run_elastic_training(log, ctx, build_workflow, device=None,
                          mesh=None, trainer_cls=None,
                          trainer_kwargs=None, on_epoch=None,
                          max_epochs=None):
    snapdir = ctx.snapshot_dir if ctx is not None else None
    workflow = None
    if snapdir:
        from veles_tpu import snapshotter
        t0 = time.perf_counter()
        try:
            workflow, restored_path = snapshotter.restore_latest(snapdir)
        except FileNotFoundError:
            workflow = None
    fresh = workflow is None
    if fresh:
        workflow = build_workflow()
    else:
        if device is None:
            from veles_tpu.backends import Device
            device = Device()
        workflow.initialize(device=device)
        resume_epoch = workflow.decision.prepare_resume()
        _metrics()["recovery"].labels(event="restore").observe(
            (time.perf_counter() - t0) * 1e3)
        if resume_epoch is None:
            log.info("restored run %s is already complete",
                     restored_path)
            return workflow.decision.epoch_history
        workflow.loader.reset_to_epoch_start(resume_epoch)
        log.info("restored %s; resuming from the start of epoch %d "
                 "at world size %d", restored_path, resume_epoch,
                 ctx.world_size)
    if mesh is None:
        # the launcher-SPMD tier's named batch×model mesh (ISSUE 15):
        # an elastic world-size change = this mesh re-built over the
        # surviving devices + reshard-on-restore through pull_params'
        # measured re-placement (parallel/reshard.py)
        from veles_tpu.parallel.gspmd import gspmd_mesh
        mesh = gspmd_mesh()
    if trainer_cls is None:
        from veles_tpu.parallel.gspmd import GSPMDTrainer
        trainer_cls = GSPMDTrainer
    trainer = trainer_cls(workflow, mesh=mesh,
                          **(trainer_kwargs or {}))
    if snapdir:
        def epoch_callback(tr, params, states):
            if on_epoch is not None:
                on_epoch(tr, params, states)
            _test_fail_hook(ctx, tr)
            _test_die_hook(ctx, tr)
            save_elastic_checkpoint(tr, ctx, params, states)

        trainer.epoch_callback = epoch_callback
        initial_state = None
        if fresh:
            # the generation-initial restart point: a death before the
            # first epoch closes must rewind to the seed state, not
            # re-randomize — this checkpoint carries the post-init
            # params and PRNG streams every process agreed on. The
            # pulled state is handed to train() so the model-sized
            # host→device placement happens once, not twice.
            initial_state = trainer.pull_params()
            save_elastic_checkpoint(trainer, ctx, *initial_state)
        return trainer.train(max_epochs=max_epochs,
                             initial_state=initial_state)
    if on_epoch is not None:
        trainer.epoch_callback = on_epoch
    return trainer.train(max_epochs=max_epochs)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _supervise_main(argv):
    import argparse
    worker_argv = None
    if "--" in argv:
        split = argv.index("--")
        worker_argv = argv[split + 1:]
        argv = argv[:split]
    parser = argparse.ArgumentParser(
        prog="veles-elastic supervise",
        description="per-host elastic SPMD supervisor")
    parser.add_argument("--rdzv", required=True,
                        metavar="HOST:PORT",
                        help="rendezvous server address")
    parser.add_argument("--member", default=None,
                        help="stable member token (default host-pid)")
    parser.add_argument("--snapshots", default=None, metavar="DIR",
                        help="sharded checkpoint directory (shared fs)")
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="own-worker crash budget (regroup "
                             "restarts are free)")
    parser.add_argument("--worker-env", action="append", default=[],
                        metavar="K=V", help="extra worker env "
                        "(repeatable)")
    parser.add_argument("--coord-host", default="127.0.0.1",
                        help="address rank 0 publishes for "
                             "jax.distributed")
    parser.add_argument("--poll-s", type=float, default=0.2)
    args = parser.parse_args(argv)
    if not worker_argv:
        parser.error("worker command required after `--`")
    env = {}
    for item in args.worker_env:
        key, _, value = item.partition("=")
        env[key] = value
    supervisor = ElasticSupervisor(
        args.rdzv, worker_argv, snapshot_dir=args.snapshots,
        member=args.member, max_restarts=args.max_restarts,
        worker_env=env, poll_s=args.poll_s,
        coord_host=args.coord_host, announce=True)
    return supervisor.run()


def _rendezvous_main(argv):
    import argparse
    parser = argparse.ArgumentParser(
        prog="veles-elastic rendezvous",
        description="elastic SPMD rendezvous anchor")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--min-workers", type=int, default=1)
    parser.add_argument("--expected", type=int, default=None)
    parser.add_argument("--settle-s", type=float, default=1.0)
    parser.add_argument("--hb-timeout-s", type=float, default=5.0)
    parser.add_argument("--absorb-joins", action="store_true")
    args = parser.parse_args(argv)
    server = RendezvousServer(
        port=args.port, host=args.host, min_workers=args.min_workers,
        expected=args.expected, settle_s=args.settle_s,
        heartbeat_timeout_s=args.hb_timeout_s,
        absorb_joins=args.absorb_joins).start()
    print("RENDEZVOUS %s:%d" % server.address, flush=True)
    try:
        while server.phase != "done":
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


class _DemoProvider(object):
    """Deterministic synthetic digits for the demo worker. A
    module-level class (not a closure): the loader pickles it into
    every checkpoint."""

    def __init__(self, samples, valid):
        self.samples = samples
        self.valid = valid

    def __call__(self):
        import numpy
        rng = numpy.random.RandomState(5)

        def mk(n):
            return (rng.rand(n, 8, 8).astype(numpy.float32),
                    rng.randint(0, 10, n).astype(numpy.int32))

        tx, ty = mk(self.samples)
        vx, vy = mk(self.valid)
        return tx, ty, vx, vy


def _worker_demo_main(argv):
    """The loopback demo worker: a tiny seeded MnistWorkflow driven
    through :func:`run_elastic_training` — tests and the chaos
    harness's SPMD legs both use it (with a supervisor), and the loss
    parity baselines run it bare (without one)."""
    import argparse
    parser = argparse.ArgumentParser(prog="veles-elastic worker-demo")
    parser.add_argument("--out", required=True,
                        help="write the per-epoch validation curve "
                             "here (JSON)")
    parser.add_argument("--samples", type=int, default=640)
    parser.add_argument("--valid", type=int, default=128)
    parser.add_argument("--mb", type=int, default=64)
    parser.add_argument("--layers", type=int, default=16)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.08)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--epoch-sleep", type=float, default=0.0,
                        help="sleep per epoch boundary (gives chaos "
                             "legs a mid-run window to kill into)")
    args = parser.parse_args(argv)
    # CRITICAL ordering: nothing may initialize a jax backend before
    # run_elastic_training has called jax.distributed.initialize —
    # so no Device construction or devices() query happens here, only
    # config. The supervisor already put the backend choice in env.
    os.environ.setdefault("VELES_TPU_BACKEND", "cpu")
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.mnist import MnistWorkflow

    def build():
        prng.get().seed(args.seed)
        prng.get("loader").seed(args.seed + 1)
        wf = MnistWorkflow(DummyLauncher(),
                           provider=_DemoProvider(args.samples,
                                                  args.valid),
                           layers=(args.layers,),
                           minibatch_size=args.mb,
                           learning_rate=args.lr,
                           max_epochs=args.epochs)
        wf.initialize(device=Device(backend="cpu"))
        return wf

    on_epoch = None
    if args.epoch_sleep:
        def on_epoch(trainer, params, states):
            time.sleep(args.epoch_sleep)

    history = run_elastic_training(build, on_epoch=on_epoch)
    curve = [e["validation"]["normalized"] for e in history]
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fout:
        json.dump(curve, fout)
    os.replace(tmp, args.out)
    print("worker-demo done: %s" % curve, flush=True)
    return 0


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd == "supervise":
        return _supervise_main(rest)
    if cmd == "rendezvous":
        return _rendezvous_main(rest)
    if cmd == "worker-demo":
        return _worker_demo_main(rest)
    print("unknown command %r (supervise | rendezvous | worker-demo)"
          % cmd, file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
