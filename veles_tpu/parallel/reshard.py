"""Array redistribution between device layouts (ISSUE 15).

The Zhuang et al. recipe (PAPERS.md, "Memory-efficient array
redistribution through portable collective communication"): any
layout change decomposes into all-gather / dynamic-slice /
collective-permute primitives, and the right decomposition is the
compiler's job — under a single controller, ``jax.device_put`` onto
the target ``NamedSharding`` lowers to exactly that minimal program
(multi-controller placements go through
:func:`veles_tpu.parallel.mesh.put_global`'s per-process shard
contribution instead). What this module adds is the *seam*: one
measured primitive every layout move in the repo goes through, so

* sharded-checkpoint restore at a DIFFERENT mesh shape (a world-size-N
  generation re-placed onto a world-size-M mesh — the elastic
  supervisor's reshard-on-restore),
* train→serve moves (model-axis-sharded training params gathered to
  the replicated layout serving replicas consume),
* the per-run host→mesh parameter placement (``pull_params``),

all show up in ``veles_reshard_ms{src,dst}`` instead of hiding inside
whatever code path happened to call ``device_put``.

Labels are LAYOUTS, not meshes: ``P(batch)``/``P(_,model)``/
``replicated``/``host``/``committed`` — bounded cardinality however
many mesh shapes a run moves between.
"""

import time

import jax
import numpy

from veles_tpu.parallel.mesh import put_global
from veles_tpu.telemetry import tracing


def _registry():
    from veles_tpu.telemetry.registry import get_registry
    return get_registry()


def reshard_histogram():
    return _registry().histogram(
        "veles_reshard_ms",
        "Array redistribution time between device layouts",
        labels=("src", "dst"))


def layout_label(value_or_sharding):
    """Bounded-cardinality layout label for a sharding, array or host
    value: ``replicated``, ``P(batch)``, ``P(_,model)``, ``host`` (not
    on any device yet), or ``committed`` (a device placement without a
    named spec — single-device arrays)."""
    value = value_or_sharding
    if isinstance(value, jax.Array):
        value = value.sharding
    elif not isinstance(value, jax.sharding.Sharding):
        return "host"
    spec = getattr(value, "spec", None)
    if spec is None:
        return "committed"
    parts = []
    for entry in spec:
        if entry is None:
            parts.append("_")
        elif isinstance(entry, (tuple, list)):
            parts.append("+".join(str(e) for e in entry))
        else:
            parts.append(str(entry))
    # trailing unsharded dims are elided by PartitionSpec; P() means
    # fully replicated whatever the rank
    while parts and parts[-1] == "_":
        parts.pop()
    return "P(%s)" % ",".join(parts) if parts else "replicated"


def reshard(value, sharding, *, block=False):
    """Move ``value`` (host ndarray or ``jax.Array`` in any layout) to
    ``sharding``, measured as ``veles_reshard_ms{src,dst}``.

    ``block=True`` waits for the moved buffers (honest end-to-end
    reshard time — checkpoint restore, train→serve moves);
    ``block=False`` records the dispatch time only, preserving async
    transfer for hot paths (streamed shard placement, per-run
    parameter pull) exactly like ``veles_prefetch_h2d_ms`` does.
    """
    if isinstance(value, jax.Array) and \
            value.sharding.is_equivalent_to(sharding, value.ndim):
        return value  # already in the target layout: no move to measure
    src = layout_label(value)
    dst = layout_label(sharding)
    t0 = time.perf_counter()
    if isinstance(value, jax.Array) and jax.process_count() > 1:
        if value.is_fully_addressable:
            # a process-local array (host-committed params): read it
            # out and contribute per-process shards like a host value
            out = put_global(numpy.asarray(value), sharding)
        else:
            # a live GLOBAL array reshards through device_put (the
            # all-gather/dynamic-slice decomposition across processes;
            # jaxlibs that cannot do this raise here — the callers
            # that reach it (model-sharded push_params under
            # multi-controller) degrade by keeping the source layout)
            out = jax.device_put(value, sharding)
    else:
        out = put_global(value, sharding)
    if block:
        jax.block_until_ready(out)
    elapsed = time.perf_counter() - t0
    reshard_histogram().labels(src=src, dst=dst).observe(elapsed * 1e3)
    tracing.add_complete("reshard", t0, elapsed, src=src, dst=dst)
    return out


def reshard_tree(tree, shardings, *, block=False):
    """``reshard`` every leaf of ``tree``; ``shardings`` is either one
    sharding for all leaves or a matching pytree prefix of shardings."""
    if isinstance(shardings, jax.sharding.Sharding):
        return jax.tree_util.tree_map(
            lambda v: reshard(v, shardings, block=block), tree)
    return jax.tree_util.tree_map(
        lambda v, s: reshard(v, s, block=block), tree, shardings)


def host_placer(device=None):
    """Host ndarray -> committed single-device array, measured as
    ``veles_reshard_ms{src="host", dst="committed"}``.

    The H2D leg of the out-of-core model-state ring (ISSUE 17): the
    offload engine hands this to its :class:`StagingRing` so every
    layer-group upload shows up in the reshard histogram alongside the
    other layout moves, instead of hiding inside a bare
    ``device_put``. Mirrors :func:`gather_to_host`, the D2H leg."""
    if device is not None and getattr(device, "is_jax", False):
        put = device.put
    else:
        put = jax.device_put

    def place(host_array):
        t0 = time.perf_counter()
        out = put(host_array)
        elapsed = time.perf_counter() - t0
        reshard_histogram().labels(src="host", dst="committed").observe(
            elapsed * 1e3)
        tracing.add_complete("reshard", t0, elapsed, src="host",
                             dst="committed")
        return out
    return place


def gather_to_host(value):
    """The serve-side terminal move: any layout -> a full host ndarray
    (the all-gather decomposition, then device->host). Measured under
    ``dst="host"``. Serving replicas (and single-file snapshots)
    consume exactly this form."""
    src = layout_label(value)
    t0 = time.perf_counter()
    out = numpy.asarray(value)
    elapsed = time.perf_counter() - t0
    reshard_histogram().labels(src=src, dst="host").observe(
        elapsed * 1e3)
    tracing.add_complete("reshard", t0, elapsed, src=src, dst="host")
    return out
