"""GSPMD pod-scale training path (ISSUE 15, ROADMAP item 1).

One launcher, one ``jit``: the whole train step — forward, backward,
optimizer — compiles with in/out ``NamedSharding``s over a named
``Mesh(('batch', 'model'))``, so the reference's master↔slave gradient
merge lowers to a compiler-inserted ``lax.psum`` over ICI (the
PAPER.md target) instead of the host-mediated pickle/shm exchange.
The pieces already existed as fragments; this module unifies them
into sharding *specs* consumed by the one jitted step:

* :mod:`veles_tpu.parallel.dp` supplies the batch-axis placement
  (dataset row-sharded, per-step index gather crossing shards, the
  prefetch staging ring landing streamed shards directly as
  addressable per-device shards of the global batch);
* :mod:`veles_tpu.parallel.tp` supplies the model-axis rules
  (:func:`~veles_tpu.parallel.tp.tp_param_shardings`'s Megatron
  column/row alternation for dense AND conv);
* :mod:`veles_tpu.parallel.reshard` supplies the measured
  layout-change primitive for checkpoint restore at a different mesh
  shape and for train→serve moves.

Axis naming: ``batch`` × ``model`` (the ISSUE 15 convention for the
launcher-SPMD tier; the coordinator remains the cross-pod /
heterogeneous tier and the older ``data`` axis name keeps working for
direct :class:`~veles_tpu.parallel.dp.DataParallelTrainer` users).

**Bit-parity by construction.** The correctness bar is a loss curve
bit-identical (CPU, fixed seeds) to the coordinator path. Two facts
make that hold:

* the weight trajectory needs no help — on every backend this repo
  meets, the partitioner's gradient psum merges shard partials into
  exactly the floats the single-device contraction produces (pinned
  by tests/test_gspmd.py, weights compared bit-for-bit);
* the *reported* loss/metric scalars DO need help: a reduction over a
  batch-sharded per-sample vector lowers to local-sum + psum, whose
  summation order occasionally rounds 1 ULP away from the
  single-device reduce. :meth:`GSPMDTrainer._loss_and_metrics`
  therefore gathers the per-sample values to a REPLICATED layout
  (one all-gather of ``mb`` rows — noise next to the step) before any
  cross-sample reduction, so every scalar reduces in the single-device
  order and the curve is bit-identical structurally, not by luck.

Telemetry: ``veles_gspmd_step_ms{phase}`` (compute + compiler-inserted
exchange, per class sweep), ``veles_reshard_ms{src,dst}`` via
:mod:`~veles_tpu.parallel.reshard`, and the per-step collective-bytes
estimate harvested from the compiled step into the PR 7 CostBook
(``veles_op_collective_bytes{op="gspmd_train_segment"}``).
"""

import time

import jax

from veles_tpu.parallel.dp import DataParallelTrainer
from veles_tpu.parallel.mesh import build_mesh, named_sharding

#: the launcher-SPMD tier's axis names (ISSUE 15)
BATCH_AXIS = "batch"
MODEL_AXIS = "model"


def gspmd_mesh(batch=-1, model=1, devices=None):
    """The named ``batch`` × ``model`` mesh. ``batch=-1`` infers the
    batch extent from the device count (all devices on the batch axis
    when ``model=1``). The model axis exists even at size 1, so the
    same specs compile whether tensor parallelism is on or off."""
    return build_mesh({BATCH_AXIS: batch, MODEL_AXIS: model},
                      devices=devices)


def parse_mesh_spec(spec, devices=None):
    """``--gspmd`` argument -> mesh.

    Accepts ``"auto"``/``""`` (all devices on ``batch``),
    ``"batch=4,model=2"`` (any order, ``-1`` infers), or the shorthand
    ``"4x2"`` (batch x model)."""
    spec = (spec or "auto").strip().lower()
    if spec in ("auto", "1", "true", "on"):
        return gspmd_mesh(devices=devices)
    axes = {BATCH_AXIS: -1, MODEL_AXIS: 1}
    if "=" in spec:
        for part in spec.split(","):
            name, _, value = part.partition("=")
            name = name.strip()
            if name not in axes:
                raise ValueError(
                    "unknown GSPMD mesh axis %r (have batch, model)"
                    % name)
            axes[name] = int(value)
    else:
        sizes = spec.split("x")
        axes[BATCH_AXIS] = int(sizes[0])
        if len(sizes) > 1:
            axes[MODEL_AXIS] = int(sizes[1])
        if len(sizes) > 2:
            raise ValueError("GSPMD mesh shorthand is BATCHxMODEL, "
                             "got %r" % spec)
    return gspmd_mesh(batch=axes[BATCH_AXIS], model=axes[MODEL_AXIS],
                      devices=devices)


def gspmd_param_specs(forwards, mesh, model_axis=MODEL_AXIS):
    """The unified parameter-sharding plan: tp.py's column/row rules
    over the ``model`` axis when it is wider than 1, else fully
    replicated (pure data parallelism — the gradient psum is the only
    parameter collective)."""
    if model_axis in mesh.shape and mesh.shape[model_axis] > 1:
        from veles_tpu.parallel.tp import tp_param_shardings
        return tp_param_shardings(forwards, mesh, axis=model_axis)
    return None  # DataParallelTrainer default: replicated prefix tree


class GSPMDTrainer(DataParallelTrainer):
    """The single-launcher SPMD training path over ``batch``×``model``.

    ``mesh=None`` builds the default mesh (all devices on ``batch``);
    ``shard_model=True`` (default) consumes tp.py's model-axis specs
    whenever the mesh's model axis is wider than 1 — pass
    ``param_shardings`` to override per-layer, or ``shard_model=False``
    to keep parameters replicated on a wide model axis.

    Everything else — dataset row-sharding with release of the
    single-device copy, streamed shards placed as addressable
    per-device shards through the staging ring, the minibatch
    divisibility check an elastic restart hits first — is inherited
    from :class:`~veles_tpu.parallel.dp.DataParallelTrainer`, now
    driven through the ``batch`` axis.
    """

    _op_prefix = "gspmd_"

    def __init__(self, workflow, mesh=None, batch_axis=BATCH_AXIS,
                 model_axis=MODEL_AXIS, param_shardings=None,
                 shard_model=True, **kwargs):
        if mesh is None:
            mesh = gspmd_mesh()
        if batch_axis not in mesh.shape:
            raise ValueError(
                "GSPMD mesh %r has no %r axis (gspmd_mesh/"
                "parse_mesh_spec build the right one)"
                % (dict(mesh.shape), batch_axis))
        self.model_axis = model_axis
        if param_shardings is None and shard_model:
            param_shardings = gspmd_param_specs(
                workflow.forwards, mesh, model_axis=model_axis)
        from veles_tpu.telemetry.registry import get_registry
        self._gspmd_ms = get_registry().histogram(
            "veles_gspmd_step_ms",
            "GSPMD class sweep: compute + compiler-inserted exchange, "
            "blocked on results", labels=("phase",))
        super(GSPMDTrainer, self).__init__(
            workflow, mesh=mesh, axis=batch_axis,
            param_shardings=param_shardings, **kwargs)

    # -- shard-invariant loss reductions (bit-parity by construction) ------

    def _loss_and_metrics(self, out, labels_or_targets, valid):
        """Gather per-sample values to the replicated layout before any
        cross-sample reduction (see the module docstring): the loss and
        metric scalars then reduce in the single-device order, making
        the reported curve bit-identical to the coordinator path. The
        gradient seed is computed from the same replicated logits; its
        transpose reshards the cotangent back to the batch axis with
        values untouched."""
        repl = named_sharding(self.mesh)
        out = jax.lax.with_sharding_constraint(out, repl)
        labels_or_targets = jax.lax.with_sharding_constraint(
            labels_or_targets, repl)
        valid = jax.lax.with_sharding_constraint(valid, repl)
        return super(GSPMDTrainer, self)._loss_and_metrics(
            out, labels_or_targets, valid)

    # -- measured sweeps (veles_gspmd_step_ms) ------------------------------

    def train_class(self, params, states, skip=0):
        t0 = time.perf_counter()
        out = super(GSPMDTrainer, self).train_class(params, states,
                                                    skip=skip)
        # block: the honest exchange+compute cycle, not the async
        # dispatch (the runner blocks on these results right after
        # anyway, so this moves the wait, it does not add one)
        jax.block_until_ready(out)
        self._gspmd_ms.labels(phase="train").observe(
            (time.perf_counter() - t0) * 1e3)
        return out

    def eval_class(self, params, klass, skip=0):
        t0 = time.perf_counter()
        out = super(GSPMDTrainer, self).eval_class(params, klass,
                                                   skip=skip)
        jax.block_until_ready([o for o in out if o is not None])
        self._gspmd_ms.labels(phase="eval").observe(
            (time.perf_counter() - t0) * 1e3)
        return out

    # -- train→serve layout moves ------------------------------------------

    def push_params(self, params, states):
        """Device pytrees -> unit Arrays, via the measured train→serve
        reshard: model-axis-sharded leaves move to the fully replicated
        layout (the all-gather decomposition) before landing in the
        unit Arrays, so snapshots and the serving model store read full
        arrays without a hidden gather on their own path."""
        from veles_tpu.parallel import reshard
        repl = named_sharding(self.mesh)

        def to_replicated(v):
            try:
                return reshard.reshard(v, repl)
            except ValueError:
                # a jaxlib that cannot device_put across processes:
                # keep the source layout (the pre-ISSUE-15 behavior —
                # readers gather on their own path)
                return v

        params = tuple(
            {k: to_replicated(v) for k, v in layer.items()}
            for layer in params)
        states = jax.tree_util.tree_map(to_replicated, states)
        return super(GSPMDTrainer, self).push_params(params, states)
