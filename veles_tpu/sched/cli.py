"""``python -m veles_tpu sched serve|submit|status``.

``serve`` runs the scheduler + its loopback control endpoint (and
optionally pushes a status blob to a web_status dashboard, whose
``/jobs.json`` and jobs table render it). ``submit`` and ``status``
are thin HTTP clients of a running ``serve``.

Knobs (all resolvable per-invocation by flags; the env knobs are the
deployment defaults)::

    VELES_SCHED_POOL       device-slot count for `serve` (default 2)
    VELES_SCHED_TICK_S     scheduling pass interval (default 0.2)
    VELES_SCHED_ADDR       control endpoint host:port — `serve` binds
                           it, `submit`/`status` dial it
                           (default 127.0.0.1:4730)
    VELES_SCHED_PREEMPT    enable preemption (default on)
    VELES_SCHED_MIN_RUN_S  victim thrash guard seconds (default 1.0)
    VELES_SCHED_LOG_DIR    per-gang-member log directory (default:
                           inherit the scheduler's stdio)
    VELES_SCHED_STATE_DIR  durable state directory — the write-ahead
                           job journal + compacted snapshots live
                           here; a restart on the same dir recovers
                           every job and adopts surviving gangs
                           (default: in-memory only)
"""

import argparse
import json
import sys
import time
import urllib.request

from veles_tpu.envknob import env_flag, env_knob

DEFAULT_ADDR = "127.0.0.1:4730"


def _default_addr():
    return env_knob("VELES_SCHED_ADDR", DEFAULT_ADDR)


def _split_addr(addr):
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def _http(addr, path, payload=None, timeout=10.0):
    url = "http://%s/%s" % (addr, path.lstrip("/"))
    data = None
    headers = {}
    if payload is not None:
        data = json.dumps(payload).encode("utf-8")
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _serve_main(argv):
    parser = argparse.ArgumentParser(
        prog="veles_tpu sched serve",
        description="run the gang scheduler + control endpoint")
    parser.add_argument("--pool", type=int, default=None,
                        help="device-slot count")
    parser.add_argument("--tick-s", type=float, default=None,
                        help="scheduling pass interval")
    parser.add_argument("--addr", default=None, metavar="HOST:PORT",
                        help="control endpoint to bind")
    parser.add_argument("--no-preempt", action="store_true",
                        help="disable preemption (jobs only place "
                             "into free holes)")
    parser.add_argument("--min-run-s", type=float, default=None,
                        help="victim must have run this long")
    parser.add_argument("--log-dir", default=None,
                        help="per-gang-member log files land here")
    parser.add_argument("--state-dir", default=None, metavar="DIR",
                        help="journal job state here and recover "
                             "from it at startup (adopting gangs "
                             "that survived the restart)")
    parser.add_argument("--status-url", default=None, metavar="URL",
                        help="web_status dashboard base URL to push "
                             "the jobs table to (e.g. "
                             "http://127.0.0.1:8090)")
    args = parser.parse_args(argv)
    # env knobs resolve OUTSIDE argparse defaults so a bad value fails
    # with the knob's name, and --help never triggers a parse
    pool = args.pool if args.pool is not None else \
        env_knob("VELES_SCHED_POOL", 2, parse=int)
    tick_s = args.tick_s if args.tick_s is not None else \
        env_knob("VELES_SCHED_TICK_S", 0.2, parse=float)
    addr = args.addr or _default_addr()
    preempt = (not args.no_preempt) and \
        env_flag("VELES_SCHED_PREEMPT", True)
    min_run_s = args.min_run_s if args.min_run_s is not None else \
        env_knob("VELES_SCHED_MIN_RUN_S", 1.0, parse=float)
    log_dir = args.log_dir or env_knob("VELES_SCHED_LOG_DIR")
    state_dir = args.state_dir or env_knob("VELES_SCHED_STATE_DIR")

    from veles_tpu.sched.scheduler import Scheduler, SchedulerControl
    host, port = _split_addr(addr)
    scheduler = Scheduler(pool, tick_s=tick_s, preempt=preempt,
                          min_run_s=min_run_s, log_dir=log_dir,
                          state_dir=state_dir)
    # control first: clients get 503 + Retry-After during the replay
    # window instead of a connection refusal
    control = SchedulerControl(scheduler, host=host, port=port)
    control.start()
    scheduler.start()
    print("SCHED %s:%d pool=%d" % (control.address[0], control.port,
                                   pool), flush=True)
    try:
        while True:
            time.sleep(2.0)
            if args.status_url:
                _push_status(args.status_url, scheduler)
    except KeyboardInterrupt:
        pass
    finally:
        control.stop()
        scheduler.stop()
    return 0


def _push_status(base_url, scheduler):
    """POST the dashboard blob web_status's jobs table renders."""
    import os
    import socket
    blob = {"id": "sched-%s-%d" % (socket.gethostname(), os.getpid()),
            "name": "scheduler", "mode": "sched",
            "master": socket.gethostname(),
            "jobs": scheduler.jobs_report()["jobs"],
            "sched": scheduler.stats()}
    try:
        req = urllib.request.Request(
            base_url.rstrip("/") + "/update",
            data=json.dumps(blob).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=2.0)
    except OSError:
        pass   # the dashboard being down must not stop scheduling


def _submit_main(argv):
    exec_argv = None
    if "--" in argv:
        split = argv.index("--")
        exec_argv = argv[split + 1:]
        argv = argv[:split]
    parser = argparse.ArgumentParser(
        prog="veles_tpu sched submit",
        description="submit one job (workflow [config] [overrides], "
                    "or a raw command after `--`)")
    parser.add_argument("spec", nargs="*",
                        help="workflow file, optional config file, "
                             "then path=value overrides")
    parser.add_argument("--addr", default=None, metavar="HOST:PORT")
    parser.add_argument("--name", default=None)
    parser.add_argument("--tenant", default="default")
    parser.add_argument("--qos", default="batch",
                        choices=("interactive", "batch",
                                 "best_effort"))
    parser.add_argument("--weight", type=float, default=1.0)
    parser.add_argument("--world", default="1", metavar="MIN[:MAX]",
                        help="elastic world-size range")
    parser.add_argument("--snapshots", default=None, metavar="DIR",
                        help="sharded checkpoint dir (makes the job "
                             "preemptible)")
    parser.add_argument("--result-file", default=None)
    parser.add_argument("-s", "--seed", type=int, default=None)
    parser.add_argument("--max-retries", type=int, default=0,
                        help="re-run a failed gang up to this many "
                             "times (exponential backoff) before "
                             "FAILED")
    parser.add_argument("--retry-backoff-s", type=float, default=1.0,
                        help="base backoff before a retry re-queues")
    parser.add_argument("--wait", action="store_true",
                        help="poll until the job is terminal; exit "
                             "0 only on DONE")
    args = parser.parse_args(argv)
    addr = args.addr or _default_addr()
    world_min, _, world_max = args.world.partition(":")
    spec = {"name": args.name, "tenant": args.tenant, "qos": args.qos,
            "weight": args.weight, "world_min": int(world_min),
            "world_max": int(world_max or world_min),
            "snapshot_dir": args.snapshots,
            "result_file": args.result_file, "seed": args.seed,
            "max_retries": args.max_retries,
            "retry_backoff_s": args.retry_backoff_s}
    if exec_argv:
        if args.spec:
            parser.error("give either workflow args or a `--` "
                         "command, not both")
        spec["argv"] = exec_argv
    elif args.spec:
        spec["workflow"] = args.spec[0]
        rest = args.spec[1:]
        overrides = {}
        for item in rest:
            if "=" in item:
                path, _, value = item.partition("=")
                overrides[path] = _literal(value)
            elif "config" not in spec or spec["config"] is None:
                spec["config"] = item
            else:
                parser.error("unexpected positional %r" % item)
        if overrides:
            spec["overrides"] = overrides
    else:
        parser.error("nothing to run: give a workflow file or a "
                     "`--` command")
    reply = _http(addr, "/submit", payload=spec)
    if "error" in reply:
        print("submit failed: %s" % reply["error"], file=sys.stderr)
        return 1
    print(reply["id"], flush=True)
    if not args.wait:
        return 0
    while True:
        jobs = {j["id"]: j for j in
                _http(addr, "/jobs.json")["jobs"]}
        job = jobs.get(reply["id"])
        if job is None:
            print("job %s vanished" % reply["id"], file=sys.stderr)
            return 1
        if job["state"] in ("done", "failed"):
            print("%s %s" % (job["id"], job["state"]), flush=True)
            return 0 if job["state"] == "done" else 1
        time.sleep(0.2)


def _literal(value):
    """Overrides come in as text; eval-free literal parsing keeps
    ints/floats/bools as the types ``%r`` would round-trip."""
    lowered = value.lower()
    if lowered in ("true", "false"):
        return lowered == "true"
    for parse in (int, float):
        try:
            return parse(value)
        except ValueError:
            continue
    return value


def _status_main(argv):
    parser = argparse.ArgumentParser(
        prog="veles_tpu sched status",
        description="print a running scheduler's pool/tenant/job "
                    "state")
    parser.add_argument("--addr", default=None, metavar="HOST:PORT")
    parser.add_argument("--json", action="store_true",
                        help="raw JSON instead of the table")
    args = parser.parse_args(argv)
    addr = args.addr or _default_addr()
    stats = _http(addr, "/status")
    jobs = _http(addr, "/jobs.json")["jobs"]
    if args.json:
        print(json.dumps({"status": stats, "jobs": jobs}, indent=2))
        return 0
    pool = stats["pool"]
    print("pool: %d slots (%d held / %d free)"
          % (pool["size"], pool["held"], pool["free"]))
    for name, t in sorted(stats.get("tenants", {}).items()):
        print("tenant %-12s weight=%.1f qos=%-11s held=%d share=%s"
              % (name, t["weight"], t["qos"], t["held"], t["share"]))
    for job in jobs:
        print("%-8s %-10s %-24s tenant=%-10s world=%d preempts=%d%s"
              % (job["id"], job["state"], job["name"][:24],
                 job["tenant"], job["world"], job["preemptions"],
                 " error=%s" % job["error"] if job["error"] else ""))
    return 0


def sched_main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd == "serve":
        return _serve_main(rest)
    if cmd == "submit":
        return _submit_main(rest)
    if cmd == "status":
        return _status_main(rest)
    print("unknown command %r (serve | submit | status)" % cmd,
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(sched_main())
