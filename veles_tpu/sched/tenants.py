"""The scheduler's first native tenants: genetics + ensembling.

The paper's headline workloads are populations of short training runs
— a genetics generation is ``population_size`` independent fitness
evaluations, an ensemble is ``size`` independent member trainings —
exactly the traffic a gang scheduler exists for. These subclasses keep
the serial drivers' EXACT result-file contract (same module argv, same
seeds, same fitness/gather parsing) and only change WHO runs the
subprocess: instead of one cold/warm evaluation at a time, the whole
wave is submitted as concurrent scheduler jobs and collected when the
scheduler reports them terminal.

Bit-exactness (pinned by ``tests/test_sched.py``): the scheduled
genetics path reports the same best fitness as the serial path under
fixed seeds, because (a) :meth:`JobSpec.build_argv` mirrors
``GeneticsOptimizer._evaluate_subprocess`` argv construction
bit-for-bit, (b) every evaluation gets the same ``-s <seed>`` the
serial path passes, and (c) ``Population.update()``'s PRNG consumption
is untouched — fitness assignment order within a generation does not
feed the stream.
"""

import json
import os
import sys
import tempfile

from veles_tpu.ensemble.train import EnsembleTrainManager
from veles_tpu.fairshare import DEFAULT_QOS
from veles_tpu.genetics.optimizer import (EvaluationError,
                                          GeneticsOptimizer)
from veles_tpu.sched.job import DONE, JobSpec


class ScheduledGeneticsOptimizer(GeneticsOptimizer):
    """Genetics with generation-wide concurrent fitness evaluation.

    ``run()`` has the serial driver's exact shape — evaluate pending,
    log the generation, ``population.update()`` — but the pending wave
    goes through ``scheduler.submit`` as one job per chromosome, so a
    generation's wall clock is bounded by the pool, not by
    ``population_size`` serial runs.
    """

    def __init__(self, scheduler=None, tenant="genetics",
                 qos=DEFAULT_QOS, job_timeout_s=None, **kwargs):
        super(ScheduledGeneticsOptimizer, self).__init__(**kwargs)
        if scheduler is None:
            raise ValueError("ScheduledGeneticsOptimizer needs a "
                             "started Scheduler")
        self.scheduler = scheduler
        self.tenant = tenant
        self.qos = qos
        self.job_timeout_s = job_timeout_s

    def run(self):
        try:
            for _ in range(self.generations):
                self._evaluate_generation()
                best = self.population.best
                self.info(
                    "generation %d: best=%.6g avg=%.6g %s",
                    self.population.generation, best.fitness,
                    self.population.average_fitness,
                    self.overrides_for(best))
                if self.on_generation is not None:
                    self.on_generation(self.population)
                if self.population.generation < self.generations - 1:
                    self.population.update()
        finally:
            self.close_pool()
        self._write_results()
        return self.population.best

    def _evaluate_generation(self):
        pending = list(self.population.pending)
        if not pending:
            return
        if self.evaluator is not None:
            # in-process evaluators have nothing to schedule
            for chromo in pending:
                self.evaluate(chromo)
            return
        entries = []
        for chromo in pending:
            values = self.overrides_for(chromo)
            fd, result_path = tempfile.mkstemp(
                suffix=".json", prefix="veles_tpu_fitness_")
            os.close(fd)
            job = self.scheduler.submit(JobSpec(
                name="genetics-g%d" % self.population.generation,
                workflow=self.workflow_file, config=self.config_file,
                overrides=values, extra_argv=self.extra_argv,
                result_file=result_path, seed=self.seed,
                tenant=self.tenant, qos=self.qos))
            entries.append((chromo, job, result_path))
        self.scheduler.wait([job.id for _, job, _ in entries],
                            timeout_s=self.job_timeout_s)
        for chromo, job, result_path in entries:
            try:
                if job.state != DONE:
                    raise EvaluationError(
                        "scheduled fitness job %s ended %s: %s"
                        % (job.id, job.state, job.error))
                with open(result_path) as f:
                    results = json.load(f)
            finally:
                try:
                    os.unlink(result_path)
                except OSError:
                    pass
            chromo.fitness = self._fitness_from_results(results)
            self.debug("fitness %.6g for %s (%s)", chromo.fitness,
                       self.overrides_for(chromo), job.id)


class ScheduledEnsembleTrainManager(EnsembleTrainManager):
    """Ensemble training with members as concurrent scheduler jobs.

    Same per-member argv (``model_argv``: per-member seed + ensemble
    overrides) and the same gathered-results contract as the serial
    manager — a failed member lands as ``None`` in its slot, the rest
    of the ensemble survives.
    """

    def __init__(self, scheduler=None, tenant="ensemble",
                 qos=DEFAULT_QOS, job_timeout_s=None, **kwargs):
        super(ScheduledEnsembleTrainManager, self).__init__(**kwargs)
        if scheduler is None:
            raise ValueError("ScheduledEnsembleTrainManager needs a "
                             "started Scheduler")
        self.scheduler = scheduler
        self.tenant = tenant
        self.qos = qos
        self.job_timeout_s = job_timeout_s

    def run(self):
        if self.runner is not None:
            return super(ScheduledEnsembleTrainManager, self).run()
        entries = []
        for index in range(self.size):
            if self.results[index] is not None:
                continue
            fd, result_path = tempfile.mkstemp(
                suffix=".json", prefix="veles_tpu_ensemble_")
            os.close(fd)
            argv = [sys.executable, "-m", "veles_tpu"] + \
                self.model_argv(index, result_path)
            job = self.scheduler.submit(JobSpec(
                name="ensemble-member-%d" % index, argv=argv,
                tenant=self.tenant, qos=self.qos))
            entries.append((index, job, result_path))
        self.info("submitted %d ensemble members to the scheduler",
                  len(entries))
        self.scheduler.wait([job.id for _, job, _ in entries],
                            timeout_s=self.job_timeout_s)
        for index, job, result_path in entries:
            try:
                if job.state != DONE:
                    self.warning("model #%d job %s ended %s: %s",
                                 index, job.id, job.state, job.error)
                    continue
                with open(result_path) as f:
                    self.results[index] = json.load(f)
            finally:
                try:
                    os.unlink(result_path)
                except OSError:
                    pass
        self.write_results()
        return self.results
