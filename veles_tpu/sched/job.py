"""JobSpec + the job FSM for the multi-job gang scheduler.

A *job* is one training run packed onto the shared device pool: a
workflow invocation (or a raw command) owned by a tenant, wanting an
elastic gang of ``world_min..world_max`` device slots. Its lifecycle
is a small FSM::

    PENDING --> RUNNING --> DONE
                  |  ^ \\       \\-> FAILED
                  v  |  \\------> RETRYING --> FAILED
               PREEMPTED -------/   (budgeted, backoff)

``RUNNING -> PREEMPTED`` is checkpoint + shrink (the gang is killed;
its last complete per-epoch sharded checkpoint is the resume point)
and ``PREEMPTED -> RUNNING`` is re-form + reshard-on-restore — the
PR 12/13 determinism contract makes the resumed loss curve
bit-identical to an uninterrupted run. ``RUNNING -> RETRYING`` is the
failure policy: a gang that exited nonzero with retry budget left
(``JobSpec.max_retries``) re-queues after a jittered exponential
backoff instead of landing in FAILED on the first strike. Every
transition lands in the ``veles_sched_transitions_total`` counter;
terminal states also count into ``veles_sched_jobs_total``.

Jobs survive scheduler restarts: :meth:`Job.record` /
:meth:`Job.from_record` round-trip the full job through the
write-ahead journal (:mod:`veles_tpu.sched.journal`) without touching
the metric counters — replay must not double-count what the live
scheduler already counted.
"""

import itertools
import sys
import time
import uuid

from veles_tpu.fairshare import DEFAULT_QOS, QOS_MULTIPLIER

#: FSM states (string-valued: they travel through /jobs.json verbatim)
PENDING = "pending"
RUNNING = "running"
PREEMPTED = "preempted"
RETRYING = "retrying"
DONE = "done"
FAILED = "failed"

STATES = (PENDING, RUNNING, PREEMPTED, RETRYING, DONE, FAILED)

#: legal FSM moves; anything else is a scheduler bug, not a runtime
#: condition — transition() raises instead of recording garbage
TRANSITIONS = {
    PENDING: (RUNNING, FAILED),
    RUNNING: (PREEMPTED, RETRYING, DONE, FAILED),
    PREEMPTED: (RUNNING, FAILED),
    RETRYING: (RUNNING, FAILED),
    DONE: (),
    FAILED: (),
}

DEFAULT_TENANT = "default"

_ids = itertools.count(1)


def reserve_job_ids(floor):
    """Advance the job-id mint past ``floor`` (an int) so ids recovered
    from the journal and freshly minted ones never collide."""
    global _ids
    current = next(_ids)
    _ids = itertools.count(max(floor + 1, current))


def _metrics():
    from veles_tpu.telemetry.registry import get_registry
    r = get_registry()
    return {
        "transitions": r.counter(
            "veles_sched_transitions_total",
            "Job FSM transitions", labels=("tenant", "to")),
        "jobs": r.gauge(
            "veles_sched_jobs", "Jobs per FSM state",
            labels=("state",)),
        "jobs_total": r.counter(
            "veles_sched_jobs_total",
            "Jobs reaching a terminal state",
            labels=("tenant", "state")),
        "preemptions": r.counter(
            "veles_sched_preemptions_total",
            "Jobs preempted (checkpoint + shrink)",
            labels=("tenant",)),
        "preempt_resume": r.histogram(
            "veles_sched_preempt_resume_ms",
            "Preemption -> the job is RUNNING again (re-form + "
            "reshard-on-restore)"),
        "devices": r.gauge(
            "veles_sched_pool_devices",
            "Device-slot inventory by state", labels=("state",)),
        "oldest_wait": r.gauge(
            "veles_sched_oldest_pending_s",
            "Age of the oldest PENDING/PREEMPTED job (feeds "
            "job_stuck)"),
        "tenant_wait": r.gauge(
            "veles_sched_tenant_wait_s",
            "Oldest runnable-job wait per tenant (feeds "
            "tenant_starvation)", labels=("tenant",)),
        "queue_wait": r.histogram(
            "veles_sched_queue_wait_s",
            "Submit -> FIRST placement wait (resumes excluded)"),
        "share_fraction": r.gauge(
            "veles_sched_share_fraction",
            "Guaranteed fair share as a fraction of the pool per "
            "tenant (the ledger's decision, not its outcome)",
            labels=("tenant",)),
        # the federated job view: each gang's rank-0 pushes its
        # registry delta to the scheduler; these mirror the live
        # training signal under {job,tenant} so alert rules and the
        # cluster /metrics read it like any local family
        "job_loss": r.gauge(
            "veles_sched_job_loss",
            "Live training loss per job (federated from the gang)",
            labels=("job", "tenant")),
        "job_samples": r.gauge(
            "veles_sched_job_samples_per_s",
            "Live training throughput per job (federated)",
            labels=("job", "tenant")),
        "job_mfu": r.gauge(
            "veles_sched_job_mfu",
            "Live model FLOPs utilization per job (federated)",
            labels=("job", "tenant")),
        "beat_age": r.gauge(
            "veles_sched_beat_age_s",
            "Seconds since the job's last beat-carried telemetry "
            "delta (feeds gang_silent)", labels=("job", "tenant")),
        "loss_age": r.gauge(
            "veles_sched_job_loss_age_s",
            "Seconds since the job's loss last CHANGED (feeds "
            "job_loss_plateau)", labels=("job", "tenant")),
        # durability plane (write-ahead journal + crash recovery)
        "journal_bytes": r.gauge(
            "veles_sched_journal_bytes",
            "Current size of the scheduler's write-ahead journal "
            "(sawtooths at each compaction)"),
        "replays": r.counter(
            "veles_sched_replays_total",
            "Journal replays completed at scheduler start"),
        "adopted": r.counter(
            "veles_sched_gangs_adopted_total",
            "Still-alive gangs re-attached (not killed) after a "
            "scheduler restart"),
        "retries": r.counter(
            "veles_sched_job_retries_total",
            "Failed gangs re-queued under the job's retry budget",
            labels=("tenant",)),
        "recovery_ms": r.histogram(
            "veles_sched_recovery_ms",
            "Restart recovery phase wall time",
            labels=("phase",)),
    }


class InvalidTransition(RuntimeError):
    """The scheduler asked for an FSM move the table forbids."""


class JobSpec(object):
    """What to run, who owns it, and how elastic it is.

    Two command shapes:

    * ``workflow`` (+ ``config`` + ``overrides`` + ``result_file`` +
      ``seed`` + ``extra_argv``) — a ``python -m veles_tpu`` run whose
      module argv is built EXACTLY like the genetics/ensemble serial
      evaluators build theirs (same ``path=repr(value)`` overrides,
      same flag order), so a scheduled evaluation is bit-identical to
      a serial one;
    * ``argv`` — a raw command executed verbatim (the elastic
      worker-demo, bench workers, anything already on disk).

    ``world_min..world_max`` is the elastic gang range: the scheduler
    grants the largest contiguous slice in range that fits, and a
    resume may be granted a DIFFERENT size — reshard-on-restore makes
    that safe. ``snapshot_dir`` marks the job preemptible: workers get
    it as ``VELES_ELASTIC_SNAPSHOTS`` and cut per-epoch sharded
    checkpoints; a job without one is never chosen as a preemption
    victim (there is nothing to resume it from).
    """

    def __init__(self, name=None, argv=None, workflow=None, config=None,
                 overrides=None, extra_argv=(), result_file=None,
                 seed=None, tenant=DEFAULT_TENANT, qos=DEFAULT_QOS,
                 weight=1.0, world_min=1, world_max=None,
                 snapshot_dir=None, env=None, max_retries=0,
                 retry_backoff_s=1.0):
        if (argv is None) == (workflow is None):
            raise ValueError(
                "exactly one of argv / workflow must be given")
        if qos not in QOS_MULTIPLIER:
            raise ValueError("unknown QoS class %r (one of %s)"
                             % (qos, sorted(QOS_MULTIPLIER)))
        self.name = name or (workflow or argv[0])
        self.argv = list(argv) if argv else None
        self.workflow = workflow
        self.config = config
        self.overrides = dict(overrides or {})
        self.extra_argv = list(extra_argv)
        self.result_file = result_file
        self.seed = seed
        self.tenant = tenant or DEFAULT_TENANT
        self.qos = qos
        self.weight = float(weight)
        self.world_min = int(world_min)
        self.world_max = int(world_max if world_max is not None
                             else world_min)
        if not 1 <= self.world_min <= self.world_max:
            raise ValueError("need 1 <= world_min <= world_max (got "
                             "%d..%d)" % (self.world_min,
                                          self.world_max))
        self.snapshot_dir = snapshot_dir
        self.env = dict(env or {})
        self.max_retries = int(max_retries)
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0 (got %d)"
                             % self.max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        if self.retry_backoff_s < 0:
            raise ValueError("retry_backoff_s must be >= 0 (got %s)"
                             % self.retry_backoff_s)

    @property
    def preemptible(self):
        return self.snapshot_dir is not None

    def build_argv(self, python=None):
        """The full command for one gang member. The workflow shape
        mirrors ``GeneticsOptimizer._evaluate_subprocess`` /
        ``EnsembleManagerBase._base_argv`` ordering bit-for-bit."""
        if self.argv is not None:
            return list(self.argv)
        argv = [self.workflow]
        if self.config:
            argv.append(self.config)
        argv.extend("%s=%r" % (path, value)
                    for path, value in self.overrides.items())
        if self.result_file:
            argv.extend(["--result-file", self.result_file])
        if self.seed is not None:
            argv.extend(["-s", str(self.seed)])
        argv.extend(["-v", "warning"])
        argv.extend(self.extra_argv)
        return [python or sys.executable, "-m", "veles_tpu"] + argv

    def to_dict(self):
        """JSON body for ``sched submit`` -> the control endpoint."""
        return {
            "name": self.name, "argv": self.argv,
            "workflow": self.workflow, "config": self.config,
            "overrides": self.overrides, "extra_argv": self.extra_argv,
            "result_file": self.result_file, "seed": self.seed,
            "tenant": self.tenant, "qos": self.qos,
            "weight": self.weight, "world_min": self.world_min,
            "world_max": self.world_max,
            "snapshot_dir": self.snapshot_dir, "env": self.env,
            "max_retries": self.max_retries,
            "retry_backoff_s": self.retry_backoff_s,
        }

    @classmethod
    def from_dict(cls, data):
        known = ("name", "argv", "workflow", "config", "overrides",
                 "extra_argv", "result_file", "seed", "tenant", "qos",
                 "weight", "world_min", "world_max", "snapshot_dir",
                 "env", "max_retries", "retry_backoff_s")
        unknown = set(data) - set(known)
        if unknown:
            raise ValueError("unknown JobSpec fields %s"
                             % sorted(unknown))
        return cls(**{k: data[k] for k in known if data.get(k)
                      is not None})


class Job(object):
    """One submitted job: spec + FSM state + grant bookkeeping."""

    def __init__(self, spec, metrics=None, now=None):
        self.id = "job-%d" % next(_ids)
        self.spec = spec
        #: ONE trace id for the job's whole life — every grant's
        #: workers, their spans and flight records, and the
        #: scheduler's own sched_job_failed record correlate under it
        self.trace_id = uuid.uuid4().hex[:16]
        self.state = PENDING
        self.submitted_t = time.time() if now is None else now
        #: when the job last became runnable (PENDING or PREEMPTED) —
        #: the wait-age gauges and starvation alerts key off this
        self.runnable_since = self.submitted_t
        self.started_t = None
        self.finished_t = None
        self.preempted_t = None        # perf_counter at last preempt
        self.preempt_resume_s = None   # last measured preempt->resume
        self.queue_wait_s = None       # submit -> FIRST placement
        #: last federated view of the gang's training signal:
        #: loss / samples_per_s / mfu plus beat_t (last delta) and
        #: loss_t (last loss CHANGE) wall times
        self.live = {}
        self.granted_world = 0
        self.slots = ()
        self.procs = []
        #: last grant's worker pids (== pgids: workers start their own
        #: session) — what the journal records and recovery probes
        self.pids = ()
        self.grants = 0                # ENV_GEN generation counter
        self.preemptions = 0
        self.retries = 0               # failure-policy re-runs used
        self.retry_at = None           # wall time the next run unlocks
        self.failure_times = []        # crash-loop detection window
        self.error = None
        self.history = [(self.submitted_t, PENDING)]
        self._metrics = metrics if metrics is not None else _metrics()

    @property
    def runnable(self):
        return self.state in (PENDING, PREEMPTED, RETRYING)

    def ready(self, now=None):
        """Runnable AND past any retry backoff hold."""
        if not self.runnable:
            return False
        if self.retry_at is None:
            return True
        return (time.time() if now is None else now) >= self.retry_at

    @property
    def terminal(self):
        return self.state in (DONE, FAILED)

    def transition(self, to, now=None):
        """One FSM move; counts the ``veles_sched_*`` families."""
        now = time.time() if now is None else now
        if to not in TRANSITIONS[self.state]:
            raise InvalidTransition(
                "%s: illegal transition %s -> %s" % (self.id,
                                                     self.state, to))
        self.state = to
        self.history.append((now, to))
        self._metrics["transitions"].labels(
            tenant=self.spec.tenant, to=to).inc()
        if to == RUNNING:
            self.retry_at = None
            if self.started_t is None:
                self.started_t = now
                self.queue_wait_s = now - self.submitted_t
                self._metrics["queue_wait"].observe(self.queue_wait_s)
            if self.preempted_t is not None:
                self.preempt_resume_s = \
                    time.perf_counter() - self.preempted_t
                self._metrics["preempt_resume"].observe(
                    self.preempt_resume_s * 1e3)
                self.preempted_t = None
        elif to == PREEMPTED:
            self.preemptions += 1
            self.preempted_t = time.perf_counter()
            self.runnable_since = now
            self._metrics["preemptions"].labels(
                tenant=self.spec.tenant).inc()
        elif to == RETRYING:
            self.retries += 1
            self.runnable_since = now
            self._metrics["retries"].labels(
                tenant=self.spec.tenant).inc()
        if to in (DONE, FAILED):
            self.finished_t = now
            self._metrics["jobs_total"].labels(
                tenant=self.spec.tenant, state=to).inc()
        return self

    def live_view(self, now=None):
        """The federated live-metrics slice of the /jobs.json row:
        loss / throughput / MFU plus the last-beat age."""
        if not self.live:
            return {}
        now = time.time() if now is None else now
        view = {key: self.live[key] for key
                in ("loss", "samples_per_s", "mfu")
                if key in self.live}
        beat_t = self.live.get("beat_t")
        if beat_t is not None:
            view["beat_age_s"] = round(now - beat_t, 3)
        return view

    def to_dict(self):
        """The /jobs.json row."""
        return {
            "id": self.id, "name": self.spec.name,
            "tenant": self.spec.tenant, "qos": self.spec.qos,
            "trace_id": self.trace_id,
            "state": self.state, "world": self.granted_world,
            "world_range": [self.spec.world_min, self.spec.world_max],
            "slots": list(self.slots),
            "submitted_t": self.submitted_t,
            "started_t": self.started_t,
            "finished_t": self.finished_t,
            "queue_wait_s": self.queue_wait_s,
            "preemptions": self.preemptions,
            "retries": self.retries,
            "preempt_resume_s": self.preempt_resume_s,
            "metrics": self.live_view(),
            "error": self.error,
        }

    def record(self):
        """The journal image of this job: everything a restarted
        scheduler needs to rebuild it exactly (upsert semantics — each
        journaled event carries the FULL record, which is what makes
        replay trivially idempotent)."""
        return {
            "id": self.id, "trace_id": self.trace_id,
            "spec": self.spec.to_dict(), "state": self.state,
            "submitted_t": self.submitted_t,
            "runnable_since": self.runnable_since,
            "started_t": self.started_t,
            "finished_t": self.finished_t,
            "queue_wait_s": self.queue_wait_s,
            "preempt_resume_s": self.preempt_resume_s,
            "granted_world": self.granted_world,
            "slots": list(self.slots), "pids": list(self.pids),
            "grants": self.grants, "preemptions": self.preemptions,
            "retries": self.retries, "retry_at": self.retry_at,
            "failure_times": list(self.failure_times),
            "error": self.error,
            "history": [list(h) for h in self.history],
        }

    @classmethod
    def from_record(cls, record, metrics=None):
        """Rebuild a journaled job WITHOUT walking the FSM — replay
        must not re-count transitions/queue-wait/preemptions the live
        scheduler already metered."""
        job = cls.__new__(cls)
        job.id = record["id"]
        job.spec = JobSpec.from_dict(record["spec"])
        job.trace_id = record["trace_id"]
        job.state = record["state"]
        if job.state not in STATES:
            raise ValueError("journaled job %s has unknown state %r"
                             % (job.id, job.state))
        job.submitted_t = record["submitted_t"]
        job.runnable_since = record.get("runnable_since",
                                        job.submitted_t)
        job.started_t = record.get("started_t")
        job.finished_t = record.get("finished_t")
        #: perf_counter spans are meaningless across processes — a
        #: preemption in flight at crash time is re-timed from resume
        job.preempted_t = None
        job.preempt_resume_s = record.get("preempt_resume_s")
        job.queue_wait_s = record.get("queue_wait_s")
        job.live = {}
        job.granted_world = record.get("granted_world", 0)
        job.slots = tuple(record.get("slots") or ())
        job.procs = []
        job.pids = tuple(record.get("pids") or ())
        job.grants = record.get("grants", 0)
        job.preemptions = record.get("preemptions", 0)
        job.retries = record.get("retries", 0)
        job.retry_at = record.get("retry_at")
        job.failure_times = list(record.get("failure_times") or ())
        job.error = record.get("error")
        job.history = [tuple(h) for h in (record.get("history") or ())]
        if not job.history:
            job.history = [(job.submitted_t, PENDING)]
        job._metrics = metrics if metrics is not None else _metrics()
        return job
