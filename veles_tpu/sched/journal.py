"""Write-ahead job journal: the scheduler's durable state (ISSUE 20).

The gang scheduler keeps jobs, accounts and grants in memory; this
module makes a crash survivable. Every state-changing event (submit,
FSM transition, grant, preempt, reap) appends ONE fsync'd JSON line to
``<state_dir>/journal.jsonl`` before the scheduler acts on it — the
classic write-ahead discipline: after a crash, replaying the journal
reconstructs exactly the state the scheduler had acknowledged.

Two properties keep replay simple and safe:

* **Upsert events.** Each event carries the job's FULL record
  (:meth:`veles_tpu.sched.job.Job.record`), not an increment — so
  replaying a line twice is the same as replaying it once, and replay
  order only matters per job (last write wins).
* **Torn-tail tolerance.** ``fsync`` bounds loss to the line being
  written at crash time; a half-written final line is expected, not
  corruption. Replay stops at the first undecodable line with a
  warning — it never aborts (the ``snapshotter.py`` corrupt-artifact
  fallback discipline, applied to the control plane).

On size the journal **compacts**: the full state image is written to
``snapshot.json`` via the snapshotter's ``_atomic_write`` (hidden tmp
+ rename — a crash mid-compaction never destroys the previous image),
THEN the journal truncates. A crash between the two steps leaves a
snapshot plus a journal whose events are already folded into it —
harmless, because replay-on-top is idempotent by construction.
"""

import json
import logging
import os

from veles_tpu.snapshotter import _atomic_write

JOURNAL_NAME = "journal.jsonl"
SNAPSHOT_NAME = "snapshot.json"

#: compaction threshold: generous for a control plane writing ~1 KiB
#: per event, small enough that replay stays instant
DEFAULT_MAX_BYTES = 4 << 20

logger = logging.getLogger("JobJournal")


class JobJournal(object):
    """Append-only fsync'd event log + compacted snapshot image."""

    def __init__(self, state_dir, max_bytes=DEFAULT_MAX_BYTES,
                 metrics=None):
        self.state_dir = os.path.abspath(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.journal_path = os.path.join(self.state_dir, JOURNAL_NAME)
        self.snapshot_path = os.path.join(self.state_dir, SNAPSHOT_NAME)
        self.max_bytes = int(max_bytes)
        self._metrics = metrics
        self._f = None

    # -- write path --------------------------------------------------------

    def append(self, event):
        """One fsync'd line; the event is durable when this returns."""
        if self._f is None:
            self._f = open(self.journal_path, "a", encoding="utf-8")
        self._f.write(json.dumps(event, sort_keys=True) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())
        return self._gauge()

    def should_compact(self):
        return self._f is not None and self._f.tell() > self.max_bytes

    def compact(self, image):
        """Fold the journal into ``snapshot.json``: atomic image write
        FIRST, journal truncate second (the crash-safe order)."""
        _atomic_write(
            self.state_dir, SNAPSHOT_NAME,
            lambda tmp: self._write_image(tmp, image))
        if self._f is not None:
            self._f.close()
        self._f = open(self.journal_path, "w", encoding="utf-8")
        self._f.flush()
        os.fsync(self._f.fileno())
        self._gauge()

    @staticmethod
    def _write_image(tmp, image):
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(image, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())

    def _gauge(self):
        size = self._f.tell() if self._f is not None else 0
        if self._metrics is not None:
            self._metrics["journal_bytes"].set(size)
        return size

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    # -- replay path -------------------------------------------------------

    def replay(self):
        """``(image, events)``: the last compacted snapshot (or None)
        plus every journal event since it. Corrupt artifacts degrade
        — a bad snapshot is ignored with a warning (the journal alone
        still replays everything since the last truncate), and a torn
        journal tail stops the scan instead of aborting it."""
        image = None
        if os.path.exists(self.snapshot_path):
            try:
                with open(self.snapshot_path, encoding="utf-8") as f:
                    image = json.load(f)
            except (ValueError, OSError) as e:
                logger.warning(
                    "ignoring corrupt journal snapshot %s: %s",
                    self.snapshot_path, e)
                image = None
        events = []
        if os.path.exists(self.journal_path):
            with open(self.journal_path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if not line.strip():
                        continue
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        # torn tail (or garbage) — everything after
                        # the first bad line is untrustworthy
                        logger.warning(
                            "journal %s: stopping replay at "
                            "undecodable line %d",
                            self.journal_path, lineno)
                        break
        return image, events
