"""Training as a service: a multi-job gang scheduler over the elastic
mesh (ROADMAP item 4).

The elastic supervisor (PR 13) re-forms a mesh at any world size,
reshard-on-restore is bit-exact (PR 15), and serving already does
per-tenant weighted-fair QoS (PR 14) — so the cluster stops being
dedicated to one job. This package packs many training jobs onto one
device pool:

* :mod:`veles_tpu.sched.job` — :class:`JobSpec` (workflow + config
  overrides + tenant + QoS + elastic world-size range + retry budget)
  and the job FSM (``PENDING -> RUNNING -> PREEMPTED/RETRYING ->
  DONE/FAILED``), every transition counted in ``veles_sched_*``
  metric families;
* :mod:`veles_tpu.sched.journal` — the write-ahead job journal:
  fsync'd JSONL events + compacted snapshots under ``--state-dir``,
  replayed at restart so a scheduler crash loses nothing — surviving
  gangs are adopted in place, dead ones resume from checkpoint;
* :mod:`veles_tpu.sched.scheduler` — device-inventory pool, gang
  placement of contiguous mesh slices, weighted-fair per-tenant quotas
  through the shared :mod:`veles_tpu.fairshare` ledger, preemption =
  checkpoint + shrink (the per-epoch sharded-checkpoint seam), resume
  = re-form at the granted size + reshard-on-restore — a preempted
  job's loss curve is bit-identical to an uninterrupted run;
* :mod:`veles_tpu.sched.tenants` — the first native tenants: the
  genetic optimizer submits a whole generation of fitness evaluations
  as concurrent jobs, the ensemble trainer submits its members the
  same way;
* :mod:`veles_tpu.sched.cli` — ``python -m veles_tpu sched
  serve|submit|status``.
"""

from veles_tpu.sched.job import (DONE, FAILED, PENDING, PREEMPTED,
                                 RETRYING, RUNNING, Job, JobSpec)
from veles_tpu.sched.journal import JobJournal
from veles_tpu.sched.scheduler import (DevicePool, Scheduler,
                                       SchedulerControl)
from veles_tpu.sched.tenants import (ScheduledEnsembleTrainManager,
                                     ScheduledGeneticsOptimizer)

__all__ = ["JobSpec", "Job", "PENDING", "RUNNING", "PREEMPTED",
           "RETRYING", "DONE", "FAILED", "DevicePool", "JobJournal",
           "Scheduler", "SchedulerControl",
           "ScheduledGeneticsOptimizer", "ScheduledEnsembleTrainManager"]
