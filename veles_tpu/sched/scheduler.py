"""The gang scheduler: device pool + weighted-fair quotas + preemption.

One :class:`Scheduler` owns a :class:`DevicePool` of N device slots and
packs submitted :class:`~veles_tpu.sched.job.Job` gangs onto it:

* **gang placement** — a job wants ``world_min..world_max`` slots; the
  scheduler grants the LARGEST contiguous slice in range that fits
  (contiguous because a mesh slice is an ICI neighborhood, not a bag
  of devices), best-fit among the free holes so big holes survive for
  big gangs;
* **weighted-fair quotas** — per-tenant :class:`ShareAccount` ledgers
  from :mod:`veles_tpu.fairshare`, the SAME math the serving
  AdmissionController meters samples with, here metering device slots:
  a tenant under its guaranteed share always places (slots permitting);
  over-share placement may only borrow headroom no active tenant holds
  a claim on;
* **preemption = checkpoint + shrink** — a preemptible job (one with a
  ``snapshot_dir``) cuts a per-epoch sharded checkpoint through the
  elastic seam (``save_elastic_checkpoint`` riding
  ``snapshotter.save_snapshot_sharded``), so preempting it is the
  ElasticSupervisor kill: SIGKILL the gang's process groups. Resume
  respawns at the newly granted world size with the same snapshot
  directory — ``run_elastic_training`` restores the newest complete
  generation and reshard-on-restore re-partitions it, making the
  resumed loss curve bit-identical to an uninterrupted run (the
  PR 12/13 invariant, proven at this tier by
  ``tests/test_sched.py::test_preempt_resume_loss_parity``);
* a failed gang re-queues under its retry budget
  (``JobSpec.max_retries`` with jittered exponential backoff and
  crash-loop detection) and dumps a flight record
  (``sched_job_failed``) before the job lands in FAILED.

**Durability** (ISSUE 20): pass ``state_dir`` and every submit,
transition, grant, preempt and reap is journaled through
:class:`veles_tpu.sched.journal.JobJournal` before the caller sees
the result. A restarting scheduler replays the journal, rebuilds
jobs/accounts/pool holds, then reconciles reality: still-alive gangs
(workers run in their own sessions, so they survive our death) are
*adopted* in place via :class:`_AdoptedProc` — never killed — while
dead gangs route through the preempt-style resume (preemptible) or
the retry policy. PENDING/PREEMPTED jobs rejoin the queue with their
original submit times, so queue-wait accounting and fair-share do not
reset. The control surface answers 503 + Retry-After while replay is
in flight.

:class:`SchedulerControl` is the loopback HTTP surface the CLI talks
to: ``POST /submit`` (a JobSpec dict), ``GET /status``,
``GET /jobs.json`` — plus the ONE-pane-of-glass observability
surface: ``POST /telemetry`` absorbs each gang rank-0's delta-encoded
registry push into a per-job :class:`FederatedRegistry` feed, ``GET
/metrics`` / ``/metrics.json`` serve the cluster view with
``{job,tenant}`` labels, and ``GET /history.json?series=&since=``
serves the bounded time-series store. Every job runs under ONE
minted trace id (``VELES_ELASTIC_TRACE``) for its whole life, so
worker flight records, supervisor spans, and the scheduler's
``sched_job_failed`` record correlate.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from veles_tpu.fairshare import (DEFAULT_QOS, ShareAccount,
                                 guaranteed_share, reserved_claim)
from veles_tpu.logger import Logger
from veles_tpu.parallel.elastic import (ENV_COORD, ENV_GEN, ENV_JOB,
                                        ENV_RANK, ENV_SNAPSHOTS,
                                        ENV_TENANT, ENV_TRACE,
                                        ENV_WORLD, _free_port)
from veles_tpu.parallel.retry import backoff_delay
from veles_tpu.sched.job import (DONE, FAILED, PENDING, PREEMPTED,
                                 RETRYING, RUNNING, STATES, Job,
                                 _metrics, reserve_job_ids)
from veles_tpu.sched.journal import JobJournal


def _pid_alive(pid):
    """Is ``pid`` still a live process? pidfd when the platform has
    it (no pid-reuse race while the fd is held), signal-0 probe
    otherwise."""
    try:
        opener = os.pidfd_open
    except AttributeError:
        opener = None
    if opener is not None:
        try:
            os.close(opener(pid))
        except ProcessLookupError:
            return False
        except OSError:
            pass            # fall through to the portable probe
        else:
            return True
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class _AdoptedProc(object):
    """Popen-shaped handle for a gang member spawned by a PREVIOUS
    scheduler process and adopted across a restart.

    The member is NOT our child: init reaps it, so its real exit code
    is unobservable. :meth:`poll` therefore reports ``0`` the moment
    the process is gone — an adopted gang's exit is reaped as success
    by design (a worker that actually failed leaves its own flight
    records, and the job's result file tells the truth). Liveness
    rides a pidfd held open from adoption time when available (immune
    to pid reuse); otherwise the signal-0 probe."""

    def __init__(self, pid):
        self.pid = pid
        self._pidfd = None
        #: death is sticky: once observed dead, stay dead (the pidfd
        #: is consumed by the first observation, and a later signal-0
        #: probe could hit a reused pid — or an unreaped zombie)
        self._dead = False
        try:
            self._pidfd = os.pidfd_open(pid)
        except (AttributeError, OSError):
            pass

    def _alive(self):
        if self._dead:
            return False
        if self._pidfd is not None:
            import select
            # the pidfd becomes readable when the process exits
            ready, _, _ = select.select([self._pidfd], [], [], 0)
            if not ready:
                return True
            os.close(self._pidfd)
            self._pidfd = None
        elif _pid_alive(self.pid):
            return True
        self._dead = True
        return False

    def poll(self):
        return None if self._alive() else 0

    def wait(self, timeout=None):
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while self._alive():
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(
                    "adopted-pid-%d" % self.pid, timeout)
            time.sleep(0.05)
        return 0

    def kill(self):
        try:
            os.kill(self.pid, signal.SIGKILL)
        except OSError:
            pass


class DevicePool(object):
    """Slot inventory: ``size`` device slots, contiguous gang grants.

    Holes are tracked implicitly (the complement of held intervals);
    :meth:`allocate` is best-fit — the SMALLEST hole that still fits
    the gang — so one small job does not fragment the hole a large
    gang is waiting for.
    """

    def __init__(self, size):
        if int(size) < 1:
            raise ValueError("pool size must be > 0 (got %s)" % size)
        self.size = int(size)
        self._held = {}  # job_id -> (start, n)

    @property
    def held(self):
        return sum(n for _, n in self._held.values())

    @property
    def free(self):
        return self.size - self.held

    def holes(self):
        """Free contiguous ``(start, length)`` runs, ascending."""
        taken = sorted(self._held.values())
        holes, cursor = [], 0
        for start, n in taken:
            if start > cursor:
                holes.append((cursor, start - cursor))
            cursor = max(cursor, start + n)
        if cursor < self.size:
            holes.append((cursor, self.size - cursor))
        return holes

    def allocate(self, job_id, want):
        """Grant ``want`` contiguous slots to ``job_id`` (best-fit),
        or return ``None`` when no hole is big enough."""
        if job_id in self._held:
            raise ValueError("%s already holds slots" % job_id)
        best = None
        for start, length in self.holes():
            if length >= want and (best is None or length < best[1]):
                best = (start, length)
        if best is None:
            return None
        self._held[job_id] = (best[0], want)
        return tuple(range(best[0], best[0] + want))

    def hold(self, job_id, start, n):
        """Re-impose a journaled grant verbatim (recovery path): the
        exact ``(start, n)`` interval, validated against the pool
        bounds and every other hold — a collision means the journal
        and reality disagree, which must surface, not silently
        fragment."""
        start, n = int(start), int(n)
        if job_id in self._held:
            raise ValueError("%s already holds slots" % job_id)
        if n < 1 or start < 0 or start + n > self.size:
            raise ValueError(
                "hold [%d, %d) is outside the pool of %d"
                % (start, start + n, self.size))
        for other, (o_start, o_n) in self._held.items():
            if start < o_start + o_n and o_start < start + n:
                raise ValueError(
                    "hold [%d, %d) for %s overlaps %s at [%d, %d)"
                    % (start, start + n, job_id, other, o_start,
                       o_start + o_n))
        self._held[job_id] = (start, n)
        return tuple(range(start, start + n))

    def release(self, job_id):
        self._held.pop(job_id, None)


class Scheduler(Logger):
    """Multi-job gang scheduler over one device pool."""

    def __init__(self, pool_size, tick_s=0.2, preempt=True,
                 min_run_s=1.0, activity_window_s=10.0, python=None,
                 log_dir=None, state_dir=None, crash_loop_k=3,
                 crash_loop_window_s=60.0):
        super(Scheduler, self).__init__()
        self.pool = DevicePool(pool_size)
        self.tick_s = float(tick_s)
        self.preempt_enabled = bool(preempt)
        #: thrash guard: a job must RUN this long before it can be
        #: chosen as a victim — with it, mutual preemption degrades
        #: into round-robin time slices of at least min_run_s, not a
        #: kill storm
        self.min_run_s = float(min_run_s)
        self.activity_window_s = float(activity_window_s)
        self.python = python or sys.executable
        self.log_dir = log_dir
        #: crash-loop tripwire: this many failures inside the window
        #: overrides any remaining retry budget (a gang dying in a
        #: tight loop is a bug, not a transient)
        self.crash_loop_k = int(crash_loop_k)
        self.crash_loop_window_s = float(crash_loop_window_s)
        self._lock = threading.RLock()
        self._jobs = {}        # id -> Job (insertion = submission order)
        self._accounts = {}    # tenant -> ShareAccount
        self._grant_seq = 0
        self._metrics = _metrics()
        self._journal = None
        #: the control surface answers 503 while this is True; set
        #: from construction until recover() finishes so requests
        #: racing the replay never see half-rebuilt state
        self.recovering = False
        if state_dir:
            self._journal = JobJournal(state_dir,
                                       metrics=self._metrics)
            self.recovering = True
        #: per-job federation feeds (sid = job id), fed by POST
        #: /telemetry from each gang's rank-0 metrics pusher; lazy so
        #: a push-less scheduler never mints the federation families
        self._federation = None
        #: set by SchedulerControl: the /telemetry URL spawned gangs
        #: receive as VELES_SCHED_METRICS_URL
        self.metrics_url = None
        self._stop = threading.Event()
        self._thread = None

    # -- submission --------------------------------------------------------

    def submit(self, spec, now=None):
        now = time.time() if now is None else now
        if spec.world_max > self.pool.size:
            raise ValueError(
                "job wants up to %d slots but the pool has %d"
                % (spec.world_max, self.pool.size))
        with self._lock:
            job = Job(spec, metrics=self._metrics, now=now)
            self._jobs[job.id] = job
            account = self._account(spec.tenant, spec)
            account.last_active = now
            self._journal_event("submit", job, now)
            self.info("submitted %s (%s): tenant=%s qos=%s world=%d..%d"
                      "%s", job.id, spec.name, spec.tenant, spec.qos,
                      spec.world_min, spec.world_max,
                      " preemptible" if spec.preemptible else "")
        return job

    # -- durability --------------------------------------------------------

    def _journal_event(self, ev, job, now, **extra):
        """One durable upsert line: the event name is decoration for
        humans; the job's FULL record is the payload (what makes
        replay idempotent). Compacts when the journal is over size."""
        if self._journal is None:
            return
        event = {"ev": ev, "t": now, "grant_seq": self._grant_seq,
                 "job": job.record()}
        account = self._accounts.get(job.spec.tenant)
        if account is not None:
            event["account"] = {
                "tenant": account.name, "weight": account.weight,
                "qos": account.qos,
                "admitted_total": account.admitted_total}
        event.update(extra)
        self._journal.append(event)
        if self._journal.should_compact():
            self._journal.compact(self._image_locked())

    def _image_locked(self):
        """The compacted journal snapshot: full scheduler state."""
        return {
            "grant_seq": self._grant_seq,
            "jobs": [j.record() for j in self._jobs.values()],
            "accounts": {
                a.name: {"tenant": a.name, "weight": a.weight,
                         "qos": a.qos,
                         "admitted_total": a.admitted_total}
                for a in self._accounts.values()},
        }

    def recover(self, now=None):
        """Replay the journal and reconcile against reality. Runs
        once, synchronously, before the tick loop — the control
        surface 503s until it returns."""
        if self._journal is None:
            return self
        now = time.time() if now is None else now
        try:
            with self._lock:
                t0 = time.perf_counter()
                image, events = self._journal.replay()
                self._replay_locked(image, events, now)
                self._metrics["recovery_ms"].labels(
                    phase="replay").observe(
                        (time.perf_counter() - t0) * 1e3)
                self._metrics["replays"].inc()
                self._reconcile_locked(now)
                # fold everything just replayed into one fresh image
                # so the NEXT restart replays a snapshot, not history
                self._journal.compact(self._image_locked())
        finally:
            self.recovering = False
        return self

    def _replay_locked(self, image, events, now):
        records = {}
        accounts = {}
        grant_seq = 0
        if image:
            grant_seq = int(image.get("grant_seq") or 0)
            for record in image.get("jobs") or ():
                if isinstance(record, dict) and "id" in record:
                    records[record["id"]] = record
            for name, info in (image.get("accounts") or {}).items():
                accounts[name] = info
        for event in events:
            record = event.get("job")
            if isinstance(record, dict) and "id" in record:
                # upsert keeps the FIRST-insert position: submission
                # order survives replay, which the fair queue needs
                records[record["id"]] = record
            grant_seq = max(grant_seq,
                            int(event.get("grant_seq") or 0))
            info = event.get("account")
            if isinstance(info, dict) and info.get("tenant"):
                accounts[info["tenant"]] = info
        floor = 0
        for record in records.values():
            try:
                job = Job.from_record(record, metrics=self._metrics)
            except (KeyError, TypeError, ValueError) as e:
                self.warning("dropping unreadable journaled job "
                             "%r: %s", record.get("id"), e)
                continue
            self._jobs[job.id] = job
            suffix = job.id.rsplit("-", 1)[-1]
            if suffix.isdigit():
                floor = max(floor, int(suffix))
        reserve_job_ids(floor)
        self._grant_seq = grant_seq
        for job in self._jobs.values():
            account = self._account(job.spec.tenant, job.spec)
            account.last_active = max(
                account.last_active, job.submitted_t,
                job.started_t or 0.0, job.finished_t or 0.0)
            if job.finished_t is not None:
                account.completions.append(job.finished_t)
            if job.state == RUNNING and job.slots:
                account.outstanding += job.granted_world
                self.pool.hold(job.id, job.slots[0],
                               len(job.slots))
        for name, info in accounts.items():
            account = self._account(name)
            account.weight = float(info.get("weight",
                                            account.weight))
            account.qos = info.get("qos", account.qos)
            account.admitted_total = int(
                info.get("admitted_total", account.admitted_total))
        self.info("journal replay: %d job(s), %d account(s), "
                  "grant_seq=%d", len(self._jobs),
                  len(self._accounts), self._grant_seq)

    def _reconcile_locked(self, now):
        """Journal state vs reality: adopt gangs that survived our
        death, route dead ones through resume/retry."""
        t0 = time.perf_counter()
        running = [j for j in self._jobs.values()
                   if j.state == RUNNING]
        alive = {job.id: bool(job.pids) and
                 all(_pid_alive(pid) for pid in job.pids)
                 for job in running}
        self._metrics["recovery_ms"].labels(phase="probe").observe(
            (time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        for job in running:
            if alive[job.id]:
                job.procs = [_AdoptedProc(pid) for pid in job.pids]
                self._metrics["adopted"].inc()
                self._journal_event("adopt", job, now)
                self.info("%s: adopted surviving gang (pids %s)",
                          job.id, list(job.pids))
                continue
            # the gang died while we were down: some members may
            # still linger — take the remains down before re-placing
            for pid in job.pids:
                try:
                    os.killpg(pid, signal.SIGKILL)
                except OSError:
                    pass
            self._release_locked(job, now)
            if job.spec.preemptible:
                job.transition(PREEMPTED, now)
                self._journal_event("recover", job, now)
                self.info("%s: gang died while scheduler was down — "
                          "resuming from checkpoint", job.id)
            else:
                self._fail_or_retry_locked(
                    job, now,
                    "gang died while scheduler was down")
        self._metrics["recovery_ms"].labels(phase="adopt").observe(
            (time.perf_counter() - t0) * 1e3)

    def _account(self, tenant, spec=None):
        account = self._accounts.get(tenant)
        if account is None:
            account = self._accounts[tenant] = ShareAccount(
                tenant, weight=spec.weight if spec else 1.0,
                qos=spec.qos if spec else DEFAULT_QOS)
        elif spec is not None:
            # latest submission's weight/qos wins (one account per
            # tenant; jobs are the granularity specs ride in on)
            account.weight = spec.weight
            account.qos = spec.qos
        return account

    def jobs(self):
        with self._lock:
            return list(self._jobs.values())

    def get(self, job_id):
        with self._lock:
            return self._jobs.get(job_id)

    def wait(self, job_ids, timeout_s=None, poll_s=0.05):
        """Block until every listed job is terminal (DONE/FAILED).
        Returns ``{id: state}``; raises ``TimeoutError`` on timeout.
        Requires a started scheduler (the tick thread does the work)."""
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        ids = list(job_ids)
        while True:
            with self._lock:
                jobs = [self._jobs[i] for i in ids]
                if all(j.terminal for j in jobs):
                    return {j.id: j.state for j in jobs}
            if deadline and time.monotonic() > deadline:
                raise TimeoutError(
                    "jobs still not terminal after %.0fs: %s"
                    % (timeout_s, [j.id for j in jobs
                                   if not j.terminal]))
            time.sleep(poll_s)

    # -- the tick ----------------------------------------------------------

    def tick(self, now=None):
        """One scheduling pass: reap finished gangs, place runnable
        jobs (preempting when fair-share justifies it), publish the
        gauges. The loop calls this; tests drive it directly."""
        now = time.time() if now is None else now
        with self._lock:
            self._reap_locked(now)
            self._schedule_locked(now)
            self._publish_locked(now)

    def _reap_locked(self, now):
        for job in self._jobs.values():
            if job.state != RUNNING:
                continue
            codes = [proc.poll() for proc in job.procs]
            if any(code not in (None, 0) for code in codes):
                # one gang member died: the rest are wedged in (or
                # heading into) a dead collective — take the gang down
                self._kill_gang(job)
                self._release_locked(job, now)
                rc = [c for c in codes if c not in (None, 0)][0]
                self._fail_or_retry_locked(
                    job, now, "worker exited rc=%s" % (rc,),
                    rc=codes)
            elif all(code == 0 for code in codes):
                self._release_locked(job, now)
                job.transition(DONE, now)
                self._journal_event("reap", job, now, rc=0)
                self._drop_job_view_locked(job)
                self.info("%s done (world=%d, %d preemption%s)",
                          job.id, job.granted_world, job.preemptions,
                          "" if job.preemptions == 1 else "s")

    def _fail_or_retry_locked(self, job, now, error, rc=None):
        """The failure policy: re-queue with backoff while retry
        budget remains, UNLESS the gang is crash-looping
        (``crash_loop_k`` failures inside ``crash_loop_window_s``) —
        a tight failure loop is a bug to surface, not a transient to
        absorb. Terminal failures dump the correlated
        ``sched_job_failed`` flight record."""
        job.failure_times.append(now)
        cutoff = now - self.crash_loop_window_s
        job.failure_times = [t for t in job.failure_times
                             if t >= cutoff]
        crash_loop = len(job.failure_times) >= self.crash_loop_k
        if not crash_loop and job.retries < job.spec.max_retries:
            job.error = "%s (retrying %d/%d)" % (
                error, job.retries + 1, job.spec.max_retries)
            job.transition(RETRYING, now)
            job.retry_at = now + backoff_delay(
                job.retries - 1, base_s=job.spec.retry_backoff_s)
            self._journal_event("reap", job, now, rc=rc)
            self.warning("%s: %s — retry %d/%d in %.2fs", job.id,
                         error, job.retries, job.spec.max_retries,
                         job.retry_at - now)
            return
        if crash_loop:
            error = "%s (crash loop: %d failures in %.0fs)" % (
                error, len(job.failure_times),
                self.crash_loop_window_s)
        job.error = error
        job.transition(FAILED, now)
        self._journal_event("reap", job, now, rc=rc)
        self._drop_job_view_locked(job)
        self.warning("%s failed: %s", job.id, job.error)
        from veles_tpu.telemetry.flight import get_recorder
        get_recorder().dump("sched_job_failed", job=job.to_dict(),
                            rc=rc, retries=job.retries,
                            failures=list(job.failure_times),
                            trace_id=job.trace_id)

    def _schedule_locked(self, now):
        # resumes first (a preempted job already earned its slot once),
        # oldest-runnable first within each class; ready() keeps a
        # RETRYING job parked until its backoff hold expires
        runnable = [j for j in self._jobs.values() if j.ready(now)]
        runnable.sort(key=lambda j: (j.state != PREEMPTED,
                                     j.runnable_since))
        for job in runnable:
            if self._try_place_locked(job, now):
                continue
            if self.preempt_enabled and \
                    self._try_preempt_for_locked(job, now):
                self._try_place_locked(job, now)

    def _gate_locked(self, account, want, now):
        """The fair-share admission gate for ``want`` more slots."""
        accounts = self._accounts.values()
        share = guaranteed_share(self.pool.size, account, accounts,
                                 now, self.activity_window_s)
        if account.outstanding + want <= share:
            return True
        reserved = reserved_claim(self.pool.size, account, accounts,
                                  now, self.activity_window_s)
        return want <= self.pool.size - self.pool.held - reserved

    def _try_place_locked(self, job, now):
        account = self._accounts[job.spec.tenant]
        for want in range(min(job.spec.world_max, self.pool.free),
                          job.spec.world_min - 1, -1):
            if not self._gate_locked(account, want, now):
                continue
            slots = self.pool.allocate(job.id, want)
            if slots is None:
                continue
            # account BEFORE the spawn journals its "grant" event, so
            # the journaled ledger matches the grant it rides with
            account.outstanding += want
            account.admitted_total += want
            account.last_active = now
            try:
                self._spawn_locked(job, slots, now)
            except OSError as e:
                account.outstanding -= want
                account.admitted_total -= want
                self.pool.release(job.id)
                job.error = "spawn failed: %s" % e
                job.transition(FAILED, now)
                self._journal_event("spawn_failed", job, now)
                return False
            return True
        return False

    def _try_preempt_for_locked(self, job, now):
        """Preempt ONE victim gang to make room for ``job``, when the
        fair-share ledger justifies it: the claimant tenant is under
        its guaranteed share, the victim's tenant is at-or-over its
        own, and the victim has run at least ``min_run_s`` (the
        thrash guard that turns contention into time slices)."""
        account = self._accounts[job.spec.tenant]
        accounts = self._accounts.values()
        share = guaranteed_share(self.pool.size, account, accounts,
                                 now, self.activity_window_s)
        if account.outstanding + job.spec.world_min > share:
            return False            # not owed anything — wait, don't kill
        victims = []
        for other in self._jobs.values():
            if other.state != RUNNING or not other.spec.preemptible:
                continue
            if other.spec.tenant == job.spec.tenant:
                continue
            if now - other.history[-1][0] < self.min_run_s:
                continue
            v_account = self._accounts[other.spec.tenant]
            v_share = guaranteed_share(self.pool.size, v_account,
                                       accounts, now,
                                       self.activity_window_s)
            if v_account.outstanding < v_share:
                continue            # that tenant is within its guarantee
            victims.append((v_account.outstanding - v_share,
                            other.history[-1][0], other))
        if not victims:
            return False
        # most over-share tenant first; within it, the most recently
        # (re)started gang loses the least completed work
        victims.sort(key=lambda v: (-v[0], -v[1]))
        victim = victims[0][2]
        self.info("preempting %s (tenant %s) for %s (tenant %s) — "
                  "checkpoint + shrink", victim.id, victim.spec.tenant,
                  job.id, job.spec.tenant)
        self._kill_gang(victim)
        self._release_locked(victim, now)
        victim.transition(PREEMPTED, now)
        self._journal_event("preempt", victim, now)
        return True

    # -- gang lifecycle ----------------------------------------------------

    def _spawn_locked(self, job, slots, now):
        world = len(slots)
        self._grant_seq += 1
        job.grants += 1
        coord = None
        if world > 1:
            coord = "127.0.0.1:%d" % _free_port()
        argv = job.spec.build_argv(python=self.python)
        procs = []
        for rank in range(world):
            env = dict(os.environ)
            env.update(job.spec.env)
            env[ENV_GEN] = str(self._grant_seq)
            env[ENV_WORLD] = str(world)
            env[ENV_RANK] = str(rank)
            # trace correlation + the job view: every grant of this
            # job (resumes included) runs under the SAME trace id,
            # and rank 0 pushes its registry deltas back to us
            env[ENV_TRACE] = job.trace_id
            env[ENV_JOB] = job.id
            env[ENV_TENANT] = job.spec.tenant
            if self.metrics_url:
                env["VELES_SCHED_METRICS_URL"] = self.metrics_url
            if coord:
                env[ENV_COORD] = coord
            else:
                env.pop(ENV_COORD, None)
            if job.spec.snapshot_dir:
                env[ENV_SNAPSHOTS] = job.spec.snapshot_dir
            stdout = stderr = None
            logf = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                logf = open(os.path.join(
                    self.log_dir, "%s-g%d-r%d.log"
                    % (job.id, job.grants, rank)), "ab")
                stdout = stderr = logf
            try:
                procs.append(subprocess.Popen(
                    argv, env=env, stdout=stdout, stderr=stderr,
                    start_new_session=True))
            finally:
                if logf is not None:
                    logf.close()   # the child keeps its own dup
        job.slots = slots
        job.granted_world = world
        job.procs = procs
        job.pids = tuple(proc.pid for proc in procs)
        job.transition(RUNNING, now)
        self._journal_event("grant", job, now)
        self.info("%s: granted slots %s (world=%d, grant #%d)",
                  job.id, list(slots), world, job.grants)

    def _kill_gang(self, job):
        """The ElasticSupervisor kill: SIGKILL each member's process
        group (workers run in their own sessions) — per-epoch sharded
        checkpoints make this checkpoint + shrink, not data loss."""
        for proc in job.procs:
            if proc.poll() is not None:
                continue
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    proc.kill()
                except OSError:
                    pass
        for proc in job.procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    def _release_locked(self, job, now):
        if not job.granted_world:
            return
        account = self._accounts[job.spec.tenant]
        account.outstanding = max(
            0, account.outstanding - job.granted_world)
        account.completions.append(now)
        account.last_active = now
        self.pool.release(job.id)
        job.slots = ()
        job.granted_world = 0
        job.procs = []
        job.pids = ()

    # -- telemetry ---------------------------------------------------------

    #: gang registry families mirrored into the per-job view:
    #: (federated family, Job.live key, _metrics key, mirror family)
    _LIVE_FAMILIES = (
        ("veles_train_loss", "loss", "job_loss",
         "veles_sched_job_loss"),
        ("veles_train_samples_per_s", "samples_per_s",
         "job_samples", "veles_sched_job_samples_per_s"),
        ("veles_step_mfu", "mfu", "job_mfu",
         "veles_sched_job_mfu"),
    )

    def absorb_telemetry(self, job_id, delta):
        """Merge one POST ``/telemetry`` delta (a gang rank-0 push)
        into the job's federation feed; returns the ack hints
        (``{"resync": True}`` asks the pusher for a full snapshot).
        A feed from a job we no longer track is GC'd, not stored."""
        with self._lock:
            job = self._jobs.get(job_id)
            live = job is not None and not job.terminal
            if live and self._federation is None:
                from veles_tpu.telemetry.federation import \
                    FederatedRegistry
                self._federation = FederatedRegistry()
            federation = self._federation
        if not live or federation is None:
            if federation is not None:
                federation.remove_slave(job_id)
            return {}
        # apply OUTSIDE the scheduler lock (the feed has its own),
        # then re-check liveness — the gang may have been reaped
        # while the delta merged
        hints = federation.apply(job_id, delta)
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.terminal:
                federation.remove_slave(job_id)
                return {}
            job.live["beat_t"] = time.time()
        return hints or {}

    def _drop_job_view_locked(self, job):
        """GC a terminal job's federation feed and mirror gauges
        (history keeps its points until retention ages them out)."""
        if self._federation is not None:
            self._federation.remove_slave(job.id)
        job_id = job.id
        for _, _, metric, _ in self._LIVE_FAMILIES:
            self._metrics[metric].remove(job=job_id)
        self._metrics["beat_age"].remove(job=job_id)
        self._metrics["loss_age"].remove(job=job_id)

    def _publish_jobs_locked(self, now):
        """Fold the federation feeds into the per-job mirror gauges
        and the history store — the live half of /jobs.json."""
        if self._federation is None:
            return
        latest = {}
        for sid, tag, name, _, data in self._federation.series_rows():
            if tag != "g":
                continue
            for family, key, _, _ in self._LIVE_FAMILIES:
                if name == family:
                    latest.setdefault(sid, {})[key] = data
        from veles_tpu.telemetry.timeseries import get_history
        history = get_history()
        for job in self._jobs.values():
            if job.terminal:
                continue
            fresh = latest.get(job.id)
            if fresh:
                if "loss" in fresh and \
                        fresh["loss"] != job.live.get("loss"):
                    job.live["loss_t"] = now
                job.live.update(fresh)
            if not job.live:
                continue
            job_id, tenant = job.id, job.spec.tenant
            for _, key, metric, mirror in self._LIVE_FAMILIES:
                value = job.live.get(key)
                if value is None:
                    continue
                self._metrics[metric].labels(
                    job=job_id, tenant=tenant).set(value)
                # only a RUNNING gang appends history: a preempted
                # job's series must show the gap, not a flat line
                if job.state == RUNNING:
                    history.record(
                        mirror, {"job": job_id, "tenant": tenant},
                        value, now=now)
            beat_t = job.live.get("beat_t")
            if beat_t is not None:
                self._metrics["beat_age"].labels(
                    job=job_id, tenant=tenant).set(now - beat_t)
            loss_t = job.live.get("loss_t")
            if loss_t is not None:
                self._metrics["loss_age"].labels(
                    job=job_id, tenant=tenant).set(now - loss_t)

    def _publish_locked(self, now):
        counts = dict.fromkeys(STATES, 0)
        oldest = 0.0
        waits = {}
        for job in self._jobs.values():
            counts[job.state] += 1
            if job.runnable:
                wait = now - job.runnable_since
                oldest = max(oldest, wait)
                tenant = job.spec.tenant
                waits[tenant] = max(waits.get(tenant, 0.0), wait)
        for state, n in counts.items():
            self._metrics["jobs"].labels(state=state).set(n)
        self._metrics["devices"].labels(state="free").set(
            self.pool.free)
        self._metrics["devices"].labels(state="held").set(
            self.pool.held)
        self._metrics["oldest_wait"].set(oldest)
        accounts = self._accounts.values()
        for tenant, account in self._accounts.items():
            self._metrics["tenant_wait"].labels(tenant=tenant).set(
                waits.get(tenant, 0.0))
            share = guaranteed_share(self.pool.size, account,
                                     accounts, now,
                                     self.activity_window_s)
            self._metrics["share_fraction"].labels(
                tenant=tenant).set(share / self.pool.size)
        self._publish_jobs_locked(now)

    def cluster_snapshot(self):
        """The ONE cluster view: the scheduler's own registry
        snapshot with every job feed's series folded in under
        ``{job, tenant}`` labels — the /metrics(.json) body."""
        from veles_tpu.telemetry.registry import get_registry
        with self._lock:
            tenants = {job.id: job.spec.tenant
                       for job in self._jobs.values()}
            federation = self._federation
        snap = get_registry().snapshot()
        if federation is None:
            return snap
        kind_of = {"c": "counters", "g": "gauges", "h": "histograms"}
        for sid, tag, name, labels, data in federation.series_rows():
            bucket = snap[kind_of[tag]]
            family = bucket.get(name)
            if family is None:
                family = bucket[name] = {"help": "", "series": []}
            labels = dict(labels)
            labels["job"] = sid
            tenant = tenants.get(sid)
            if tenant:
                labels["tenant"] = tenant
            if tag == "h":
                entry = dict(data)
                entry["labels"] = labels
            else:
                entry = {"value": data, "labels": labels}
            family["series"].append(entry)
        return snap

    def stats(self, now=None):
        now = time.time() if now is None else now
        with self._lock:
            counts = dict.fromkeys(STATES, 0)
            for job in self._jobs.values():
                counts[job.state] += 1
            return {
                "pool": {"size": self.pool.size,
                         "free": self.pool.free,
                         "held": self.pool.held},
                "jobs": counts,
                "tenants": {
                    a.name: {
                        "weight": a.weight, "qos": a.qos,
                        "held": a.outstanding,
                        "granted": a.admitted_total,
                        "share": round(guaranteed_share(
                            self.pool.size, a, self._accounts.values(),
                            now, self.activity_window_s), 1),
                        "share_fraction": round(guaranteed_share(
                            self.pool.size, a, self._accounts.values(),
                            now, self.activity_window_s)
                            / self.pool.size, 4),
                    } for a in self._accounts.values()},
            }

    def jobs_report(self):
        """The ``/jobs.json`` body (also what a dashboard push
        embeds as its ``jobs`` list)."""
        with self._lock:
            return {"jobs": [job.to_dict()
                             for job in self._jobs.values()]}

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Recover from the journal (when configured), then run the
        tick loop on a daemon thread."""
        self.recover()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="sched-tick")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:
                self.exception("scheduler tick failed")

    def stop(self, kill=True):
        """Stop the loop; ``kill`` takes down every running gang (a
        drain would wait for them — the caller owns that choice)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if kill:
            with self._lock:
                now = time.time()
                for job in self._jobs.values():
                    if job.state == RUNNING:
                        self._kill_gang(job)
                        self._release_locked(job, now)
                        job.error = "scheduler stopped"
                        job.transition(FAILED, now)
                        self._journal_event("stop", job, now)
                        self._drop_job_view_locked(job)
        if self._journal is not None:
            self._journal.close()


class _ControlHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        self.server.owner.debug("http: " + fmt, *args)

    def _reply(self, body, code=200, headers=None):
        data = json.dumps(body).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def _recovering(self, scheduler):
        """503 + Retry-After while journal replay is in flight — the
        state a client would read is not rebuilt yet."""
        if not scheduler.recovering:
            return False
        self._reply({"error": "scheduler is recovering, retry"},
                    code=503, headers={"Retry-After": "1"})
        return True

    def _reply_text(self, body, content_type="text/plain"):
        data = body.encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        scheduler = self.server.owner.scheduler
        if self._recovering(scheduler):
            return
        if self.path.startswith("/status"):
            self._reply(scheduler.stats())
        elif self.path.startswith("/jobs.json"):
            self._reply(scheduler.jobs_report())
        elif self.path.startswith("/history.json"):
            query = parse_qs(urlsplit(self.path).query)
            from veles_tpu.telemetry.timeseries import get_history
            try:
                self._reply(get_history().query(
                    series=(query.get("series") or [None])[0],
                    since=(query.get("since") or [None])[0]))
            except (TypeError, ValueError):
                self._reply({"error": "bad since cursor"}, code=400)
        elif self.path.startswith("/metrics.json"):
            self._reply(scheduler.cluster_snapshot())
        elif self.path.startswith("/metrics"):
            from veles_tpu.telemetry.registry import render_snapshot
            self._reply_text(
                render_snapshot(scheduler.cluster_snapshot()),
                content_type="text/plain; version=0.0.4")
        else:
            self._reply({"error": "not found"}, code=404)

    def do_POST(self):
        scheduler = self.server.owner.scheduler
        if self._recovering(scheduler):
            return
        if self.path.startswith("/telemetry"):
            try:
                length = int(self.headers.get("Content-Length", 0))
                data = json.loads(
                    self.rfile.read(length).decode("utf-8"))
                hints = scheduler.absorb_telemetry(
                    str(data.get("job") or ""),
                    data.get("telemetry"))
            except (TypeError, ValueError, KeyError) as e:
                self._reply({"error": str(e) or type(e).__name__},
                            code=400)
                return
            self._reply(hints)
            return
        if not self.path.startswith("/submit"):
            self._reply({"error": "not found"}, code=404)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            data = json.loads(self.rfile.read(length).decode("utf-8"))
            from veles_tpu.sched.job import JobSpec
            job = scheduler.submit(JobSpec.from_dict(data))
        except (TypeError, ValueError, KeyError) as e:
            self._reply({"error": str(e) or type(e).__name__},
                        code=400)
            return
        self._reply({"id": job.id, "state": job.state})


class SchedulerControl(Logger):
    """Loopback HTTP control plane for one scheduler: ``POST
    /submit`` + ``POST /telemetry`` (gang metrics pushes), ``GET
    /status``, ``GET /jobs.json``, and the cluster observability
    surface ``GET /metrics`` / ``/metrics.json`` /
    ``/history.json?series=&since=``. Binds loopback by default —
    the submit surface executes commands, so exposing it beyond the
    host is an operator's explicit choice."""

    def __init__(self, scheduler, host="127.0.0.1", port=0):
        super(SchedulerControl, self).__init__()
        self.scheduler = scheduler
        self._server = ThreadingHTTPServer((host, port),
                                           _ControlHandler)
        self._server.owner = self
        self._server.daemon_threads = True
        self.address = self._server.server_address
        # spawned gangs learn where to push their registry deltas
        scheduler.metrics_url = (
            "http://127.0.0.1:%d/telemetry" % self.address[1])
        self._thread = None

    @property
    def port(self):
        return self.address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="sched-control")
        self._thread.start()
        self.info("scheduler control on %s:%d", *self.address)
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
