"""The gang scheduler: device pool + weighted-fair quotas + preemption.

One :class:`Scheduler` owns a :class:`DevicePool` of N device slots and
packs submitted :class:`~veles_tpu.sched.job.Job` gangs onto it:

* **gang placement** — a job wants ``world_min..world_max`` slots; the
  scheduler grants the LARGEST contiguous slice in range that fits
  (contiguous because a mesh slice is an ICI neighborhood, not a bag
  of devices), best-fit among the free holes so big holes survive for
  big gangs;
* **weighted-fair quotas** — per-tenant :class:`ShareAccount` ledgers
  from :mod:`veles_tpu.fairshare`, the SAME math the serving
  AdmissionController meters samples with, here metering device slots:
  a tenant under its guaranteed share always places (slots permitting);
  over-share placement may only borrow headroom no active tenant holds
  a claim on;
* **preemption = checkpoint + shrink** — a preemptible job (one with a
  ``snapshot_dir``) cuts a per-epoch sharded checkpoint through the
  elastic seam (``save_elastic_checkpoint`` riding
  ``snapshotter.save_snapshot_sharded``), so preempting it is the
  ElasticSupervisor kill: SIGKILL the gang's process groups. Resume
  respawns at the newly granted world size with the same snapshot
  directory — ``run_elastic_training`` restores the newest complete
  generation and reshard-on-restore re-partitions it, making the
  resumed loss curve bit-identical to an uninterrupted run (the
  PR 12/13 invariant, proven at this tier by
  ``tests/test_sched.py::test_preempt_resume_loss_parity``);
* a failed gang dumps a flight record (``sched_job_failed``) before
  the job lands in FAILED.

:class:`SchedulerControl` is the loopback HTTP surface the CLI talks
to: ``POST /submit`` (a JobSpec dict), ``GET /status``,
``GET /jobs.json``.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu.fairshare import (DEFAULT_QOS, ShareAccount,
                                 guaranteed_share, reserved_claim)
from veles_tpu.logger import Logger
from veles_tpu.parallel.elastic import (ENV_COORD, ENV_GEN, ENV_RANK,
                                        ENV_SNAPSHOTS, ENV_WORLD,
                                        _free_port)
from veles_tpu.sched.job import (DONE, FAILED, PENDING, PREEMPTED,
                                 RUNNING, STATES, Job, _metrics)


class DevicePool(object):
    """Slot inventory: ``size`` device slots, contiguous gang grants.

    Holes are tracked implicitly (the complement of held intervals);
    :meth:`allocate` is best-fit — the SMALLEST hole that still fits
    the gang — so one small job does not fragment the hole a large
    gang is waiting for.
    """

    def __init__(self, size):
        if int(size) < 1:
            raise ValueError("pool size must be > 0 (got %s)" % size)
        self.size = int(size)
        self._held = {}  # job_id -> (start, n)

    @property
    def held(self):
        return sum(n for _, n in self._held.values())

    @property
    def free(self):
        return self.size - self.held

    def holes(self):
        """Free contiguous ``(start, length)`` runs, ascending."""
        taken = sorted(self._held.values())
        holes, cursor = [], 0
        for start, n in taken:
            if start > cursor:
                holes.append((cursor, start - cursor))
            cursor = max(cursor, start + n)
        if cursor < self.size:
            holes.append((cursor, self.size - cursor))
        return holes

    def allocate(self, job_id, want):
        """Grant ``want`` contiguous slots to ``job_id`` (best-fit),
        or return ``None`` when no hole is big enough."""
        if job_id in self._held:
            raise ValueError("%s already holds slots" % job_id)
        best = None
        for start, length in self.holes():
            if length >= want and (best is None or length < best[1]):
                best = (start, length)
        if best is None:
            return None
        self._held[job_id] = (best[0], want)
        return tuple(range(best[0], best[0] + want))

    def release(self, job_id):
        self._held.pop(job_id, None)


class Scheduler(Logger):
    """Multi-job gang scheduler over one device pool."""

    def __init__(self, pool_size, tick_s=0.2, preempt=True,
                 min_run_s=1.0, activity_window_s=10.0, python=None,
                 log_dir=None):
        super(Scheduler, self).__init__()
        self.pool = DevicePool(pool_size)
        self.tick_s = float(tick_s)
        self.preempt_enabled = bool(preempt)
        #: thrash guard: a job must RUN this long before it can be
        #: chosen as a victim — with it, mutual preemption degrades
        #: into round-robin time slices of at least min_run_s, not a
        #: kill storm
        self.min_run_s = float(min_run_s)
        self.activity_window_s = float(activity_window_s)
        self.python = python or sys.executable
        self.log_dir = log_dir
        self._lock = threading.RLock()
        self._jobs = {}        # id -> Job (insertion = submission order)
        self._accounts = {}    # tenant -> ShareAccount
        self._grant_seq = 0
        self._metrics = _metrics()
        self._stop = threading.Event()
        self._thread = None

    # -- submission --------------------------------------------------------

    def submit(self, spec, now=None):
        now = time.time() if now is None else now
        if spec.world_max > self.pool.size:
            raise ValueError(
                "job wants up to %d slots but the pool has %d"
                % (spec.world_max, self.pool.size))
        with self._lock:
            job = Job(spec, metrics=self._metrics, now=now)
            self._jobs[job.id] = job
            account = self._account(spec.tenant, spec)
            account.last_active = now
            self.info("submitted %s (%s): tenant=%s qos=%s world=%d..%d"
                      "%s", job.id, spec.name, spec.tenant, spec.qos,
                      spec.world_min, spec.world_max,
                      " preemptible" if spec.preemptible else "")
        return job

    def _account(self, tenant, spec=None):
        account = self._accounts.get(tenant)
        if account is None:
            account = self._accounts[tenant] = ShareAccount(
                tenant, weight=spec.weight if spec else 1.0,
                qos=spec.qos if spec else DEFAULT_QOS)
        elif spec is not None:
            # latest submission's weight/qos wins (one account per
            # tenant; jobs are the granularity specs ride in on)
            account.weight = spec.weight
            account.qos = spec.qos
        return account

    def jobs(self):
        with self._lock:
            return list(self._jobs.values())

    def get(self, job_id):
        with self._lock:
            return self._jobs.get(job_id)

    def wait(self, job_ids, timeout_s=None, poll_s=0.05):
        """Block until every listed job is terminal (DONE/FAILED).
        Returns ``{id: state}``; raises ``TimeoutError`` on timeout.
        Requires a started scheduler (the tick thread does the work)."""
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        ids = list(job_ids)
        while True:
            with self._lock:
                jobs = [self._jobs[i] for i in ids]
                if all(j.terminal for j in jobs):
                    return {j.id: j.state for j in jobs}
            if deadline and time.monotonic() > deadline:
                raise TimeoutError(
                    "jobs still not terminal after %.0fs: %s"
                    % (timeout_s, [j.id for j in jobs
                                   if not j.terminal]))
            time.sleep(poll_s)

    # -- the tick ----------------------------------------------------------

    def tick(self, now=None):
        """One scheduling pass: reap finished gangs, place runnable
        jobs (preempting when fair-share justifies it), publish the
        gauges. The loop calls this; tests drive it directly."""
        now = time.time() if now is None else now
        with self._lock:
            self._reap_locked(now)
            self._schedule_locked(now)
            self._publish_locked(now)

    def _reap_locked(self, now):
        for job in self._jobs.values():
            if job.state != RUNNING:
                continue
            codes = [proc.poll() for proc in job.procs]
            if any(code not in (None, 0) for code in codes):
                # one gang member died: the rest are wedged in (or
                # heading into) a dead collective — take the gang down
                self._kill_gang(job)
                self._release_locked(job, now)
                job.error = "worker exited rc=%s" % (
                    [c for c in codes if c not in (None, 0)][0],)
                job.transition(FAILED, now)
                self.warning("%s failed: %s", job.id, job.error)
                from veles_tpu.telemetry.flight import get_recorder
                get_recorder().dump("sched_job_failed",
                                    job=job.to_dict(), rc=codes)
            elif all(code == 0 for code in codes):
                self._release_locked(job, now)
                job.transition(DONE, now)
                self.info("%s done (world=%d, %d preemption%s)",
                          job.id, job.granted_world, job.preemptions,
                          "" if job.preemptions == 1 else "s")

    def _schedule_locked(self, now):
        # resumes first (a preempted job already earned its slot once),
        # oldest-runnable first within each class
        runnable = [j for j in self._jobs.values() if j.runnable]
        runnable.sort(key=lambda j: (j.state != PREEMPTED,
                                     j.runnable_since))
        for job in runnable:
            if self._try_place_locked(job, now):
                continue
            if self.preempt_enabled and \
                    self._try_preempt_for_locked(job, now):
                self._try_place_locked(job, now)

    def _gate_locked(self, account, want, now):
        """The fair-share admission gate for ``want`` more slots."""
        accounts = self._accounts.values()
        share = guaranteed_share(self.pool.size, account, accounts,
                                 now, self.activity_window_s)
        if account.outstanding + want <= share:
            return True
        reserved = reserved_claim(self.pool.size, account, accounts,
                                  now, self.activity_window_s)
        return want <= self.pool.size - self.pool.held - reserved

    def _try_place_locked(self, job, now):
        account = self._accounts[job.spec.tenant]
        for want in range(min(job.spec.world_max, self.pool.free),
                          job.spec.world_min - 1, -1):
            if not self._gate_locked(account, want, now):
                continue
            slots = self.pool.allocate(job.id, want)
            if slots is None:
                continue
            try:
                self._spawn_locked(job, slots, now)
            except OSError as e:
                self.pool.release(job.id)
                job.error = "spawn failed: %s" % e
                job.transition(FAILED, now)
                return False
            account.outstanding += want
            account.admitted_total += want
            account.last_active = now
            return True
        return False

    def _try_preempt_for_locked(self, job, now):
        """Preempt ONE victim gang to make room for ``job``, when the
        fair-share ledger justifies it: the claimant tenant is under
        its guaranteed share, the victim's tenant is at-or-over its
        own, and the victim has run at least ``min_run_s`` (the
        thrash guard that turns contention into time slices)."""
        account = self._accounts[job.spec.tenant]
        accounts = self._accounts.values()
        share = guaranteed_share(self.pool.size, account, accounts,
                                 now, self.activity_window_s)
        if account.outstanding + job.spec.world_min > share:
            return False            # not owed anything — wait, don't kill
        victims = []
        for other in self._jobs.values():
            if other.state != RUNNING or not other.spec.preemptible:
                continue
            if other.spec.tenant == job.spec.tenant:
                continue
            if now - other.history[-1][0] < self.min_run_s:
                continue
            v_account = self._accounts[other.spec.tenant]
            v_share = guaranteed_share(self.pool.size, v_account,
                                       accounts, now,
                                       self.activity_window_s)
            if v_account.outstanding < v_share:
                continue            # that tenant is within its guarantee
            victims.append((v_account.outstanding - v_share,
                            other.history[-1][0], other))
        if not victims:
            return False
        # most over-share tenant first; within it, the most recently
        # (re)started gang loses the least completed work
        victims.sort(key=lambda v: (-v[0], -v[1]))
        victim = victims[0][2]
        self.info("preempting %s (tenant %s) for %s (tenant %s) — "
                  "checkpoint + shrink", victim.id, victim.spec.tenant,
                  job.id, job.spec.tenant)
        self._kill_gang(victim)
        self._release_locked(victim, now)
        victim.transition(PREEMPTED, now)
        return True

    # -- gang lifecycle ----------------------------------------------------

    def _spawn_locked(self, job, slots, now):
        world = len(slots)
        self._grant_seq += 1
        job.grants += 1
        coord = None
        if world > 1:
            coord = "127.0.0.1:%d" % _free_port()
        argv = job.spec.build_argv(python=self.python)
        procs = []
        for rank in range(world):
            env = dict(os.environ)
            env.update(job.spec.env)
            env[ENV_GEN] = str(self._grant_seq)
            env[ENV_WORLD] = str(world)
            env[ENV_RANK] = str(rank)
            if coord:
                env[ENV_COORD] = coord
            else:
                env.pop(ENV_COORD, None)
            if job.spec.snapshot_dir:
                env[ENV_SNAPSHOTS] = job.spec.snapshot_dir
            stdout = stderr = None
            logf = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                logf = open(os.path.join(
                    self.log_dir, "%s-g%d-r%d.log"
                    % (job.id, job.grants, rank)), "ab")
                stdout = stderr = logf
            try:
                procs.append(subprocess.Popen(
                    argv, env=env, stdout=stdout, stderr=stderr,
                    start_new_session=True))
            finally:
                if logf is not None:
                    logf.close()   # the child keeps its own dup
        job.slots = slots
        job.granted_world = world
        job.procs = procs
        job.transition(RUNNING, now)
        self.info("%s: granted slots %s (world=%d, grant #%d)",
                  job.id, list(slots), world, job.grants)

    def _kill_gang(self, job):
        """The ElasticSupervisor kill: SIGKILL each member's process
        group (workers run in their own sessions) — per-epoch sharded
        checkpoints make this checkpoint + shrink, not data loss."""
        for proc in job.procs:
            if proc.poll() is not None:
                continue
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    proc.kill()
                except OSError:
                    pass
        for proc in job.procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    def _release_locked(self, job, now):
        if not job.granted_world:
            return
        account = self._accounts[job.spec.tenant]
        account.outstanding = max(
            0, account.outstanding - job.granted_world)
        account.completions.append(now)
        account.last_active = now
        self.pool.release(job.id)
        job.slots = ()
        job.granted_world = 0
        job.procs = []

    # -- telemetry ---------------------------------------------------------

    def _publish_locked(self, now):
        counts = dict.fromkeys(STATES, 0)
        oldest = 0.0
        waits = {}
        for job in self._jobs.values():
            counts[job.state] += 1
            if job.runnable:
                wait = now - job.runnable_since
                oldest = max(oldest, wait)
                tenant = job.spec.tenant
                waits[tenant] = max(waits.get(tenant, 0.0), wait)
        for state, n in counts.items():
            self._metrics["jobs"].labels(state=state).set(n)
        self._metrics["devices"].labels(state="free").set(
            self.pool.free)
        self._metrics["devices"].labels(state="held").set(
            self.pool.held)
        self._metrics["oldest_wait"].set(oldest)
        for tenant in self._accounts:
            self._metrics["tenant_wait"].labels(tenant=tenant).set(
                waits.get(tenant, 0.0))

    def stats(self, now=None):
        now = time.time() if now is None else now
        with self._lock:
            counts = dict.fromkeys(STATES, 0)
            for job in self._jobs.values():
                counts[job.state] += 1
            return {
                "pool": {"size": self.pool.size,
                         "free": self.pool.free,
                         "held": self.pool.held},
                "jobs": counts,
                "tenants": {
                    a.name: {
                        "weight": a.weight, "qos": a.qos,
                        "held": a.outstanding,
                        "granted": a.admitted_total,
                        "share": round(guaranteed_share(
                            self.pool.size, a, self._accounts.values(),
                            now, self.activity_window_s), 1),
                    } for a in self._accounts.values()},
            }

    def jobs_report(self):
        """The ``/jobs.json`` body (also what a dashboard push
        embeds as its ``jobs`` list)."""
        with self._lock:
            return {"jobs": [job.to_dict()
                             for job in self._jobs.values()]}

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Run the tick loop on a daemon thread."""
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="sched-tick")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:
                self.exception("scheduler tick failed")

    def stop(self, kill=True):
        """Stop the loop; ``kill`` takes down every running gang (a
        drain would wait for them — the caller owns that choice)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if kill:
            with self._lock:
                for job in self._jobs.values():
                    if job.state == RUNNING:
                        self._kill_gang(job)
                        self._release_locked(job, time.time())
                        job.error = "scheduler stopped"
                        job.transition(FAILED)


class _ControlHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        self.server.owner.debug("http: " + fmt, *args)

    def _reply(self, body, code=200):
        data = json.dumps(body).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        scheduler = self.server.owner.scheduler
        if self.path.startswith("/status"):
            self._reply(scheduler.stats())
        elif self.path.startswith("/jobs.json"):
            self._reply(scheduler.jobs_report())
        else:
            self._reply({"error": "not found"}, code=404)

    def do_POST(self):
        if not self.path.startswith("/submit"):
            self._reply({"error": "not found"}, code=404)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            data = json.loads(self.rfile.read(length).decode("utf-8"))
            from veles_tpu.sched.job import JobSpec
            job = self.server.owner.scheduler.submit(
                JobSpec.from_dict(data))
        except (TypeError, ValueError, KeyError) as e:
            self._reply({"error": str(e) or type(e).__name__},
                        code=400)
            return
        self._reply({"id": job.id, "state": job.state})


class SchedulerControl(Logger):
    """Loopback HTTP control plane for one scheduler: ``POST
    /submit``, ``GET /status``, ``GET /jobs.json``. Binds loopback by
    default — the submit surface executes commands, so exposing it
    beyond the host is an operator's explicit choice."""

    def __init__(self, scheduler, host="127.0.0.1", port=0):
        super(SchedulerControl, self).__init__()
        self.scheduler = scheduler
        self._server = ThreadingHTTPServer((host, port),
                                           _ControlHandler)
        self._server.owner = self
        self._server.daemon_threads = True
        self.address = self._server.server_address
        self._thread = None

    @property
    def port(self):
        return self.address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="sched-control")
        self._thread.start()
        self.info("scheduler control on %s:%d", *self.address)
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
