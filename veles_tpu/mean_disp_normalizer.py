"""MeanDispNormalizer unit (re-designs ``veles/mean_disp_normalizer.py``).

On-device ``output = (input - mean) * rdisp`` with per-feature mean and
reciprocal dispersion, the reference's kernel pair
``ocl|cuda/mean_disp_normalizer.*`` mapped onto one fused VPU pass
(:func:`veles_tpu.ops.normalize.mean_disp_normalize`).
"""

import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.ops.normalize import mean_disp_normalize


class MeanDispNormalizer(AcceleratedUnit):
    """Demands input/mean/rdisp; produces normalized float32 output."""

    def __init__(self, workflow, **kwargs):
        super(MeanDispNormalizer, self).__init__(workflow, **kwargs)
        self.input = None
        self.mean = None
        self.rdisp = None
        self.output = Array()
        self.demand("input", "mean", "rdisp")

    def _mem(self, attr):
        value = getattr(self, attr)
        return value.mem if isinstance(value, Array) else value

    def _dev(self, attr):
        value = getattr(self, attr)
        if isinstance(value, Array):
            value.unmap()
            return value.devmem
        return value

    def initialize(self, device=None, **kwargs):
        super(MeanDispNormalizer, self).initialize(device=device, **kwargs)
        self.output.reset(numpy.zeros(self._mem("input").shape,
                                      numpy.float32))
        self.init_vectors(self.output, *(getattr(self, a) for a in
                                         ("input", "mean", "rdisp")
                                         if isinstance(getattr(self, a),
                                                       Array)))

    def jax_run(self):
        self.output.assign_devmem(mean_disp_normalize(
            self._dev("input"), self._dev("mean"), self._dev("rdisp")))

    def numpy_run(self):
        out = self.output.map_invalidate()
        x = numpy.asarray(self._mem("input"), numpy.float32)
        out[...] = (x - self._mem("mean")) * self._mem("rdisp")
