"""Web status dashboard (re-designs ``veles/web_status.py:66-265``).

One process serves a fleet of masters: every running Launcher with
``--web-status`` POSTs periodic status JSON to ``/update`` (see
``Launcher._start_status_notifier``); browsers/tools POST service
queries to ``/service``; humans read ``/status.html`` (auto-refreshing
table of live workflows) and ``/logs.html`` (event timeline).

The reference kept logs/events in MongoDB (motor) and purged old
sessions periodically; pymongo is not in this environment, so the
store is in-memory bounded deques with the same query surface — the
``/service`` protocol (``{"request": "workflows"|"logs"|"events",
...}``) and the garbage-collection of silent masters
(``GARBAGE_TIMEOUT``) are preserved. Log duplication to the dashboard
(the reference's Mongo log handler, ``veles/logger.py:292``) is
provided by :class:`WebStatusLogHandler`, which POSTs record batches
to ``/logs``.
"""

import argparse
import collections
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.telemetry import alerts, federation, profiler
from veles_tpu.telemetry.registry import get_registry
from veles_tpu.telemetry.timeseries import get_history

GARBAGE_TIMEOUT = 60

_STATUS_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu status</title><style>
body { font-family: sans-serif; margin: 2em; background: #fafafa; }
table { border-collapse: collapse; min-width: 60em; }
th, td { border: 1px solid #ccc; padding: 0.4em 0.8em; text-align: left; }
th { background: #eee; }
.dead { color: #999; }
</style></head><body>
<h1>veles_tpu workflows</h1>
<p><a href="/workflow.html">graph view</a> ·
<a href="/timeline.html">event timeline</a> ·
<a href="/slaves.html">slave stats</a> ·
<a href="/logs.html">logs</a> ·
<a href="/frontend.html">command composer</a> ·
<a href="/metrics">metrics</a> ·
<a href="/profile.json">profile</a> ·
<a href="/cluster.json">cluster</a> ·
<a href="/alerts.json">alerts</a> ·
<a href="/jobs.json">jobs</a></p>
<div id="perf" style="margin-bottom:1em"></div>
<table id="wf"><thead><tr>
<th>id</th><th>name</th><th>mode</th><th>master</th><th>uptime</th>
<th>slaves</th><th>units</th><th>serving</th><th>perf</th>
<th>stopped</th>
</tr></thead><tbody></tbody></table>
<h2 id="jobs-h" style="display:none">scheduled jobs</h2>
<table id="jobs" style="display:none"><thead><tr>
<th>id</th><th>name</th><th>tenant</th><th>qos</th><th>state</th>
<th>world</th><th>preempts</th><th>resume s</th>
<th>loss</th><th>MFU</th><th>error</th>
</tr></thead><tbody></tbody></table>
<script>
function servingCell(s) {
  if (!s) return "";
  const model = s.model && s.model.name
    ? s.model.name + " v" + s.model.version + " · " : "";
  return model + (s.qps || 0) + " qps · q" + (s.queue_depth || 0) +
    " · p95 " + (s.p95_ms || 0) + "ms" +
    (s.rejected_total ? " · " + s.rejected_total + " shed" : "");
}
function perfCell(p) {
  if (!p) return "";
  let parts = [];
  if (p.mfu) parts.push("MFU " + (p.mfu * 100).toFixed(1) + "%");
  if (p.flight_record) parts.push("flight: " + p.flight_record);
  return parts.join(" · ");
}
function fmtGB(b) { return (b / 1073741824).toFixed(2) + " GB"; }
function renderPerf(p) {
  const div = document.getElementById("perf");
  let html = "";
  if (p.step_mfu)
    html += "<b>step MFU " + (p.step_mfu * 100).toFixed(1) + "%</b>";
  const mem = p.memory || {};
  const devs = Object.entries(mem.devices || {});
  if (devs.length) {
    html += "<table><thead><tr><th>device</th><th>HBM live</th>" +
      "<th>HBM peak</th><th>limit</th></tr></thead><tbody>";
    for (const [d, m] of devs)
      html += "<tr><td>" + d + "</td><td>" + fmtGB(m.live_bytes || 0) +
        "</td><td>" + fmtGB(m.peak_bytes || 0) + "</td><td>" +
        fmtGB(m.limit_bytes || 0) + "</td></tr>";
    html += "</tbody></table>";
  }
  // per-op roofline rows from the cost book — offload:h2d/g* and
  // offload:d2h/g* rows surface out-of-core transfer traffic here
  const ops = (p.ops || []).filter(o => o.p50_ms != null)
    .sort((a, b) => (b.p50_ms || 0) - (a.p50_ms || 0)).slice(0, 12);
  if (ops.length) {
    html += "<table style='margin-top:0.5em'><thead><tr><th>op</th>" +
      "<th>p50 ms</th><th>MB</th><th>GB/s</th><th>bound</th>" +
      "</tr></thead><tbody>";
    for (const o of ops)
      html += "<tr><td>" + o.op + "</td><td>" +
        (o.p50_ms || 0).toFixed(2) + "</td><td>" +
        (o.bytes != null ? (o.bytes / 1e6).toFixed(2) : "") +
        "</td><td>" +
        (o.achieved_gbps != null ? o.achieved_gbps.toFixed(1) : "") +
        "</td><td>" + (o.bound || "") + "</td></tr>";
    html += "</tbody></table>";
  }
  const phases = Object.entries(p.phases_ms || {});
  if (phases.length) {
    // startup-phase bar: one stacked strip, widths proportional
    const total = phases.reduce((a, kv) => a + kv[1], 0);
    const hues = [210, 30, 120, 275, 0, 55];
    html += "<div style='margin-top:0.5em'>startup phases (" +
      (total / 1000).toFixed(1) + "s): </div>" +
      "<div style='display:flex;width:40em;height:1.4em;" +
      "border:1px solid #ccc'>";
    phases.forEach(([name, ms], i) => {
      const w = Math.max(100.0 * ms / Math.max(total, 1e-9), 0.5);
      html += "<div title='" + name + ": " + ms.toFixed(0) +
        "ms' style='width:" + w + "%;background:hsl(" +
        hues[i % hues.length] + ",55%,70%)'></div>";
    });
    html += "</div><div style='font-size:0.85em;color:#555'>" +
      phases.map(([n, ms]) => n + " " + ms.toFixed(0) + "ms")
        .join(" · ") + "</div>";
  }
  if (p.flight_record)
    html += "<div style='margin-top:0.5em'>last flight record: " +
      "<code>" + p.flight_record + "</code></div>";
  div.innerHTML = html;
}
async function refreshPerf() {
  try {
    const resp = await fetch("/profile.json");
    renderPerf(await resp.json());
  } catch (e) {}
}
async function refresh() {
  const resp = await fetch("/service", {method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify({request: "workflows",
      args: ["name", "mode", "master", "time", "slaves", "units",
             "serving", "perf", "stopped"]})});
  const data = await resp.json();
  const tbody = document.querySelector("#wf tbody");
  tbody.innerHTML = "";
  for (const [mid, wf] of Object.entries(data.result || {})) {
    const tr = document.createElement("tr");
    const slaves = wf.slaves ? Object.keys(wf.slaves).length : 0;
    for (const v of [mid.slice(0, 8), wf.name, wf.mode, wf.master,
                     Math.round(wf.time) + "s", slaves, wf.units,
                     servingCell(wf.serving), perfCell(wf.perf),
                     wf.stopped]) {
      const td = document.createElement("td");
      td.textContent = v === undefined ? "" : String(v);
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
}
const HIST = {};   // "family|job" -> [[t, v], ...]
function sparkline(points, width, height) {
  if (!points || points.length < 2) return "";
  const ts = points.map(p => p[0]), vs = points.map(p => p[1]);
  const t0 = Math.min(...ts), t1 = Math.max(...ts);
  const v0 = Math.min(...vs), v1 = Math.max(...vs);
  const sx = t => (t1 > t0 ? (t - t0) / (t1 - t0) : 0) *
    (width - 2) + 1;
  const sy = v => height - 1 -
    (v1 > v0 ? (v - v0) / (v1 - v0) : 0.5) * (height - 2);
  // lift the pen across a gap over 5x the median spacing — a
  // preemption window stays VISIBLE instead of being bridged
  const gaps = [];
  for (let i = 1; i < ts.length; i++) gaps.push(ts[i] - ts[i - 1]);
  gaps.sort((a, b) => a - b);
  const lift = gaps.length
    ? gaps[Math.floor(gaps.length / 2)] * 5 : 1e9;
  let d = "", pen = false;
  for (let i = 0; i < points.length; i++) {
    if (i && ts[i] - ts[i - 1] > lift) pen = false;
    d += (pen ? "L" : "M") + sx(ts[i]).toFixed(1) + " " +
      sy(vs[i]).toFixed(1) + " ";
    pen = true;
  }
  return "<svg width='" + width + "' height='" + height +
    "'><path d='" + d + "' fill='none' stroke='#36c'/></svg>";
}
async function refreshHist() {
  try {
    const resp = await fetch("/history.json?series=veles_sched_job_");
    const data = await resp.json();
    for (const s of data.series || [])
      HIST[s.name + "|" + (s.labels.job || "")] = s.points;
  } catch (e) {}
}
function liveCell(family, jobId) {
  const pts = HIST[family + "|" + jobId];
  const last = pts && pts.length ? pts[pts.length - 1][1] : null;
  return sparkline(pts, 90, 18) +
    (last == null ? "" : " " + (+last).toFixed(3));
}
async function refreshJobs() {
  try {
    await refreshHist();
    const resp = await fetch("/jobs.json");
    const jobs = (await resp.json()).jobs || [];
    const show = jobs.length ? "" : "none";
    document.getElementById("jobs-h").style.display = show;
    document.getElementById("jobs").style.display = show;
    const tbody = document.querySelector("#jobs tbody");
    tbody.innerHTML = "";
    for (const j of jobs) {
      const tr = document.createElement("tr");
      if (j.state === "done" || j.state === "failed")
        tr.className = "dead";
      for (const v of [j.id, j.name, j.tenant, j.qos, j.state,
                       j.world, j.preemptions,
                       j.preempt_resume_s == null ? ""
                         : j.preempt_resume_s.toFixed(2)]) {
        const td = document.createElement("td");
        td.textContent = v === undefined || v === null ? "" : String(v);
        tr.appendChild(td);
      }
      for (const family of ["veles_sched_job_loss",
                            "veles_sched_job_mfu"]) {
        const td = document.createElement("td");
        td.innerHTML = liveCell(family, j.id);
        tr.appendChild(td);
      }
      const td = document.createElement("td");
      td.textContent = j.error == null ? "" : String(j.error);
      tr.appendChild(td);
      tbody.appendChild(tr);
    }
  } catch (e) {}
}
refresh(); setInterval(refresh, 2000);
refreshPerf(); setInterval(refreshPerf, 5000);
refreshJobs(); setInterval(refreshJobs, 2000);
</script></body></html>"""

_SLAVES_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu slave stats</title><style>
body { font-family: sans-serif; margin: 2em; background: #fafafa; }
table { border-collapse: collapse; min-width: 60em; }
th, td { border: 1px solid #ccc; padding: 0.4em 0.8em; text-align: left; }
th { background: #eee; }
.stale { color: #b00; }
</style></head><body>
<h1>slave stats</h1>
<p><a href="/status.html">&larr; workflows</a></p>
<table id="sl"><thead><tr>
<th>master</th><th>slave</th><th>state</th><th>power</th>
<th>jobs done</th><th>in flight</th><th>last seen (s)</th>
</tr></thead><tbody></tbody></table>
<script>
async function refresh() {
  const resp = await fetch("/service", {method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify({request: "workflows",
      args: ["name", "slaves"]})});
  const data = await resp.json();
  const tbody = document.querySelector("#sl tbody");
  tbody.innerHTML = "";
  for (const [mid, wf] of Object.entries(data.result || {})) {
    for (const [sid, s] of Object.entries(wf.slaves || {})) {
      const tr = document.createElement("tr");
      if ((s.age || 0) > 10) tr.className = "stale";
      for (const v of [wf.name || mid.slice(0, 8), sid, s.state,
                       s.power, s.jobs_done, s.in_flight, s.age]) {
        const td = document.createElement("td");
        td.textContent = v === undefined ? "" : String(v);
        tr.appendChild(td);
      }
      tbody.appendChild(tr);
    }
  }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""

_LOGS_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu logs</title><style>
body { font-family: monospace; margin: 2em; background: #fafafa; }
table { border-collapse: collapse; width: 100%; }
th, td { border: 1px solid #ccc; padding: 0.2em 0.6em; text-align: left; }
th { background: #eee; }
.ERROR, .CRITICAL { color: #b00; } .WARNING { color: #b70; }
</style></head><body>
<h1>veles_tpu logs &amp; events</h1>
<table id="logs"><thead><tr>
<th>time</th><th>session</th><th>level</th><th>node</th><th>message</th>
</tr></thead><tbody></tbody></table>
<script>
async function refresh() {
  const resp = await fetch("/service", {method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify({request: "logs", find: {}})});
  const data = await resp.json();
  const tbody = document.querySelector("#logs tbody");
  tbody.innerHTML = "";
  for (const rec of (data.result || []).slice(-500).reverse()) {
    const tr = document.createElement("tr");
    tr.className = rec.levelname || "";
    for (const v of [new Date((rec.created || 0) * 1000).toISOString(),
                     (rec.session || "").slice(0, 8), rec.levelname,
                     rec.node, rec.message]) {
      const td = document.createElement("td");
      td.textContent = v === undefined ? "" : String(v);
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


_FRONTEND_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu frontend</title><style>
body { font-family: sans-serif; margin: 2em; background: #fafafa;
       max-width: 70em; }
code, #cmd { font-family: monospace; background: #eee; padding: 0.5em;
             display: block; margin: 1em 0; white-space: pre-wrap; }
.arg { margin: 0.25em 0; } .arg input { margin-left: 0.5em; }
h2 { margin-top: 1.5em; } .doc { color: #666; font-size: 0.9em; }
table { border-collapse: collapse; } td, th { border: 1px solid #ccc;
padding: 0.3em 0.6em; text-align: left; }
</style></head><body>
<h1>veles_tpu command composer</h1>
<p>Build a <code style="display:inline">python -m veles_tpu</code>
command line from the registered options; the unit catalog below is
what <code style="display:inline">generate_frontend</code> exports.</p>
<div class="arg">workflow file: <input id="wf" size="40"
     value="workflow.py"></div>
<div class="arg">config file: <input id="cfg" size="40"></div>
<div id="args"></div>
<code id="cmd"></code>
<h2>Registered units</h2>
<table id="units"><thead><tr><th>unit</th><th>module</th><th>group</th>
<th>doc</th></tr></thead><tbody></tbody></table>
<script>
let catalog = {arguments: [], units: {}};
function rebuild() {
  let cmd = "python -m veles_tpu";
  for (const arg of catalog.arguments) {
    if (arg.kind === "positional") continue;  // wf/cfg inputs cover these
    const el = document.getElementById("arg-" + arg.dest);
    if (!el) continue;
    if (arg.kind === "flag") {
      if (el.checked) cmd += " " + arg.flags[0];
      continue;
    }
    if (!el.value || el.value === String(arg.default)) continue;
    cmd += " " + arg.flags[0] + " " + el.value;
  }
  cmd += " " + document.getElementById("wf").value;
  const cfg = document.getElementById("cfg").value;
  if (cfg) cmd += " " + cfg;
  document.getElementById("cmd").textContent = cmd;
}
async function load() {
  const resp = await fetch("/catalog");
  catalog = await resp.json();
  const argsDiv = document.getElementById("args");
  for (const arg of catalog.arguments) {
    if (arg.kind === "positional") continue;
    const div = document.createElement("div");
    div.className = "arg";
    const label = document.createElement("label");
    label.textContent = arg.flags.join(", ");
    label.title = arg.help;
    let input;
    if (arg.kind === "flag") {
      input = document.createElement("input");
      input.type = "checkbox";
    } else if (arg.choices && arg.choices.length) {
      input = document.createElement("select");
      const blank = document.createElement("option");
      blank.value = "";
      blank.textContent = "(default)";
      input.appendChild(blank);
      for (const c of arg.choices) {
        const opt = document.createElement("option");
        opt.value = c;
        opt.textContent = c;
        input.appendChild(opt);
      }
    } else {
      input = document.createElement("input");
      input.placeholder = arg.default === null ? "" : String(arg.default);
    }
    input.id = "arg-" + arg.dest;
    input.addEventListener("input", rebuild);
    input.addEventListener("change", rebuild);
    div.appendChild(label); div.appendChild(input);
    if (arg.help) {
      const doc = document.createElement("span");
      doc.className = "doc";
      doc.textContent = " — " + arg.help;
      div.appendChild(doc);
    }
    argsDiv.appendChild(div);
  }
  const tbody = document.querySelector("#units tbody");
  for (const [name, unit] of Object.entries(catalog.units)) {
    const tr = document.createElement("tr");
    for (const v of [name, unit.module, unit.view_group, unit.doc]) {
      const td = document.createElement("td");
      td.textContent = v || "";
      tr.appendChild(td);
    }
    tbody.appendChild(tr);
  }
  document.getElementById("wf").addEventListener("input", rebuild);
  document.getElementById("cfg").addEventListener("input", rebuild);
  rebuild();
}
load();
</script></body></html>"""


#: shared HTML-escaping helper for every inline page that builds markup
#: via innerHTML from unauthenticated POST data (one definition so a
#: future hardening fix cannot miss a copy)
_ESC_JS = """function esc(s) {
  return String(s).replace(/[&<>"']/g, c => ({"&": "&amp;",
    "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;"}[c]));
}"""


_WORKFLOW_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu workflow graph</title><style>
body { font-family: sans-serif; margin: 2em; background: #fafafa; }
svg { background: #fff; border: 1px solid #ccc; }
text { font-size: 11px; font-family: sans-serif; }
.node rect { fill: #e8eef7; stroke: #5b7db1; rx: 4; }
.node.PLUMBING rect { fill: #f4f4f4; stroke: #999; }
.node.SERVICE rect, .node.PLOTTER rect { fill: #f1e8f7; stroke: #8b5bb1; }
.node.TRAINER rect { fill: #e8f7ec; stroke: #4d9a63; }
.edge { stroke: #888; fill: none; marker-end: url(#arrow); }
select { margin-bottom: 1em; }
</style></head><body>
<h1>workflow graph</h1>
<select id="master"></select>
<div id="view"></div>
<script>
// layered layout (Sugiyama-lite): BFS ranks from the roots, then
// order-within-rank by mean parent position — the role the
// reference's viz.js svg_view.js played, without the 2MB dependency
function layout(nodes, edges) {
  const succ = new Map(nodes.map(n => [n.id, []]));
  const indeg = new Map(nodes.map(n => [n.id, 0]));
  for (const [s, d] of edges) {
    succ.get(s).push(d);
    indeg.set(d, indeg.get(d) + 1);
  }
  const rank = new Map();
  let frontier = nodes.filter(n => indeg.get(n.id) === 0).map(n => n.id);
  if (!frontier.length && nodes.length) frontier = [nodes[0].id];
  let depth = 0;
  const seen = new Set(frontier);
  while (frontier.length) {
    for (const id of frontier) rank.set(id, depth);
    const next = [];
    for (const id of frontier)
      for (const d of succ.get(id) || [])
        if (!seen.has(d)) { seen.add(d); next.push(d); }
    frontier = next; depth++;
  }
  for (const n of nodes) if (!rank.has(n.id)) rank.set(n.id, depth);
  const layers = [];
  for (const n of nodes) {
    const r = rank.get(n.id);
    (layers[r] = layers[r] || []).push(n);
  }
  const pos = new Map();
  layers.forEach((layer, r) => {
    layer.forEach((n, i) => pos.set(n.id,
      {x: 40 + i * 170 + (r % 2) * 40, y: 40 + r * 80}));
  });
  return pos;
}
//__ESC__
function render(graph) {
  const pos = layout(graph.nodes, graph.edges);
  const w = Math.max(...[...pos.values()].map(p => p.x)) + 200;
  const h = Math.max(...[...pos.values()].map(p => p.y)) + 80;
  let svg = `<svg width="${w}" height="${h}">
    <defs><marker id="arrow" viewBox="0 0 10 10" refX="9" refY="5"
      markerWidth="7" markerHeight="7" orient="auto-start-reverse">
      <path d="M 0 0 L 10 5 L 0 10 z" fill="#888"/></marker></defs>`;
  for (const [s, d] of graph.edges) {
    const a = pos.get(s), b = pos.get(d);
    if (!a || !b) continue;
    const my = (a.y + b.y) / 2;
    svg += `<path class="edge" d="M ${a.x + 65} ${a.y + 36}
      C ${a.x + 65} ${my}, ${b.x + 65} ${my}, ${b.x + 65} ${b.y}"/>`;
  }
  for (const n of graph.nodes) {
    const p = pos.get(n.id);
    svg += `<g class="node ${esc(n.group || "")}"
      transform="translate(${p.x},${p.y})">
      <rect width="130" height="36"/>
      <text x="65" y="15" text-anchor="middle">${esc(n.type)}</text>
      <text x="65" y="29" text-anchor="middle"
        fill="#555">${esc(n.name)}</text>
      </g>`;
  }
  document.getElementById("view").innerHTML = svg + "</svg>";
}
async function refresh() {
  const resp = await fetch("/service", {method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify({request: "workflows",
                          args: ["name", "graph"]})});
  const data = await resp.json();
  const sel = document.getElementById("master");
  const current = sel.value;
  sel.innerHTML = "";
  for (const [mid, wf] of Object.entries(data.result || {})) {
    if (!wf.graph) continue;
    const opt = document.createElement("option");
    opt.value = mid;
    opt.textContent = mid.slice(0, 8) + "  " + (wf.name || "");
    sel.appendChild(opt);
  }
  if (current) sel.value = current;
  const pick = (data.result || {})[sel.value];
  if (pick && pick.graph) render(pick.graph);
}
document.getElementById("master").addEventListener("change", refresh);
refresh(); setInterval(refresh, 5000);
</script></body></html>"""

_TIMELINE_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu timeline</title><style>
body { font-family: sans-serif; margin: 2em; background: #fafafa; }
svg { background: #fff; border: 1px solid #ccc; }
text { font-size: 10px; font-family: monospace; }
rect.bar { fill: #5b7db1; opacity: 0.8; }
rect.bar:hover { opacity: 1; }
line.single { stroke: #b14d4d; stroke-width: 2; }
</style></head><body>
<h1>event timeline</h1>
<p>begin/end trace records per instance (the role of the reference's
Rickshaw logs view); newest 60s window, refreshed live.</p>
<div id="view"></div>
<script>
//__ESC__
async function refresh() {
  const resp = await fetch("/service", {method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify({request: "events", find: {}})});
  const data = await resp.json();
  const events = data.result || [];
  if (!events.length) {
    document.getElementById("view").textContent = "no events yet";
    return;
  }
  const tmax = Math.max(...events.map(e => e.time || 0));
  const tmin = Math.max(Math.min(...events.map(e => e.time || 0)),
                        tmax - 60);
  const lanes = new Map();   // instance -> lane index
  const open = new Map();    // instance:name -> begin time
  const bars = [], singles = [];
  for (const ev of events) {
    if (ev.time < tmin - 60) continue;
    if (!lanes.has(ev.instance)) lanes.set(ev.instance, lanes.size);
    const key = ev.instance + ":" + ev.name;
    if (ev.type === "begin") open.set(key, ev.time);
    else if (ev.type === "end" && open.has(key)) {
      bars.push({lane: lanes.get(ev.instance), name: ev.name,
                 t0: open.get(key), t1: ev.time});
      open.delete(key);
    } else if (ev.type === "single")
      singles.push({lane: lanes.get(ev.instance), name: ev.name,
                    t: ev.time});
  }
  const W = 1100, laneH = 22, left = 240;
  const H = lanes.size * laneH + 40;
  const x = t => left + (W - left - 20) *
    (t - tmin) / Math.max(tmax - tmin, 1e-3);
  let svg = `<svg width="${W}" height="${H}">`;
  for (const [inst, lane] of lanes) {
    svg += `<text x="4" y="${30 + lane * laneH + 12}">` +
      esc(inst.split("@")[0].slice(0, 30)) + `</text>`;
    svg += `<line x1="${left}" y1="${30 + lane * laneH + laneH - 2}"
      x2="${W - 10}" y2="${30 + lane * laneH + laneH - 2}"
      stroke="#eee"/>`;
  }
  for (const b of bars) {
    if (b.t1 < tmin) continue;
    const x0 = x(Math.max(b.t0, tmin));
    svg += `<rect class="bar" x="${x0}" y="${30 + b.lane * laneH + 2}"
      width="${Math.max(x(b.t1) - x0, 1.5)}" height="${laneH - 6}">
      <title>${esc(b.name)}: ${((b.t1 - b.t0) * 1000).toFixed(1)}ms</title>
      </rect>`;
  }
  for (const s of singles) {
    if (s.t < tmin) continue;
    svg += `<line class="single" x1="${x(s.t)}" x2="${x(s.t)}"
      y1="${30 + s.lane * laneH + 2}" y2="${30 + s.lane * laneH + laneH - 4}">
      <title>${esc(s.name)}</title></line>`;
  }
  svg += `<text x="${left}" y="16">${new Date(tmin * 1000)
    .toISOString()}</text>
    <text x="${W - 200}" y="16">${new Date(tmax * 1000)
    .toISOString()}</text>`;
  document.getElementById("view").innerHTML = svg + "</svg>";
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""

_WORKFLOW_PAGE = _WORKFLOW_PAGE.replace("//__ESC__", _ESC_JS)
_TIMELINE_PAGE = _TIMELINE_PAGE.replace("//__ESC__", _ESC_JS)


def _match(record, query):
    """MongoDB-lite ``find``: top-level equality (+ $in / $gte / $lte)."""
    for key, cond in query.items():
        value = record.get(key)
        if isinstance(cond, dict):
            if "$in" in cond and value not in cond["$in"]:
                return False
            if "$gte" in cond and not (value is not None
                                       and value >= cond["$gte"]):
                return False
            if "$lte" in cond and not (value is not None
                                       and value <= cond["$lte"]):
                return False
        elif value != cond:
            return False
    return True


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        self.server.owner.debug("http: " + fmt, *args)

    def _reply(self, body, code=200, ctype="application/json"):
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode("utf-8")
        elif isinstance(body, str):
            body = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        try:
            length = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            length = 0
        raw = self.rfile.read(length)
        try:
            return json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None

    def do_GET(self):
        self.server.owner.count_request(self.path)
        if self.path in ("", "/", "/status.html"):
            self._reply(_STATUS_PAGE, ctype="text/html; charset=utf-8")
        elif self.path.startswith("/profile.json"):
            self._reply(profiler.profile_report())
        elif self.path.startswith("/cluster.json"):
            self._reply(self.server.owner.cluster_report())
        elif self.path.startswith("/alerts.json"):
            self._reply(alerts.get_engine().report())
        elif self.path.startswith("/jobs.json"):
            self._reply(self.server.owner.jobs_report())
        elif self.path.startswith("/history.json"):
            query = parse_qs(urlsplit(self.path).query)
            try:
                self._reply(get_history().query(
                    series=(query.get("series") or [None])[0],
                    since=(query.get("since") or [None])[0]))
            except (TypeError, ValueError):
                self._reply({"error": "bad since cursor"}, code=400)
        elif self.path.startswith("/metrics.json"):
            # cluster-wide: local registry + federated slave series
            self._reply(federation.cluster_snapshot())
        elif self.path.startswith("/metrics"):
            self._reply(federation.render_cluster_prometheus(),
                        ctype="text/plain; version=0.0.4")
        elif self.path.startswith("/logs.html"):
            self._reply(_LOGS_PAGE, ctype="text/html; charset=utf-8")
        elif self.path.startswith("/slaves.html"):
            self._reply(_SLAVES_PAGE, ctype="text/html; charset=utf-8")
        elif self.path.startswith("/frontend.html"):
            self._reply(_FRONTEND_PAGE, ctype="text/html; charset=utf-8")
        elif self.path.startswith("/workflow.html"):
            self._reply(_WORKFLOW_PAGE, ctype="text/html; charset=utf-8")
        elif self.path.startswith("/timeline.html"):
            self._reply(_TIMELINE_PAGE, ctype="text/html; charset=utf-8")
        elif self.path.startswith("/catalog"):
            try:
                body = json.dumps(self.server.owner.catalog(),
                                  default=str)
            except Exception as e:
                self._reply({"error": str(e) or type(e).__name__},
                            code=500)
            else:
                self._reply(body.encode("utf-8"))
        else:
            self._reply({"error": "not found"}, code=404)

    def do_POST(self):
        self.server.owner.count_request(self.path)
        data = self._body()
        if data is None:
            self._reply({"error": "bad json"}, code=400)
            return
        server = self.server.owner
        try:
            if self.path == "/update":
                server.receive_update(data)
                self._reply({"result": "ok"})
            elif self.path == "/service":
                self._reply(server.receive_request(data))
            elif self.path == "/logs":
                server.receive_logs(data)
                self._reply({"result": "ok"})
            elif self.path == "/events":
                server.receive_events(data)
                self._reply({"result": "ok"})
            else:
                self._reply({"error": "not found"}, code=404)
        except (KeyError, TypeError, ValueError) as e:
            self._reply({"error": str(e) or type(e).__name__}, code=400)


class WebStatusServer(Logger):
    """The dashboard process (``veles/web_status.py:113``)."""

    def __init__(self, host=None, port=None, max_records=100000):
        super(WebStatusServer, self).__init__()
        self.masters = {}
        self.logs = collections.deque(maxlen=max_records)
        self.events = collections.deque(maxlen=max_records)
        self._lock = threading.Lock()
        self._catalog = None
        self._catalog_lock = threading.Lock()
        self._server = ThreadingHTTPServer(
            (host if host is not None else root.common.web.host,
             port if port is not None else root.common.web.port),
            _Handler)
        self._server.owner = self
        self._server.daemon_threads = True
        self.address = self._server.server_address
        self._thread = None
        # own telemetry: the dashboard process always exposes at least
        # its request counter at /metrics (Prometheus text)
        registry = get_registry()
        self._m_requests = registry.counter(
            "veles_webstatus_http_requests_total",
            "Dashboard HTTP requests", labels=("path",))
        self._m_updates = registry.counter(
            "veles_webstatus_updates_total",
            "Master status updates received")
        self._m_records = registry.counter(
            "veles_webstatus_records_total",
            "Log/event records received", labels=("kind",))
        # the SLO engine evaluates continuously while a dashboard is
        # up, so /alerts.json and veles_alerts_active are live even in
        # a process that has no coordinator ticking them
        alerts.get_engine().start()

    #: the routes the handler actually serves — anything else counts as
    #: "other": a port scanner probing random paths must not mint an
    #: unbounded set of labeled series in a long-lived dashboard
    KNOWN_PATHS = frozenset([
        "/", "/status.html", "/logs.html", "/slaves.html",
        "/frontend.html", "/workflow.html", "/timeline.html", "/catalog",
        "/metrics", "/metrics.json", "/profile.json", "/cluster.json",
        "/alerts.json", "/jobs.json", "/history.json", "/update",
        "/service", "/logs", "/events"])

    def count_request(self, path):
        path = path.split("?")[0] or "/"
        if path not in self.KNOWN_PATHS:
            path = "other"
        self._m_requests.labels(path=path).inc()

    @property
    def port(self):
        return self.address[1]

    def catalog(self):
        """Unit/argument catalog for the composer page (lazy, cached).

        Uses its own lock: generate() imports the whole unit registry
        (seconds), and self._lock also serializes receive_update from
        live masters — the first page load must not stall them."""
        with self._catalog_lock:
            if self._catalog is None:
                from veles_tpu.scripts.generate_frontend import generate
                self._catalog = generate()
            return self._catalog

    # -- receiving ---------------------------------------------------------

    def cluster_report(self):
        """The ``/cluster.json`` body: this process's federated view
        (live when the dashboard is embedded in the master) plus the
        health tables remote masters POSTed with their status."""
        report = federation.cluster_report()
        with self._lock:
            masters = {mid: master.get("cluster")
                       for mid, master in self.masters.items()
                       if master.get("cluster")}
        if masters:
            report["masters"] = masters
        return report

    def jobs_report(self):
        """The ``/jobs.json`` body: every pushed scheduler's job
        table (a ``sched serve --status-url`` push embeds its
        ``jobs`` list in the periodic ``/update`` blob)."""
        jobs = []
        with self._lock:
            for mid, master in self.masters.items():
                for job in master.get("jobs") or ():
                    jobs.append(dict(job, scheduler=mid))
        return {"jobs": jobs}

    #: pushed job-row live metrics fed into the history store (the
    #: scheduler is a DIFFERENT process; its pushes are the only
    #: source this dashboard's sparklines have)
    _JOB_HISTORY = (("loss", "veles_sched_job_loss"),
                    ("samples_per_s", "veles_sched_job_samples_per_s"),
                    ("mfu", "veles_sched_job_mfu"))

    def _record_job_history(self, jobs):
        history = get_history()
        for job in jobs:
            if not isinstance(job, dict):
                continue
            if job.get("state") != "running":
                continue   # a preemption gap must stay visible
            metrics = job.get("metrics") or {}
            job_id = str(job.get("id"))
            tenant = str(job.get("tenant"))
            for key, family in self._JOB_HISTORY:
                value = metrics.get(key)
                if isinstance(value, (int, float)):
                    history.record(family,
                                   {"job": job_id, "tenant": tenant},
                                   value)

    def receive_update(self, data):
        """A master's periodic status (``web_status.py:244-251``)."""
        mid = data["id"]
        with self._lock:
            self.masters[mid] = dict(data, last_update=time.time())
        self._m_updates.inc()
        jobs = data.get("jobs")
        if jobs:
            self._record_job_history(jobs)
        self.debug("master %s yielded an update", mid)

    @staticmethod
    def _validated(records):
        # a single non-dict record would poison every later /service
        # query (_match does record.get), so reject the batch up front
        if isinstance(records, dict):
            raise ValueError("records must be a list of objects")
        records = list(records)
        if not all(isinstance(rec, dict) for rec in records):
            raise ValueError("every record must be a JSON object")
        return records

    def receive_logs(self, data):
        records = data["logs"] if isinstance(data, dict) else data
        records = self._validated(records)
        with self._lock:
            self.logs.extend(records)
        self._m_records.labels(kind="logs").inc(len(records))

    def receive_events(self, data):
        records = data["events"] if isinstance(data, dict) else data
        records = self._validated(records)
        with self._lock:
            self.events.extend(records)
        self._m_records.labels(kind="events").inc(len(records))

    def receive_request(self, data):
        """The ``/service`` protocol (``web_status.py:197-242``)."""
        rtype = data["request"]
        if rtype == "workflows":
            args = data.get("args", [])
            ret, garbage = {}, []
            now = time.time()
            with self._lock:
                for mid, master in self.masters.items():
                    if now - master["last_update"] > GARBAGE_TIMEOUT:
                        garbage.append(mid)
                        continue
                    ret[mid] = {item: master.get(item) for item in args}
                for mid in garbage:
                    self.info("removing the garbage collected master %s", mid)
                    del self.masters[mid]
            return {"request": rtype, "result": ret}
        if rtype in ("logs", "events"):
            query = data.get("find")
            if query is None:
                raise ValueError("only 'find' queries are supported")
            store = self.logs if rtype == "logs" else self.events
            with self._lock:
                result = [rec for rec in store if _match(rec, query)]
            return {"request": rtype, "result": result}
        return {"request": rtype, "result": None}

    # -- lifecycle ---------------------------------------------------------

    def run(self):
        """Serve until :meth:`stop` (blocking, like the reference)."""
        # local registry history (request counters etc.); the job
        # sparklines are fed by _record_job_history instead
        get_history().start()
        self.info("HTTP server is running on %s:%d", *self.address)
        self._server.serve_forever()

    def start(self):
        """Serve on a daemon thread (for embedding/tests)."""
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="web-status")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


class WebStatusEventSink(object):
    """Live event feed to the dashboard timeline: batches trace records
    and POSTs them to ``/events`` (register with
    :func:`veles_tpu.logger.add_event_sink`)."""

    def __init__(self, address=None, session_id=None,
                 flush_interval=1.0):
        if address is None:
            address = (root.common.web.host, root.common.web.port)
        self.url = "http://%s:%d/events" % tuple(address)
        self.session_id = session_id or str(time.time())
        self._buffer = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, args=(flush_interval,), daemon=True,
            name="web-status-events")
        self._flusher.start()

    def write(self, record):
        with self._lock:
            self._buffer.append(record)

    def _flush_once(self):
        import urllib.request
        with self._lock:
            batch, self._buffer = self._buffer, []
        if not batch:
            return
        try:
            req = urllib.request.Request(
                self.url, data=json.dumps({"events": batch},
                                          default=str).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=2.0)
        except Exception:
            with self._lock:  # keep for the next attempt, bounded
                self._buffer = (batch + self._buffer)[-10000:]

    def _flush_loop(self, interval):
        while not self._stop.wait(interval):
            self._flush_once()

    def close(self):
        self._stop.set()
        self._flusher.join(timeout=5)
        self._flush_once()


class WebStatusLogHandler(logging.Handler):
    """Duplicates log records to the dashboard (the reference's
    MongoLogHandler, ``veles/logger.py:292``, minus Mongo)."""

    def __init__(self, address=None, session=None, node=None,
                 flush_interval=1.0):
        super(WebStatusLogHandler, self).__init__()
        if address is None:
            address = (root.common.web.host, root.common.web.port)
        self.url = "http://%s:%d/logs" % tuple(address)
        self.session = session
        self.node = node
        self._buffer = []
        self._lock2 = threading.Lock()
        self._stop = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, args=(flush_interval,), daemon=True,
            name="web-status-logs")
        self._flusher.start()

    def emit(self, record):
        doc = {
            "session": self.session,
            "node": self.node,
            "levelname": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
            "created": record.created,
        }
        with self._lock2:
            self._buffer.append(doc)

    def _flush_once(self):
        import urllib.request
        with self._lock2:
            batch, self._buffer = self._buffer, []
        if not batch:
            return
        try:
            req = urllib.request.Request(
                self.url, data=json.dumps({"logs": batch}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=2.0)
        except Exception:
            with self._lock2:  # keep for the next attempt, bounded
                self._buffer = (batch + self._buffer)[-10000:]

    def _flush_loop(self, interval):
        while not self._stop.wait(interval):
            self._flush_once()

    def close(self):
        self._stop.set()
        self._flusher.join(timeout=5)
        # the last records before shutdown are usually the ones that
        # explain it — flush them instead of dropping the buffer
        self._flush_once()
        super(WebStatusLogHandler, self).close()


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="veles_tpu web status dashboard")
    parser.add_argument("--host", default=None)
    parser.add_argument("--port", type=int, default=None)
    args = parser.parse_args(argv)
    server = WebStatusServer(host=args.host, port=args.port)
    try:
        server.run()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
