"""PDF publishing backend (``veles/publishing/pdf_backend.py``).

The reference shelled out to LaTeX; matplotlib (which IS in this image)
can author multi-page PDFs directly, so the report becomes: a text
summary page rendered with ``figure.text`` + one page per gathered
plot (PNG bytes re-imported). No external toolchain needed.
"""

import io

from veles_tpu.publishing.backend import Backend


class PdfBackend(Backend):
    MAPPING = "pdf"
    image_formats = ("png",)

    def __init__(self, **kwargs):
        super(PdfBackend, self).__init__(**kwargs)
        self.file = kwargs.get("file")
        if not self.file:
            raise ValueError("PdfBackend needs a file=... path")

    def _summary_lines(self, info):
        lines = ["%s - training report" % info.get("name", "?"), ""]
        desc = (info.get("description") or "").strip()
        if desc:
            lines.extend(desc.split("\n") + [""])
        lines.append("run id: %s    python: %s    pid: %s" % (
            info.get("id"), info.get("python"), info.get("pid")))
        lines.append("elapsed: %dd %02d:%02d:%02d" % (
            info.get("days", 0), info.get("hours", 0),
            info.get("mins", 0), info.get("secs", 0)))
        lines.append("")
        results = info.get("results") or {}
        if results:
            lines.append("Results:")
            for key in sorted(results):
                lines.append("  %s: %s" % (key, results[key]))
        if "class_lengths" in info:
            lines.append("")
            lines.append("Data: class lengths %s, %s total samples, "
                         "%s epochs" % (info["class_lengths"],
                                        info.get("total_samples"),
                                        info.get("epochs")))
        stats = info.get("unit_run_times_by_name") or {}
        if stats:
            lines.append("")
            lines.append("Slowest units:")
            top = sorted(stats.items(), key=lambda kv: -kv[1][0])[:8]
            for name, (secs, calls) in top:
                lines.append("  %-30s %8.3f s in %d calls"
                             % (name, secs, calls))
        return lines

    def render(self, info):
        import matplotlib
        matplotlib.use("Agg", force=False)
        import matplotlib.image as mpimg
        import matplotlib.pyplot as plt
        from matplotlib.backends.backend_pdf import PdfPages

        with PdfPages(self.file) as pdf:
            figure = plt.figure(figsize=(8.27, 11.69))  # A4 portrait
            text = "\n".join(self._summary_lines(info))
            figure.text(0.06, 0.97, text, va="top", family="monospace",
                        fontsize=9, wrap=True)
            pdf.savefig(figure)
            plt.close(figure)
            for name in sorted(info.get("plots") or {}):
                png = info["plots"][name].get("png")
                if png is None:
                    continue
                img = mpimg.imread(io.BytesIO(png), format="png")
                figure = plt.figure(figsize=(8.27, 6.2))
                axes = figure.add_subplot(111)
                axes.imshow(img)
                axes.axis("off")
                figure.suptitle(name)
                pdf.savefig(figure)
                plt.close(figure)
        self.info("wrote %s", self.file)
        return self.file
