"""The Publisher unit (``veles/publishing/publisher.py:57-256``)."""

import io
import os
import platform
import time

from veles_tpu.config import root
from veles_tpu.distributable import TriviallyDistributable
from veles_tpu.publishing.backend import PublishingBackendRegistry
from veles_tpu.units import Unit


class Publisher(Unit, TriviallyDistributable):
    """Gathers run info and renders it through configured backends.

    ``backends`` maps registry names to kwargs, e.g.::

        Publisher(wf, backends={
            "markdown": {"file": "report.md"},
            "pdf": {"file": "report.pdf"},
        })

    Typically linked from the decision so it fires once at the end
    (gate it with ``~decision.complete`` like the end point), or left
    unlinked and invoked manually via :meth:`run`.
    """

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("view_group", "SERVICE")
        super(Publisher, self).__init__(workflow, **kwargs)
        self.backends = dict(kwargs.get("backends", {}))
        self.include_plots = kwargs.get("include_plots", True)
        self.loader_unit = kwargs.get("loader_unit")
        self._backend_instances = {}

    def initialize(self, **kwargs):
        for name, backend_kwargs in self.backends.items():
            cls = PublishingBackendRegistry.backends.get(name)
            if cls is None:
                raise ValueError(
                    "unknown publishing backend %r (have %s)" %
                    (name, sorted(PublishingBackendRegistry.backends)))
            self._backend_instances[name] = cls(**(backend_kwargs or {}))
        if self.loader_unit is None:
            self.loader_unit = getattr(self.workflow, "loader", None)

    def run(self):
        if self.is_slave or root.common.disable.get("publishing", False):
            return
        info = self.gather_info()
        self.info("publishing the results through %s",
                  sorted(self._backend_instances) or "no backends")
        for name, backend in self._backend_instances.items():
            self.debug("rendering %s...", name)
            try:
                backend.render(info)
            except Exception as e:
                # a broken template must not lose the other reports (or
                # crash the workflow at the very end of training)
                self.warning("backend %s failed: %s", name, e)

    # -- info gathering ----------------------------------------------------

    def gather_info(self):
        """Everything knowable about the run, in one dict
        (``publisher.py:167-235``)."""
        workflow = self.workflow
        launcher = self.launcher
        info = {
            "name": workflow.name,
            "description": workflow.__doc__,
            "id": getattr(launcher, "id", None),
            "logid": getattr(launcher, "log_id", None),
            "python": "%s %s" % (platform.python_implementation(),
                                 platform.python_version()),
            "pid": os.getpid(),
            "workflow_graph": workflow.generate_graph(),
            "unit_run_times_by_name": self._run_times_by_unit(),
            "unit_run_times_by_class": self._run_times_by_class(),
            "results": workflow.gather_results(),
            "plots": self._gather_plots() if self.include_plots else {},
        }
        sio = io.StringIO()
        root.print_(file=sio)
        info["config_text"] = sio.getvalue()
        start = getattr(launcher, "start_time", None)
        mins, secs = divmod(time.time() - (start or time.time()), 60)
        hours, mins = divmod(mins, 60)
        days, hours = divmod(hours, 24)
        info.update({"days": int(days), "hours": int(hours),
                     "mins": int(mins), "secs": int(secs)})
        loader = self.loader_unit
        if loader is not None:
            info.update({
                "class_lengths": tuple(loader.class_lengths),
                "total_samples": sum(loader.class_lengths),
                "epochs": getattr(loader, "epoch_number", None),
                "normalization": getattr(loader, "normalization_type",
                                         "none"),
                "normalization_parameters": getattr(
                    loader, "normalization_parameters", {}),
            })
            mapping = getattr(loader, "labels_mapping", None)
            if mapping:
                info["labels"] = tuple(mapping)
        return info

    @staticmethod
    def _uniquify(name, seen):
        """Unit names are not unique; reports must not lose rows."""
        if name not in seen:
            seen[name] = 1
            return name
        seen[name] += 1
        return "%s#%d" % (name, seen[name])

    def _run_times_by_unit(self):
        seen, stats = {}, {}
        for unit in self.workflow.units:
            stats[self._uniquify(unit.name, seen)] = (unit.run_time,
                                                      unit.run_calls)
        return stats

    def _run_times_by_class(self):
        stats = {}
        for unit in self.workflow.units:
            key = type(unit).__name__
            secs, calls = stats.get(key, (0.0, 0))
            stats[key] = (secs + unit.run_time, calls + unit.run_calls)
        return stats

    def _image_formats(self):
        """Only render what the configured backends will read."""
        formats = set()
        for backend in self._backend_instances.values():
            formats.update(getattr(backend, "image_formats", ("png",)))
        return sorted(formats) or ["png"]

    def _gather_plots(self):
        """Render every plotter in the workflow (``publisher.py:237-254``)."""
        from veles_tpu.plotter import Plotter
        plots = {}
        try:
            import matplotlib
            matplotlib.use("Agg", force=False)
            from matplotlib.figure import Figure
        except ImportError:  # pragma: no cover - matplotlib is baked in
            self.warning("matplotlib unavailable; skipping plots")
            return plots
        formats = self._image_formats()
        seen = {}
        for unit in self.workflow.units_in_dependency_order:
            if not isinstance(unit, Plotter) or not unit.redraw_plot:
                continue
            figure = Figure()
            try:
                # a plotter that filled during the run already holds its
                # accumulated state — calling fill() again would append
                # a duplicate point (or, with clear_plot, erase the
                # curve). Only never-filled plotters need one fill() to
                # capture the current linked-attribute state.
                if not getattr(unit, "has_filled", False):
                    unit.fill()
                unit.redraw(figure)
            except Exception as e:
                self.warning("plotter %s failed to render: %s",
                             unit.name, e)
                continue
            rendered_formats = {}
            for fmt in formats:
                rendered = io.BytesIO()
                figure.savefig(rendered, format=fmt)
                rendered_formats[fmt] = rendered.getvalue()
            plots[self._uniquify(unit.name, seen)] = rendered_formats
        return plots
