"""Publishing backend base + registry (``veles/publishing/backend.py``,
``registry.py``)."""

from veles_tpu.logger import Logger


class PublishingBackendRegistry(type):
    """Metaclass: classes with a ``MAPPING`` land in ``backends``."""

    backends = {}

    def __init__(cls, name, bases, namespace):
        super(PublishingBackendRegistry, cls).__init__(
            name, bases, namespace)
        mapping = namespace.get("MAPPING")
        if mapping:
            PublishingBackendRegistry.backends[mapping] = cls


class Backend(Logger, metaclass=PublishingBackendRegistry):
    """One way of rendering the gathered run info."""

    def __init__(self, **kwargs):
        super(Backend, self).__init__()

    def render(self, info):
        raise NotImplementedError
