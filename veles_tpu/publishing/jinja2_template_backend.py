"""Template-driven publishing backend
(``veles/publishing/jinja2_template_backend.py``)."""

import os

from veles_tpu.publishing.backend import Backend

#: the default report template — Markdown text, jinja2 syntax
DEFAULT_TEMPLATE = """\
# {{ name }} — training report

{{ description or "" }}

| | |
|---|---|
| run id | {{ id }} |
| log id | {{ logid }} |
| python | {{ python }} |
| pid | {{ pid }} |
| elapsed | {{ "%dd %02d:%02d:%02d"|format(days, hours, mins, secs) }} |

## Results

{% if results %}| metric | value |
|---|---|
{% for key in results | sort %}| {{ key }} | {{ results[key] }} |
{% endfor %}{% else %}_no result providers in the workflow_
{% endif %}

## Data

{% if class_lengths is defined %}\
- class lengths (test/validation/train): {{ class_lengths }}
- total samples: {{ total_samples }}
- epochs served: {{ epochs }}
- normalization: {{ normalization }} {{ normalization_parameters }}
{% if labels is defined %}- labels: {{ labels }}
{% endif %}{% else %}_no loader attached_
{% endif %}

## Unit run times

| unit | seconds | calls |
|---|---|---|
{% for name in unit_run_times_by_name | sort %}\
| {{ name }} | {{ "%.3f"|format(unit_run_times_by_name[name][0]) }} \
| {{ unit_run_times_by_name[name][1] }} |
{% endfor %}

{% if plots %}## Plots

{% for plot_name in plots | sort %}![{{ plot_name }}]({{ plot_name }}.{{ image_format }})
{% endfor %}{% endif %}

## Configuration

```
{{ config_text }}```

## Workflow graph

```dot
{{ workflow_graph }}```
"""


class Jinja2TemplateBackend(Backend):
    """Renders ``info`` through a jinja2 template."""

    MAPPING = "jinja2"
    #: subclasses that publish elsewhere (Confluence) may opt out
    requires_file = True

    def __init__(self, **kwargs):
        super(Jinja2TemplateBackend, self).__init__(**kwargs)
        self.template_text = kwargs.get("template", DEFAULT_TEMPLATE)
        template_file = kwargs.get("template_file")
        if template_file:
            with open(template_file) as fin:
                self.template_text = fin.read()
        self.file = kwargs.get("file")
        if self.file is None and self.requires_file \
                and not self._alternate_output(kwargs):
            # a misspelled kwarg must not silently render to nowhere
            raise ValueError("%s needs a file=... path (got kwargs %s)"
                             % (type(self).__name__, sorted(kwargs)))
        self.image_format = kwargs.get("image_format", "png")
        self.content = None

    @property
    def image_formats(self):
        return (self.image_format,)

    @staticmethod
    def _alternate_output(kwargs):
        """Subclasses with other output channels override this."""
        return False

    def render_content(self, info):
        import jinja2
        env = jinja2.Environment(
            undefined=jinja2.ChainableUndefined,
            trim_blocks=False, autoescape=False)
        template = env.from_string(self.template_text)
        ctx = dict(info)
        ctx.setdefault("image_format", self.image_format)
        self.content = template.render(**ctx)
        return self.content

    def _write(self, path, content):
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        mode = "wb" if isinstance(content, bytes) else "w"
        with open(path, mode) as fout:
            fout.write(content)
        self.info("wrote %s", path)

    def _write_plots(self, info, directory):
        for name, formats in (info.get("plots") or {}).items():
            data = formats.get(self.image_format)
            if data is None:
                continue
            self._write(os.path.join(
                directory, "%s.%s" % (name, self.image_format)), data)

    def render(self, info):
        content = self.render_content(info)
        if hasattr(self.file, "write"):
            self.file.write(content)
        elif self.file:
            self._write(self.file, content)
            self._write_plots(info, os.path.dirname(
                os.path.abspath(self.file)))
        return content
