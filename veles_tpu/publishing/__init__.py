"""Publishing: render a training-run report through pluggable backends.

Re-designs ``veles/publishing/`` (Publisher at ``publisher.py:57-256``,
backend registry at ``registry.py``, Markdown/Jinja2/PDF/Confluence
backends). The :class:`Publisher` unit gathers everything knowable
about the run — workflow identity, config text, loader statistics,
per-unit run times, metric results, rendered plots, the DOT graph —
into one ``info`` dict and hands it to each configured backend.
"""

from veles_tpu.publishing.backend import (Backend,  # noqa: F401
                                          PublishingBackendRegistry)
from veles_tpu.publishing.confluence_backend import \
    ConfluenceBackend  # noqa: F401
from veles_tpu.publishing.jinja2_template_backend import \
    Jinja2TemplateBackend  # noqa: F401
from veles_tpu.publishing.markdown_backend import \
    MarkdownBackend  # noqa: F401
from veles_tpu.publishing.pdf_backend import PdfBackend  # noqa: F401
from veles_tpu.publishing.publisher import Publisher  # noqa: F401
