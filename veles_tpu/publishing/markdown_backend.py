"""Markdown publishing backend
(``veles/publishing/markdown_backend.py``)."""

from veles_tpu.publishing.jinja2_template_backend import \
    Jinja2TemplateBackend

_HTML_WRAPPER = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>%(title)s</title>
<style>body { font-family: sans-serif; max-width: 60em; margin: 2em auto; }
table { border-collapse: collapse; } td, th { border: 1px solid #ccc;
padding: 0.3em 0.8em; } pre { background: #f5f5f5; padding: 1em;
overflow-x: auto; }</style></head><body>
%(body)s
</body></html>"""


class MarkdownBackend(Jinja2TemplateBackend):
    """Writes the report as Markdown; optional HTML rendering when the
    ``markdown`` package is installed (gated — not in this image)."""

    MAPPING = "markdown"

    def __init__(self, **kwargs):
        super(MarkdownBackend, self).__init__(**kwargs)
        self.html = kwargs.get("html", False)
        self.html_file = kwargs.get("html_file")

    @staticmethod
    def _alternate_output(kwargs):
        # html_file-only configuration is a valid output target
        return bool(kwargs.get("html_file"))

    def render(self, info):
        content = super(MarkdownBackend, self).render(info)
        if self.html or self.html_file:
            try:
                import markdown
            except ImportError:
                self.warning("the 'markdown' package is not installed; "
                             "skipping the HTML rendering")
                return content
            body = markdown.markdown(content,
                                     extensions=["tables", "fenced_code"])
            html = _HTML_WRAPPER % {"title": info.get("name", "report"),
                                    "body": body}
            if self.html_file:
                self._write(self.html_file, html)
            return html
        return content
