"""Confluence publishing backend
(``veles/publishing/confluence_backend.py``).

Posts the rendered report to a Confluence server through the storage
REST API (``/rest/api/content``). Gated: without a ``server`` URL the
backend refuses at construction; network failures surface as warnings
with the payload preserved on ``last_payload`` for inspection/retry.
The page body is the Markdown report wrapped in a preformatted
storage-format block — the reference's XML template amounted to the
same "typed-up report on a page" outcome.
"""

import base64
import json
import urllib.error
import urllib.request

from veles_tpu.publishing.markdown_backend import MarkdownBackend


class ConfluenceBackend(MarkdownBackend):
    MAPPING = "confluence"
    requires_file = False    # publishes to the server, not a path
    image_formats = ()       # report text only

    def __init__(self, **kwargs):
        kwargs.setdefault("file", None)
        super(ConfluenceBackend, self).__init__(**kwargs)
        self.server = kwargs.get("server")
        if not self.server:
            raise ValueError(
                "ConfluenceBackend needs server=https://confluence... "
                "(this backend is gated on a reachable server)")
        self.space = kwargs.get("space")
        self.parent = kwargs.get("parent")
        self.username = kwargs.get("username")
        self.password = kwargs.get("password")
        self.last_payload = None

    def render(self, info):
        content = self.render_content(info)
        title = "%s run %s" % (info.get("name", "veles_tpu"),
                               str(info.get("id", ""))[:8])
        storage = "<ac:structured-macro ac:name=\"code\">" \
                  "<ac:parameter ac:name=\"language\">text</ac:parameter>" \
                  "<ac:plain-text-body><![CDATA[%s]]></ac:plain-text-body>" \
                  "</ac:structured-macro>" % content.replace("]]>", "]] >")
        payload = {
            "type": "page",
            "title": title,  # JSON field, plain text — no XML escaping
            "space": {"key": self.space},
            "body": {"storage": {"value": storage,
                                 "representation": "storage"}},
        }
        if self.parent:
            payload["ancestors"] = [{"id": self.parent}]
        self.last_payload = payload
        url = self.server.rstrip("/") + "/rest/api/content"
        headers = {"Content-Type": "application/json"}
        if self.username:
            token = base64.b64encode(
                ("%s:%s" % (self.username, self.password or "")
                 ).encode()).decode()
            headers["Authorization"] = "Basic " + token
        request = urllib.request.Request(
            url, data=json.dumps(payload).encode(), headers=headers)
        try:
            with urllib.request.urlopen(request, timeout=30) as resp:
                reply = json.loads(resp.read())
            self.info("published to Confluence page id %s",
                      reply.get("id"))
            return reply
        except (urllib.error.URLError, OSError, ValueError) as e:
            self.warning("Confluence publish failed: %s "
                         "(payload kept on last_payload)", e)
            return None
