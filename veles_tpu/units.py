"""The dataflow node type: Unit.

Re-designs ``veles/units.py`` (Unit at :108, link_from :554, link_attrs
:638, demand :682, open_gate :524, gates/run wrappers :782-845). A Unit is
a node in a workflow graph with

* **control links** — ``a.link_from(b)`` means "a becomes runnable after
  b fires"; a unit with several incoming links waits for *all* of them
  (barrier semantics), then its fired-flags reset, which is what makes
  loops (via :class:`~veles_tpu.plumbing.Repeater`) work;
* **gates** — shared :class:`~veles_tpu.mutable.Bool` cells:
  ``gate_block`` suppresses both the unit and its subtree, ``gate_skip``
  skips the unit's body but still fires its dependents;
* **data links** — ``link_attrs`` makes attributes aliases of another
  unit's attributes (see :mod:`veles_tpu.mutable`);
* **demand contract** — ``demand("x", "y")`` declares attributes that
  must be provided (set or linked) before ``initialize()``.

Execution is driven by the owning workflow's deterministic scheduler
(:mod:`veles_tpu.workflow`) — not by a thread pool as in the reference:
on TPU, determinism and a single dispatch thread are features, and JAX's
async dispatch provides the overlap the reference got from threads.
"""

import time
import weakref

from veles_tpu.config import root
from veles_tpu.distributable import Distributable, IDistributable  # noqa: F401
from veles_tpu.mutable import Bool, link, unlink
from veles_tpu.telemetry import tracing
from veles_tpu.telemetry.registry import get_registry
from veles_tpu.unit_registry import UnitRegistry

_unit_run_ms = None


def _unit_run_hist():
    """Lazy: most processes never flip ``timings`` or enable tracing."""
    global _unit_run_ms
    if _unit_run_ms is None:
        _unit_run_ms = get_registry().histogram(
            "veles_unit_run_ms", "Per-unit run() wall time",
            labels=("unit",))
    return _unit_run_ms


class IUnit(object):
    """Documentation marker: units implement initialize() and run()."""


class Unit(Distributable, metaclass=UnitRegistry):
    """Base dataflow node. See module docstring for semantics."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.name = kwargs.pop("name", None) or type(self).__name__
        self.view_group = kwargs.pop("view_group",
                                     getattr(self, "view_group", "WORKER"))
        self.timings = kwargs.pop("timings", root.common.get("timings", False))
        super(Unit, self).__init__(**kwargs)
        self.links_from = {}
        self.links_to = []
        self.gate_block = Bool(False)
        self.gate_skip = Bool(False)
        self.demanded = set()
        self._is_initialized = False
        self.run_calls = 0
        self.run_time = 0.0
        self._workflow = None
        self.workflow = workflow

    # -- identity ---------------------------------------------------------

    @property
    def workflow(self):
        return self._workflow

    @workflow.setter
    def workflow(self, value):
        if self._workflow is not None:
            self._workflow.del_ref(self)
        self._workflow = value
        if value is not None:
            value.add_ref(self)

    @property
    def is_standalone(self):
        return self.launcher.mode == "standalone" if self.launcher else True

    @property
    def is_master(self):
        return self.launcher.mode == "master" if self.launcher else False

    @property
    def is_slave(self):
        return self.launcher.mode == "slave" if self.launcher else False

    @property
    def launcher(self):
        from veles_tpu.workflow import Workflow
        node = self._workflow
        while isinstance(node, Workflow):
            node = node.workflow
        return node

    @property
    def is_initialized(self):
        return self._is_initialized

    @property
    def stopped(self):
        return bool(self._workflow.stopped) if self._workflow else False

    # -- control links ----------------------------------------------------

    def link_from(self, *sources):
        """Run after all of ``sources``; returns self for chaining."""
        for src in sources:
            if src is self:
                raise ValueError("%s cannot link from itself" % self)
            self.links_from[src] = False
            if self not in src.links_to:
                src.links_to.append(self)
        return self

    def unlink_from(self, *sources):
        for src in sources:
            self.links_from.pop(src, None)
            if self in src.links_to:
                src.links_to.remove(self)
        return self

    def unlink_all(self):
        self.unlink_before()
        self.unlink_after()

    def unlink_before(self):
        for src in list(self.links_from):
            self.unlink_from(src)

    def unlink_after(self):
        for dst in list(self.links_to):
            dst.unlink_from(self)

    def insert_after(self, *chain):
        """Splice ``chain`` between self and self's current dependents."""
        dependents = list(self.links_to)
        for dst in dependents:
            dst.unlink_from(self)
        prev = self
        for unit in chain:
            unit.link_from(prev)
            prev = unit
        for dst in dependents:
            dst.link_from(prev)
        return prev

    def dependent_units(self):
        """BFS over control links from self (``veles/units.py:507-522``)."""
        seen = [self]
        pos = 0
        while pos < len(seen):
            for dst in seen[pos].links_to:
                if dst not in seen:
                    seen.append(dst)
            pos += 1
        return seen

    # -- data links --------------------------------------------------------

    def link_attrs(self, other, *names, two_way=False):
        """Alias attributes of ``other`` into self.

        Each name is either ``"attr"`` or ``("mine", "theirs")``
        (``veles/units.py:638-680``).
        """
        for name in names:
            if isinstance(name, tuple):
                mine, theirs = name
            else:
                mine = theirs = name
            link(self, mine, other, theirs, two_way=two_way)
        return self

    def unlink_attrs(self, *names):
        for name in names:
            unlink(self, name)

    def demand(self, *names):
        """Declare attributes that must be provided before initialize()."""
        self.demanded.update(names)

    def _check_demands(self):
        missing = sorted(n for n in self.demanded if not hasattr(self, n))
        if missing:
            raise AttributeError(
                "unit %s requires attribute(s) %s to be set or linked "
                "before initialize()" % (self.name, ", ".join(missing)))

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, **kwargs):
        """Override in subclasses. Return True to request re-init later
        (partial initialization, ``veles/workflow.py:331-336``)."""
        return None

    def _initialize_wrapped(self, **kwargs):
        self._check_demands()
        from veles_tpu.prng import get as get_rng
        rng = get_rng()
        state = rng.save_state()
        try:
            result = self.initialize(**kwargs)
        finally:
            # units must not perturb global RNG stream order during init
            # (reproducibility contract of ``veles/units.py:859-885``)
            if not getattr(self, "consumes_global_rng_on_init", False):
                rng.restore_state(state)
        self._is_initialized = result is not True
        return result

    def run(self):
        """Override in subclasses: the unit's compute body."""

    def _run_wrapped(self):
        if not self._is_initialized:
            raise RuntimeError("unit %s run before initialize" % self.name)
        if self.stopped and not getattr(self._workflow, "is_running", False) \
                and root.common.exceptions.get("run_after_stop", True):
            # running outside the workflow's drain is a bug; running
            # *during* the final drain is the normal end of a loop
            # iteration (see Workflow._drain)
            raise RuntimeError("unit %s run after workflow stop" % self.name)
        self.event("run", "begin")
        start = time.perf_counter()
        try:
            return self.run()
        finally:
            elapsed = time.perf_counter() - start
            self.run_calls += 1
            self.run_time += elapsed
            if tracing.enabled():
                tracing.add_complete("unit:%s" % self.name, start, elapsed,
                                     unit=type(self).__name__)
            if self.timings or tracing.enabled():
                # timings routes through telemetry: the data is readable
                # from /metrics (or the registry snapshot) at any log
                # level; the debug line stays for backward compatibility
                _unit_run_hist().labels(unit=self.name).observe(
                    elapsed * 1e3)
            if self.timings:
                self.debug("%s ran in %.3f ms", self.name, elapsed * 1e3)
            self.event("run", "end")

    # -- gate machinery ----------------------------------------------------

    def open_gate(self, src):
        """Record that ``src`` fired; True when all inputs have fired.

        Resets the fired-flags on success so the unit can run again in the
        next loop iteration (``veles/units.py:524-543``).
        """
        if src is not None:
            if src not in self.links_from:
                return False
            self.links_from[src] = True
        if all(self.links_from.values()) or src is None:
            for key in self.links_from:
                self.links_from[key] = False
            return True
        return False

    def reset_fired(self):
        for key in self.links_from:
            self.links_from[key] = False

    # -- manual (workflow-less) firing ------------------------------------

    def run_dependent(self):
        """Fire dependents through the owning workflow's scheduler."""
        self._workflow.signal_fired(self)

    def describe(self):
        return "%s \"%s\" [%s]" % (type(self).__name__, self.name,
                                   self.view_group)

    def __repr__(self):
        return "<%s \"%s\">" % (type(self).__name__, self.name)

    def __getstate__(self):
        state = super(Unit, self).__getstate__()
        if self.stripped_pickle:
            state["links_from"] = {}
            state["links_to"] = []
            state["_workflow"] = None
            # attribute links point at other units: without this a
            # "stripped" unit still drags the whole graph along
            state["__linked__"] = {}
        return state


class TrivialUnit(Unit):
    """A do-nothing unit (useful as a join point)."""

    hide_from_registry = True

    def initialize(self, **kwargs):
        pass

    def run(self):
        pass


class Container(Unit):
    """Marker base for units that contain other units (Workflow)."""

    hide_from_registry = True
