"""Compare two workflow snapshots array-by-array.

Re-designs ``veles/scripts/compare_snapshots.py``: loads both
snapshots, walks units in dependency order, diffs every
:class:`~veles_tpu.memory.Array` attribute and prints a sortable table
of average-relative / average / max absolute differences. Useful for
answering "did this refactor change the numerics" and "how far apart
are these two training runs".
"""

import argparse
import logging
import sys

import numpy

SORT_CHOICES = ("dep", "unit", "attr", "avgreldiff", "avgdiff", "maxdiff")
SORT_CHOICES_MAP = {k: i for i, k in enumerate(SORT_CHOICES)}


def parse_args(argv=None):
    parser = argparse.ArgumentParser(description="Compare snapshots")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="do not print logs")
    parser.add_argument("-s", "--sort", choices=SORT_CHOICES, nargs="*",
                        default=["dep", "avgreldiff"],
                        help="sort by these fields, in order")
    parser.add_argument("first", help="path to the first snapshot")
    parser.add_argument("second", help="path to the second snapshot")
    return parser.parse_args(argv)


def load_snapshot(path):
    from veles_tpu.snapshotter import SnapshotterToFile
    return SnapshotterToFile.import_(path)


def get_diffs(first_units, second_units):
    """Yield (dep_index, unit, attr, avgreldiff, avgdiff, maxdiff)."""
    from veles_tpu.memory import Array
    for index, (first_unit, second_unit) in enumerate(
            zip(first_units, second_units)):
        for key, first_val in sorted(first_unit.__dict__.items()):
            if not isinstance(first_val, Array):
                continue
            second_val = getattr(second_unit, key, None)
            if not isinstance(second_val, Array):
                continue
            if first_val.mem is None or second_val.mem is None:
                continue
            a = numpy.asarray(first_val.mem, numpy.float64)
            b = numpy.asarray(second_val.mem, numpy.float64)
            if a.shape != b.shape:
                yield (index, first_unit.name, key,
                       float("inf"), float("inf"), float("inf"))
                continue
            diff = a - b
            avg_diff = float(numpy.mean(numpy.abs(diff)))
            val_sum = a + b
            nz = numpy.nonzero(val_sum)
            rel = 2 * (diff[nz] / val_sum[nz])
            if rel.size > 0:
                avg_rel_diff = float(numpy.mean(numpy.abs(rel)))
            else:
                avg_rel_diff = float(not (diff == 0).all())
            max_diff = float(numpy.max(numpy.abs(diff))) if diff.size \
                else 0.0
            yield (index, first_unit.name, key,
                   avg_rel_diff, avg_diff, max_diff)


def sort_diffs(diffs, sorting):
    return sorted(diffs, key=lambda rec: tuple(
        rec[SORT_CHOICES_MAP[sk]] for sk in sorting))


def format_table(diffs):
    """Plain-text table (the reference used bundled prettytable)."""
    headers = ("Unit", "Attribute", "Avg Rel Diff", "Avg Diff", "Max Diff")
    rows = [(name, attr, "%.6g" % rel, "%.6g" % avg, "%.6g" % mx)
            for _, name, attr, rel, avg, mx in diffs]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    sep = "+-" + "-+-".join("-" * w for w in widths) + "-+"
    out = [sep, "| " + " | ".join(
        h.ljust(w) for h, w in zip(headers, widths)) + " |", sep]
    for row in rows:
        out.append("| " + " | ".join(
            c.ljust(w) for c, w in zip(row, widths)) + " |")
    out.append(sep)
    return "\n".join(out)


def compare(first_path, second_path, sorting=("dep", "avgreldiff")):
    first = load_snapshot(first_path)
    second = load_snapshot(second_path)
    if type(first) is not type(second) or \
            first.checksum != second.checksum:
        raise ValueError("Cannot compare different workflows")
    return sort_diffs(get_diffs(first.units_in_dependency_order,
                                second.units_in_dependency_order), sorting)


def main(argv=None):
    args = parse_args(argv)
    logging.basicConfig(
        level=logging.WARNING if args.quiet else logging.INFO)
    diffs = compare(args.first, args.second, args.sort)
    print(format_table(diffs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
