"""Operator scripts (re-designs ``veles/scripts/``): compare_snapshots,
generate_frontend. Run as ``python -m veles_tpu.scripts.<name>``."""
