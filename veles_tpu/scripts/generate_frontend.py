"""Dump the unit registry + aggregated CLI for the web frontend.

Re-designs ``veles/scripts/generate_frontend.py``: walks
:class:`~veles_tpu.unit_registry.UnitRegistry` and the aggregated
argparse tree (``veles_tpu/cmdline.py``) and emits a JSON document the
command-composer UI consumes — every unit type (name, module, docstring,
stable ``__id__``) and every CLI flag (name, default, choices, help).
"""

import argparse
import json
import sys


#: modules whose import populates the unit registry — the catalog must
#: cover the whole shipped unit surface, not just what happens to be
#: imported already
_UNIT_MODULES = (
    "veles_tpu.plumbing", "veles_tpu.loader", "veles_tpu.nn",
    "veles_tpu.snapshotter", "veles_tpu.plotting_units",
    "veles_tpu.restful_api", "veles_tpu.interaction",
    "veles_tpu.downloader", "veles_tpu.avatar", "veles_tpu.input_joiner",
    "veles_tpu.mean_disp_normalizer", "veles_tpu.zmq_loader",
    "veles_tpu.genetics", "veles_tpu.ensemble", "veles_tpu.launcher",
    "veles_tpu.publishing",
)


def describe_units():
    import importlib
    for mod in _UNIT_MODULES:
        importlib.import_module(mod)
    from veles_tpu.unit_registry import UnitRegistry
    units = {}
    for name, cls in sorted(UnitRegistry.units.items()):
        units[name] = {
            "module": cls.__module__,
            "id": getattr(cls, "__id__", None),
            "doc": (cls.__doc__ or "").strip().split("\n")[0],
            "view_group": getattr(cls, "view_group", "WORKER"),
        }
    return units


def describe_arguments():
    from veles_tpu.cmdline import init_parser
    parser = init_parser(prog="veles_tpu")
    args = []
    for action in parser._actions:
        if isinstance(action, argparse._HelpAction):
            continue
        if isinstance(action, (argparse._StoreTrueAction,
                               argparse._StoreFalseAction)):
            kind = "flag"
        elif not action.option_strings:
            kind = "positional"
        else:
            kind = "option"
        args.append({
            "flags": list(action.option_strings) or [action.dest],
            "dest": action.dest,
            "kind": kind,
            "default": action.default
            if not callable(action.default) else None,
            "choices": list(action.choices) if action.choices else None,
            "required": bool(action.required),
            "help": action.help or "",
        })
    return args


def generate(path=None):
    doc = {"units": describe_units(), "arguments": describe_arguments()}
    text = json.dumps(doc, indent=2, default=str, sort_keys=True)
    if path:
        with open(path, "w") as f:
            f.write(text + "\n")
    return doc


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Generate the frontend unit/argument catalog")
    parser.add_argument("-o", "--output", default=None,
                        help="write JSON here (default: stdout)")
    args = parser.parse_args(argv)
    doc = generate(args.output)
    if not args.output:
        json.dump(doc, sys.stdout, indent=2, default=str, sort_keys=True)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
