#!/usr/bin/env python3
"""Bulk-sync local model packages to a forge server.

Re-designs ``veles/scripts/update_forge.py``: the reference scanned
its workflow tree for directories carrying a forge manifest and
re-uploaded each to VELESForge. Here the scan root is an argument (no
hard-coded source layout), packages are any directory containing
``manifest.json`` (the forge client's contract), and failures are
reported per package instead of aborting the sweep.

Usage::

    python -m veles_tpu.scripts.update_forge SCAN_DIR \
        --server http://forge-host:8080 [--token TOKEN] [--dry-run]
"""

import argparse
import json
import os
import sys


def find_packages(scan_root):
    """Yield directories under ``scan_root`` containing manifest.json."""
    for dirpath, dirnames, filenames in os.walk(scan_root):
        if "manifest.json" in filenames:
            yield dirpath
            # a package is a leaf: never descend into its subtrees
            # (plots/, data/ ride inside the upload tar)
            dirnames[:] = []


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("scan_dir", help="tree to scan for packages")
    parser.add_argument("--server", default=os.getenv("FORGE_SERVER"),
                        help="forge base URL (or $FORGE_SERVER)")
    parser.add_argument("--token", default=os.getenv("FORGE_TOKEN"))
    parser.add_argument("--dry-run", action="store_true",
                        help="list what would upload, upload nothing")
    args = parser.parse_args(argv)
    if not args.server:
        parser.error("no forge server: pass --server or set "
                     "FORGE_SERVER")

    from veles_tpu.forge.client import ForgeClient

    client = ForgeClient(args.server, token=args.token)
    found = failed = 0
    for package in find_packages(args.scan_dir):
        found += 1
        try:
            if args.dry_run:
                with open(os.path.join(package, "manifest.json")) as f:
                    name = json.load(f).get("name",
                                            os.path.basename(package))
                print("would upload %s (%s)" % (name, package))
                continue
            # client.upload parses the manifest itself (fail fast)
            reply = client.upload(package)
            print("uploaded %s version %s" % (reply["name"],
                                              reply["version"]))
        except (RuntimeError, OSError, ValueError, KeyError) as e:
            # one broken package (bad manifest, rejected upload) must
            # not abort the sweep
            failed += 1
            print("FAILED %s: %s" % (package, e), file=sys.stderr)
    if not found:
        print("no packages (manifest.json) under %s" % args.scan_dir,
              file=sys.stderr)
        return 1
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
