"""Activation functions + standalone activation units.

Mirrors the Znicz activation family (``manualrst_veles_algorithms.rst``
"Extras": tanh/sigmoid/RELU/strict RELU/log/mul). Derivatives are never
hand-written — backward units use ``jax.vjp`` over these functions.
The reference's scaled tanh (1.7159 * tanh(2/3 x), the classic LeCun
variant used by Znicz All2AllTanh) is kept bit-for-bit.
"""

import jax
import jax.numpy as jnp

from veles_tpu.nn.base import ForwardBase


def linear(x):
    return x

def tanh_scaled(x):
    """LeCun-scaled tanh used by Znicz All2AllTanh."""
    return 1.7159 * jnp.tanh(0.6666 * x)

def sigmoid(x):
    return jax.nn.sigmoid(x)

def relu_soft(x):
    """Znicz's default "RELU": log(1 + exp(x)) (softplus)."""
    return jnp.where(x > 15.0, x, jnp.log1p(jnp.exp(jnp.minimum(x, 15.0))))

def relu_strict(x):
    return jnp.maximum(x, 0.0)

def leaky_relu(x, alpha=0.01):
    return jnp.where(x >= 0, x, alpha * x)

def log_activation(x):
    return jnp.log(x + jnp.sqrt(jnp.square(x) + 1.0))

def sincos(x):
    """Znicz ActivationSinCos: odd features sin, even features cos."""
    idx = jnp.arange(x.shape[-1])
    return jnp.where(idx % 2 == 1, jnp.sin(x), jnp.cos(x))

def mul_by_const(x, k=1.0):
    return x * k


ACTIVATIONS = {
    "linear": linear,
    "tanh": tanh_scaled,
    "sigmoid": sigmoid,
    "relu": relu_soft,
    "strict_relu": relu_strict,
    "leaky_relu": leaky_relu,
    "log": log_activation,
    "sincos": sincos,
}


def get_activation(name):
    if callable(name):
        return name
    try:
        return ACTIVATIONS[name]
    except KeyError:
        raise ValueError("unknown activation %r (have: %s)" %
                         (name, sorted(ACTIVATIONS)))


class ActivationUnit(ForwardBase):
    """Standalone elementwise activation unit (no weights)."""

    def __init__(self, workflow, activation="linear", **kwargs):
        kwargs.setdefault("include_bias", False)
        super(ActivationUnit, self).__init__(workflow, **kwargs)
        self.activation_name = (activation if isinstance(activation, str)
                                else activation.__name__)
        self._activation = get_activation(activation)

    @property
    def has_weights(self):
        return False

    def output_shape_for(self, input_shape):
        return input_shape

    def apply(self, params, x):
        return self._activation(x)

    def init_unpickled(self):
        super(ActivationUnit, self).init_unpickled()
        if hasattr(self, "activation_name"):
            self._activation = get_activation(self.activation_name)

    def __getstate__(self):
        state = super(ActivationUnit, self).__getstate__()
        state.pop("_activation", None)
        return state
