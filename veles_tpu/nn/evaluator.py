"""Evaluator units: turn forward output + ground truth into loss,
error counts and the backward seed (``err_output``).

Znicz contract: EvaluatorSoftmax feeds GDSoftmax with
``err_output = probs - onehot(target)`` (the gradient w.r.t. the
pre-softmax logits — which is why GDSoftmax differentiates only the
linear part), plus ``n_err`` (misclassification count) and a confusion
matrix; EvaluatorMSE feeds plain GD with ``output - target``.

Batch normalization of the gradient (1/batch) is applied here so the
learning rate means the same thing at any minibatch size.
"""

import functools

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.result_provider import IResultProvider


@functools.partial(jax.jit, static_argnames=("n_classes",
                                             "compute_confusion"))
def _softmax_eval(probs, labels, n_classes, compute_confusion=True):
    batch = probs.shape[0]
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    onehot = jax.nn.one_hot(safe, n_classes, dtype=probs.dtype)
    err = (probs - onehot) * valid[:, None] / batch
    pred = jnp.argmax(probs, axis=1)
    n_err = jnp.sum((pred != safe) & valid)
    p_true = jnp.take_along_axis(probs, safe[:, None], axis=1)[:, 0]
    loss = -jnp.sum(jnp.log(jnp.maximum(p_true, 1e-30)) * valid) \
        / jnp.maximum(jnp.sum(valid), 1)
    confusion = None
    if compute_confusion:
        flat = safe * n_classes + pred
        confusion = jnp.zeros((n_classes * n_classes,), jnp.int32).at[
            flat].add(valid.astype(jnp.int32)).reshape(n_classes, n_classes)
    max_err_sum = jnp.max(jnp.sum(jnp.abs(err), axis=1))
    return err, n_err, loss, confusion, max_err_sum


@jax.jit
def _mse_eval(output, target, valid=None):
    batch = output.shape[0]
    diff = output.reshape(batch, -1) - target.reshape(batch, -1)
    if valid is None:
        n_valid = jnp.float32(batch)
        vmask = jnp.ones((batch, 1), output.dtype)
    else:
        vmask = valid.astype(output.dtype)[:, None]
        n_valid = jnp.maximum(jnp.sum(vmask), 1.0)
    diff = diff * vmask  # phantom padded rows contribute nothing
    err = diff / n_valid
    mse_per_sample = jnp.mean(jnp.square(diff), axis=1)
    return err, jnp.sqrt(jnp.sum(mse_per_sample) / n_valid), mse_per_sample


class EvaluatorBase(AcceleratedUnit, IResultProvider):
    hide_from_registry = True
    view_group = "EVALUATOR"

    def __init__(self, workflow, **kwargs):
        super(EvaluatorBase, self).__init__(workflow, **kwargs)
        self.output = None         # linked from the head forward unit
        self.err_output = Array()  # consumed by the GD chain
        self.testing = kwargs.get("testing", False)
        # opt-in: accumulate per-minibatch outputs/labels and publish
        # them in the results JSON — what the ensemble layer stacks on
        # (``veles/loader/ensemble.py:64-75`` reads models[i]["Output"]).
        # Recording only happens in testing (forward-only) mode: that is
        # the single clean pass over one class the ensemble consumes;
        # recording during training would mix train/validation outputs
        # across epochs and grow without bound.
        self.publish_output = kwargs.get("publish_output", False)
        self.batch_size = None  # link from loader "minibatch_size"
        self.recorded_outputs = []
        self.recorded_labels = []
        self.demand("output")

    def initialize(self, device=None, **kwargs):
        super(EvaluatorBase, self).initialize(device=device, **kwargs)
        # a fresh (or snapshot-resumed) pass starts a fresh recording
        self.recorded_outputs = []
        self.recorded_labels = []
        out = self.output
        mem = out.mem if isinstance(out, Array) else out
        self.err_output.reset(numpy.zeros(mem.shape, numpy.float32))
        self.init_vectors(self.err_output)

    def _output_devmem(self):
        return (self.output.devmem if isinstance(self.output, Array)
                else self.output)

    def _record(self, output, labels=None):
        if not (self.publish_output and self.testing):
            return
        output = numpy.asarray(output)
        labels = None if labels is None else numpy.asarray(labels)
        # trim pad rows: the final minibatch is padded to max size
        # (pad labels are -1, see ops/gather); padding is at the tail
        if self.batch_size is not None:
            n = int(self.batch_size)
        elif labels is not None:
            valid = numpy.flatnonzero(labels >= 0)
            n = int(valid[-1]) + 1 if len(valid) else 0
        else:
            n = len(output)
        self.recorded_outputs.append(output[:n])
        if labels is not None:
            self.recorded_labels.append(labels[:n])

    def _recorded_metrics(self):
        if not (self.publish_output and self.recorded_outputs):
            return {}
        out = {"Output": numpy.concatenate(self.recorded_outputs).tolist()}
        if self.recorded_labels:
            out["Labels"] = numpy.concatenate(self.recorded_labels).tolist()
        return out


class EvaluatorSoftmax(EvaluatorBase):
    """Cross-entropy over a softmax head."""

    def __init__(self, workflow, **kwargs):
        super(EvaluatorSoftmax, self).__init__(workflow, **kwargs)
        self.labels = None  # linked from loader (minibatch_labels)
        self.n_err = 0
        self.loss = 0.0
        self.max_err_output_sum = 0.0
        self.confusion_matrix = None
        self.compute_confusion = kwargs.get("compute_confusion", True)
        self.demand("labels")

    def jax_run(self):
        probs = self._output_devmem()
        labels = (self.labels.devmem if isinstance(self.labels, Array)
                  else jnp.asarray(self.labels))
        n_classes = probs.shape[-1]
        err, n_err, loss, confusion, max_err = _softmax_eval(
            probs.reshape(probs.shape[0], -1), labels, n_classes,
            self.compute_confusion)
        if not self.testing:
            self.err_output.assign_devmem(err.reshape(
                self.err_output.shape))
        self.n_err = int(n_err)
        self.loss = float(loss)
        self.max_err_output_sum = float(max_err)
        if confusion is not None:
            self.confusion_matrix = numpy.asarray(confusion)
        self._record(probs, labels)

    numpy_run = jax_run  # same math through jax-on-host

    def get_metric_values(self):
        out = {"n_err": self.n_err, "loss": self.loss}
        out.update(self._recorded_metrics())
        return out


class EvaluatorMSE(EvaluatorBase):
    """Mean-squared-error head (autoencoders, regression)."""

    def __init__(self, workflow, **kwargs):
        super(EvaluatorMSE, self).__init__(workflow, **kwargs)
        self.target = None   # linked from loader (minibatch_targets)
        self.indices = None  # optional link: loader minibatch_indices
        self.rmse = 0.0
        self.mse_per_sample = None
        self.demand("target")

    def jax_run(self):
        out = self._output_devmem()
        target = (self.target.devmem if isinstance(self.target, Array)
                  else jnp.asarray(self.target))
        valid = None
        if self.indices is not None:
            idx = (self.indices.devmem if isinstance(self.indices, Array)
                   else jnp.asarray(self.indices))
            valid = idx >= 0  # padded tail rows are masked out
        err, rmse, per_sample = _mse_eval(out, target, valid)
        if not self.testing:
            self.err_output.assign_devmem(
                err.reshape(self.err_output.shape))
        self.rmse = float(rmse)
        self.mse_per_sample = numpy.asarray(per_sample)
        self._record(out)

    numpy_run = jax_run

    def get_metric_values(self):
        out = {"rmse": self.rmse}
        out.update(self._recorded_metrics())
        return out
