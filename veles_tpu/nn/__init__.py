"""Neural-network unit library — the TPU-native Znicz replacement.

The reference's NN engine lived in the (absent) ``veles/znicz`` submodule:
All2All/Conv/Pooling forward units, GradientDescent* backward units,
activations, evaluators, Decision, Kohonen, dropout, LRN (SURVEY.md §2,
``docs/source/manualrst_veles_algorithms.rst``). Here each forward unit
owns a *pure function* ``apply(params, x)``; backward units derive their
math from the forward via ``jax.vjp`` (no hand-written gradients), and
the step compiler (:mod:`veles_tpu.train`) composes the same pure
functions into one jitted train step for the TPU hot loop.
"""

from veles_tpu.nn.all2all import (All2All, All2AllRELU, All2AllSigmoid,  # noqa
                                  All2AllSoftmax, All2AllTanh)
from veles_tpu.nn.activation import ActivationUnit  # noqa: F401
from veles_tpu.nn.conv import Conv, ConvRELU, ConvSigmoid, ConvTanh  # noqa
from veles_tpu.nn.pooling import AvgPooling, MaxPooling  # noqa: F401
from veles_tpu.nn.evaluator import EvaluatorMSE, EvaluatorSoftmax  # noqa
from veles_tpu.nn.gd import (GradientDescent, GDActivation, GDConv,  # noqa
                             GDPooling, GDSoftmax, GDTanh, GDRELU,
                             GDSigmoid)
from veles_tpu.nn.decision import DecisionGD, DecisionMSE  # noqa: F401
from veles_tpu.nn.dropout import DropoutBackward, DropoutForward  # noqa

#: Znicz name for the dropout backward unit
GDDropout = DropoutBackward
from veles_tpu.nn.normalization import LRNormalizerForward  # noqa: F401
from veles_tpu.nn.kohonen import KohonenForward, KohonenTrainer  # noqa: F401
