"""Decision units: epoch accounting + stop criterion + GD gating.

The Znicz Decision unit is the control heart of every reference
workflow: it accumulates per-class epoch statistics from the evaluator,
decides when training is complete (max epochs, or no validation
improvement for ``fail_iterations`` epochs), exposes ``gd_skip`` so
gradient units only run on TRAIN minibatches, and raises ``improved``
for the snapshotter. Topology contract (mirrors Znicz MnistWorkflow):

    repeater -> loader -> forwards... -> evaluator -> decision
    decision -> gd[n] -> ... -> gd[0] -> repeater
    end_point.link_from(decision); end_point.gate_block = ~complete
    gd[i].gate_skip = decision.gd_skip
"""

import numpy

from veles_tpu.loader.base import TRAIN, VALIDATION, CLASS_NAMES
from veles_tpu.mutable import Bool
from veles_tpu.result_provider import IResultProvider
from veles_tpu.units import Unit


class DecisionBase(Unit, IResultProvider):
    hide_from_registry = True
    view_group = "TRAINER"

    #: lower is better for these metrics
    METRIC_NAME = "n_err"

    def __init__(self, workflow, **kwargs):
        self.max_epochs = kwargs.pop("max_epochs", None)
        self.fail_iterations = kwargs.pop("fail_iterations", 100)
        super(DecisionBase, self).__init__(workflow, **kwargs)
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.gd_skip = Bool(False)
        # forward-only mode: gradients always skipped, stop after one
        # full epoch (the ``--test`` pass)
        self.testing = False
        self.epoch_stats = [dict() for _ in range(3)]
        self.epoch_history = []
        self.best_metric = numpy.inf
        self.best_epoch = -1
        self.demand("minibatch_class", "last_minibatch", "epoch_ended",
                    "epoch_number", "class_lengths", "minibatch_size")

    def initialize(self, **kwargs):
        if getattr(self, "_restored_from_snapshot_", False):
            # mid-epoch snapshot resume: the partial epoch sums the
            # eager path accumulated per minibatch must survive — the
            # remaining minibatches complete them to the uninterrupted
            # totals (both schedulers rely on this)
            self._restored_from_snapshot_ = False
            return
        self._reset_epoch()

    def _reset_epoch(self):
        for stats in self.epoch_stats:
            stats.clear()
            stats.update(samples=0, metric=0.0)

    # -- per-minibatch metric from the evaluator ---------------------------

    def minibatch_metric(self):
        """Metric value summed over this minibatch (lower = better)."""
        raise NotImplementedError

    def run(self):
        klass = self.minibatch_class
        self.gd_skip <<= (klass != TRAIN) or self.testing
        metric = self.minibatch_metric()
        if self.is_slave:
            # one job = one minibatch: opening the end point after every
            # pass makes Workflow.do_job() run exactly one iteration
            # (the reference's slave-side job granularity,
            # ``loader/base.py:631-639``). The MASTER does the
            # authoritative epoch accounting from these updates — doing
            # it locally too would corrupt best_metric/epoch_history
            # with one slave's partial view.
            self.complete <<= True
            self._pending_update_ = {
                "klass": klass, "samples": self.minibatch_size,
                "metric": metric,
                "epoch": self.epoch_number,
                "last": bool(self.last_minibatch),
                "epoch_ended": bool(self.epoch_ended)}
            return
        stats = self.epoch_stats[klass]
        stats["samples"] += self.minibatch_size
        stats["metric"] += metric
        if bool(self.last_minibatch):
            self._on_class_finished(klass)
        if bool(self.epoch_ended):
            self._on_epoch_finished()

    # -- distribution: metrics ride slave→master, master decides stop ------

    def drop_slave(self, slave=None):
        # A dead slave may have held the very minibatches that keep the
        # oldest epoch open; the loader is about to requeue them, and
        # serving the replays requires job generation — so the run-ahead
        # throttle must reopen here. It re-closes on the next update if
        # the loader is still too far ahead.
        self.has_data_for_slave = True

    def generate_data_for_slave(self, slave=None):
        # non-None payload so the slave's apply_data_from_master runs:
        # it must re-arm the loop gate the previous job closed
        return {"reset_complete": True}

    def prepare_resume(self):
        """Master-restart resume (ISSUE 12): re-arm epoch accounting
        after a snapshot restore.

        Returns the epoch the run should resume FROM (the one after
        the last closed epoch), or ``None`` when the restored run had
        already completed — the launcher then finishes immediately
        instead of retraining the final epoch. The transient merge
        buckets (``_epoch_buckets_`` etc.) died with the old master by
        design; ``_next_close_epoch_`` re-derives from epoch_history
        on the first merged update, so all that needs doing here is
        clearing the stop/throttle state the pickle carried."""
        last_closed = max((h["epoch"] for h in self.epoch_history),
                          default=-1)
        if bool(self.complete) and self.max_epochs is not None and \
                last_closed + 1 >= self.max_epochs:
            return None
        self.complete <<= False
        self.improved <<= False
        self._stop_epoch_ = None
        self.has_data_for_slave = True
        # epoch_number is linked from the loader; the caller rewinds
        # the loader cursor (reset_to_epoch_start) and this unit reads
        # it back through the link
        return last_closed + 1

    def apply_data_from_master(self, data):
        if data.get("reset_complete"):
            self.complete <<= False

    def generate_data_for_master(self):
        update = getattr(self, "_pending_update_", None)
        self._pending_update_ = None
        return update

    def apply_data_from_slave(self, data, slave=None):
        """Master-side epoch accounting over all slaves' minibatches.

        Stats accumulate in PER-EPOCH buckets: with several slaves the
        first minibatches of epoch e+1 can return before the last
        minibatch of epoch e, and a single shared accumulator would
        misattribute them (wrong normalized metric, wrong early-stop).
        """
        if data is None:
            return
        if isinstance(data, list):
            # a fused-segment update: one stats dict per minibatch
            for item in data:
                self.apply_data_from_slave(item, slave)
            return
        stop_epoch = getattr(self, "_stop_epoch_", None)
        if stop_epoch is not None and data.get("epoch", 0) > stop_epoch:
            # run-ahead: pipelined/segmented slaves may return
            # minibatches of epochs past the stop decision — they must
            # not reopen buckets or extend epoch_history (laggard
            # updates for epochs <= the stop epoch still close
            # normally)
            return
        buckets = getattr(self, "_epoch_buckets_", None)
        if buckets is None:
            buckets = self._epoch_buckets_ = {}
        epoch = data.get("epoch", 0)
        bucket = buckets.setdefault(
            epoch, [dict(samples=0, metric=0.0) for _ in range(3)])
        klass = data["klass"]
        bucket[klass]["samples"] += data["samples"]
        bucket[klass]["metric"] += data["metric"]
        # Close on SAMPLE COUNTS, not on the last/epoch_ended flags: with
        # several slaves the flagged minibatch's update can arrive while
        # sibling updates of the same epoch are still in flight, and a
        # flag-triggered close would finalize an incomplete bucket.
        # Every epoch serves exactly sum(class_lengths) samples (requeues
        # are exact replays), so counts are a reliable completion signal.
        if bucket[klass]["samples"] == self.class_lengths[klass]:
            self._on_class_finished(klass, epoch=epoch, stats_set=bucket)
        if sum(b["samples"] for b in bucket) == sum(self.class_lengths):
            # Epochs close STRICTLY IN ORDER. With the 1-epoch run-ahead
            # window, a fast slave can complete ALL of epoch e+1 while a
            # slow sibling still holds epoch e's jobs in its pipeline —
            # closing e+1 first would let max_epochs stop the run with
            # epoch e permanently open (epoch_history [.., e-1, e+1]).
            # A complete-but-out-of-order bucket is therefore parked
            # until every older epoch has closed.
            buckets.pop(epoch, None)
            done = getattr(self, "_complete_epochs_", None)
            if done is None:
                done = self._complete_epochs_ = {}
            done[epoch] = bucket
            nxt = getattr(self, "_next_close_epoch_", None)
            if nxt is None:
                # snapshot resume: continue after the last closed epoch
                nxt = max((h["epoch"] for h in self.epoch_history),
                          default=-1) + 1
            while nxt in done:
                self._on_epoch_finished(epoch=nxt,
                                        stats_set=done.pop(nxt))
                nxt += 1
                if getattr(self, "_stop_epoch_", None) is not None:
                    done.clear()  # run-ahead epochs are cancelled
            self._next_close_epoch_ = nxt
        # bound run-ahead: with asymmetric slave speeds the loader would
        # otherwise serve arbitrarily many epochs past the oldest still
        # open one, training epochs the stop decision may cancel.
        # Withholding data (has_data_for_slave=False) idles job requests
        # until the laggard's updates close the old epoch.
        # the oldest OPEN epoch: once in-order closing has begun,
        # _next_close_epoch_ is it by construction (every older epoch
        # closed; a complete-but-parked younger epoch is NOT open but
        # must not mask an older one that has produced no update yet —
        # min(buckets) alone would, and the run-ahead window would
        # creep one epoch per parked bucket)
        nxt = getattr(self, "_next_close_epoch_", None)
        if nxt is not None:
            min_open = nxt
        elif buckets:
            min_open = min(buckets)
        else:
            min_open = None
        # ... but never throttle while requeued minibatches (from a dead
        # slave) are waiting: they belong to the oldest open epoch, and
        # serving them is the only way that epoch can ever close.
        loader = getattr(self.workflow, "loader", None)
        requeued = bool(getattr(loader, "failed_minibatches", ()))
        self.has_data_for_slave = (
            requeued or min_open is None or
            self.epoch_number - min_open <= 1)
        if bool(self.complete) and self.is_master:
            # the master's workflow never runs: propagate the stop
            # decision straight to the job source (NoMoreJobs)
            self.workflow.stop()

    def _on_class_finished(self, klass, epoch=None, stats_set=None):
        epoch = self.epoch_number if epoch is None else epoch
        stats = (self.epoch_stats if stats_set is None else stats_set)[klass]
        if not stats["samples"]:
            return
        normalized = stats["metric"] / stats["samples"]
        stats["normalized"] = normalized
        if klass == VALIDATION or (klass == TRAIN and
                                   not self.class_lengths[VALIDATION]):
            self.improved <<= normalized < self.best_metric
            if bool(self.improved):
                self.best_metric = normalized
                self.best_epoch = epoch

    def _on_epoch_finished(self, epoch=None, stats_set=None):
        # on a master, self.epoch_number (linked from the loader) may
        # already have advanced past the epoch whose last update just
        # arrived — callers with better knowledge pass the true epoch
        epoch = self.epoch_number if epoch is None else epoch
        stats_set = self.epoch_stats if stats_set is None else stats_set
        summary = {CLASS_NAMES[i]: dict(stats_set[i])
                   for i in range(3) if self.class_lengths[i]}
        summary["epoch"] = epoch
        # insertion sort by epoch: out-of-order closes (async slaves)
        # must not scramble the history
        pos = len(self.epoch_history)
        while pos and self.epoch_history[pos - 1]["epoch"] > epoch:
            pos -= 1
        self.epoch_history.insert(pos, summary)
        self.info("epoch %d: %s", epoch, "  ".join(
            "%s %s=%.4f" % (CLASS_NAMES[i], self.METRIC_NAME,
                            stats_set[i].get("normalized", numpy.nan))
            for i in range(3) if self.class_lengths[i]))
        stop = False
        if self.testing:
            self.info("test pass complete")
            stop = True
        if self.max_epochs is not None and epoch + 1 >= self.max_epochs:
            self.info("stopping: max_epochs=%d reached", self.max_epochs)
            stop = True
        if epoch - self.best_epoch > self.fail_iterations:
            self.info("stopping: no improvement in %d epochs",
                      self.fail_iterations)
            stop = True
        if stop:
            self.complete <<= True
            self._stop_epoch_ = epoch
            # discard run-ahead buckets of epochs the stop cancels
            buckets = getattr(self, "_epoch_buckets_", None)
            if buckets:
                for run_ahead in [e for e in buckets if e > epoch]:
                    buckets.pop(run_ahead)
        self._reset_epoch()

    def get_metric_values(self):
        return {"best_%s" % self.METRIC_NAME: float(self.best_metric),
                "best_epoch": self.best_epoch,
                "epochs": len(self.epoch_history)}


class DecisionGD(DecisionBase):
    """Classification: metric = misclassification count / samples."""

    METRIC_NAME = "n_err_pt"

    def __init__(self, workflow, **kwargs):
        super(DecisionGD, self).__init__(workflow, **kwargs)
        self.demand("minibatch_n_err")

    def minibatch_metric(self):
        return float(self.minibatch_n_err)


class DecisionMSE(DecisionBase):
    """Regression/AE: metric = summed per-sample MSE."""

    METRIC_NAME = "rmse"

    def __init__(self, workflow, **kwargs):
        super(DecisionMSE, self).__init__(workflow, **kwargs)
        self.demand("minibatch_mse")

    def minibatch_metric(self):
        mse = self.minibatch_mse
        if hasattr(mse, "__len__"):
            return float(numpy.sum(
                numpy.asarray(mse)[:self.minibatch_size]))
        return float(mse) * self.minibatch_size

    def _on_class_finished(self, klass, epoch=None, stats_set=None):
        stats = (self.epoch_stats if stats_set is None else stats_set)[klass]
        if stats["samples"]:
            # report RMSE, compare on MSE (monotonic — same argmin)
            stats["metric_rmse"] = float(
                numpy.sqrt(stats["metric"] / stats["samples"]))
        super(DecisionMSE, self)._on_class_finished(
            klass, epoch=epoch, stats_set=stats_set)
