"""Decision units: epoch accounting + stop criterion + GD gating.

The Znicz Decision unit is the control heart of every reference
workflow: it accumulates per-class epoch statistics from the evaluator,
decides when training is complete (max epochs, or no validation
improvement for ``fail_iterations`` epochs), exposes ``gd_skip`` so
gradient units only run on TRAIN minibatches, and raises ``improved``
for the snapshotter. Topology contract (mirrors Znicz MnistWorkflow):

    repeater -> loader -> forwards... -> evaluator -> decision
    decision -> gd[n] -> ... -> gd[0] -> repeater
    end_point.link_from(decision); end_point.gate_block = ~complete
    gd[i].gate_skip = decision.gd_skip
"""

import numpy

from veles_tpu.loader.base import TRAIN, VALIDATION, CLASS_NAMES
from veles_tpu.mutable import Bool
from veles_tpu.result_provider import IResultProvider
from veles_tpu.units import Unit


class DecisionBase(Unit, IResultProvider):
    hide_from_registry = True
    view_group = "TRAINER"

    #: lower is better for these metrics
    METRIC_NAME = "n_err"

    def __init__(self, workflow, **kwargs):
        self.max_epochs = kwargs.pop("max_epochs", None)
        self.fail_iterations = kwargs.pop("fail_iterations", 100)
        super(DecisionBase, self).__init__(workflow, **kwargs)
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.gd_skip = Bool(False)
        self.epoch_stats = [dict() for _ in range(3)]
        self.epoch_history = []
        self.best_metric = numpy.inf
        self.best_epoch = -1
        self.demand("minibatch_class", "last_minibatch", "epoch_ended",
                    "epoch_number", "class_lengths", "minibatch_size")

    def initialize(self, **kwargs):
        self._reset_epoch()

    def _reset_epoch(self):
        for stats in self.epoch_stats:
            stats.clear()
            stats.update(samples=0, metric=0.0)

    # -- per-minibatch metric from the evaluator ---------------------------

    def minibatch_metric(self):
        """Metric value summed over this minibatch (lower = better)."""
        raise NotImplementedError

    def run(self):
        klass = self.minibatch_class
        self.gd_skip <<= (klass != TRAIN)
        stats = self.epoch_stats[klass]
        stats["samples"] += self.minibatch_size
        stats["metric"] += self.minibatch_metric()
        if bool(self.last_minibatch):
            self._on_class_finished(klass)
        if bool(self.epoch_ended):
            self._on_epoch_finished()

    def _on_class_finished(self, klass):
        stats = self.epoch_stats[klass]
        if not stats["samples"]:
            return
        normalized = stats["metric"] / stats["samples"]
        stats["normalized"] = normalized
        if klass == VALIDATION or (klass == TRAIN and
                                   not self.class_lengths[VALIDATION]):
            self.improved <<= normalized < self.best_metric
            if bool(self.improved):
                self.best_metric = normalized
                self.best_epoch = self.epoch_number

    def _on_epoch_finished(self):
        summary = {CLASS_NAMES[i]: dict(self.epoch_stats[i])
                   for i in range(3) if self.class_lengths[i]}
        summary["epoch"] = self.epoch_number
        self.epoch_history.append(summary)
        self.info("epoch %d: %s", self.epoch_number, "  ".join(
            "%s %s=%.4f" % (CLASS_NAMES[i], self.METRIC_NAME,
                            self.epoch_stats[i].get("normalized",
                                                    numpy.nan))
            for i in range(3) if self.class_lengths[i]))
        stop = False
        if self.max_epochs is not None and \
                self.epoch_number + 1 >= self.max_epochs:
            self.info("stopping: max_epochs=%d reached", self.max_epochs)
            stop = True
        if self.epoch_number - self.best_epoch > self.fail_iterations:
            self.info("stopping: no improvement in %d epochs",
                      self.fail_iterations)
            stop = True
        if stop:
            self.complete <<= True
        self._reset_epoch()

    def get_metric_values(self):
        return {"best_%s" % self.METRIC_NAME: float(self.best_metric),
                "best_epoch": self.best_epoch,
                "epochs": len(self.epoch_history)}


class DecisionGD(DecisionBase):
    """Classification: metric = misclassification count / samples."""

    METRIC_NAME = "n_err_pt"

    def __init__(self, workflow, **kwargs):
        super(DecisionGD, self).__init__(workflow, **kwargs)
        self.demand("minibatch_n_err")

    def minibatch_metric(self):
        return float(self.minibatch_n_err)


class DecisionMSE(DecisionBase):
    """Regression/AE: metric = summed per-sample MSE."""

    METRIC_NAME = "rmse"

    def __init__(self, workflow, **kwargs):
        super(DecisionMSE, self).__init__(workflow, **kwargs)
        self.demand("minibatch_mse")

    def minibatch_metric(self):
        mse = self.minibatch_mse
        if hasattr(mse, "__len__"):
            return float(numpy.sum(
                numpy.asarray(mse)[:self.minibatch_size]))
        return float(mse) * self.minibatch_size

    def _on_class_finished(self, klass):
        stats = self.epoch_stats[klass]
        if stats["samples"]:
            # report RMSE, compare on MSE (monotonic — same argmin)
            stats["metric_rmse"] = float(
                numpy.sqrt(stats["metric"] / stats["samples"]))
        super(DecisionMSE, self)._on_class_finished(klass)
