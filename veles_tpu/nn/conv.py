"""Convolutional forward units.

Znicz Conv (+Tanh/RELU/Sigmoid variants): NHWC layout (the TPU-native
layout — channels last rides the 128-lane dimension), weights HWIO,
lowered through ``lax.conv_general_dilated`` so XLA tiles it onto the
MXU. Supports stride, symmetric padding, and channel-preserving groups.
"""

import jax.lax
import jax.numpy as jnp
import numpy

from veles_tpu.nn.activation import get_activation
from veles_tpu.nn.base import ForwardBase
from veles_tpu.nn.precision import get_policy


class Conv(ForwardBase):
    """NHWC convolution: y = act(conv(x, W) + b)."""

    ACTIVATION = "linear"

    def __init__(self, workflow, n_kernels=None, kx=None, ky=None, **kwargs):
        if None in (n_kernels, kx, ky):
            raise ValueError("Conv needs n_kernels, kx, ky")
        self.n_kernels = n_kernels
        self.kx, self.ky = kx, ky
        self.sliding = tuple(kwargs.pop("sliding", (1, 1)))
        self.padding = kwargs.pop("padding", "VALID")
        self.activation_name = kwargs.pop("activation", self.ACTIVATION)
        super(Conv, self).__init__(workflow, **kwargs)

    def _channels(self, input_shape):
        if len(input_shape) == 3:
            return 1
        return input_shape[3]

    def weights_shape_for(self, input_shape):
        # HWIO
        return (self.ky, self.kx, self._channels(input_shape),
                self.n_kernels)

    def bias_shape_for(self, input_shape):
        return (self.n_kernels,)

    def _pad_pairs(self):
        if isinstance(self.padding, str):
            return self.padding
        if isinstance(self.padding, int):
            p = self.padding
            return ((p, p), (p, p))
        if len(self.padding) == 2:
            return ((self.padding[0], self.padding[0]),
                    (self.padding[1], self.padding[1]))
        # reference 4-tuple (left, top, right, bottom)
        left, top, right, bottom = self.padding
        return ((top, bottom), (left, right))

    def output_shape_for(self, input_shape):
        # abstract evaluation only: no compilation, no execution
        import jax
        x = jax.ShapeDtypeStruct((1,) + tuple(input_shape[1:]),
                                 jnp.float32)
        w = jax.ShapeDtypeStruct(self.weights_shape_for(input_shape),
                                 jnp.float32)
        y = jax.eval_shape(self.apply, {"weights": w}, x)
        return (input_shape[0],) + tuple(y.shape[1:])

    def apply(self, params, x):
        if x.ndim == 3:
            x = x[..., None]  # grayscale -> NHWC
        pol = get_policy()
        xc, wc = pol.cast_in(x, params["weights"])
        # no preferred_element_type: lax.conv's vjp rejects the widened
        # output dtype (cotangent f32 vs bf16 operands — unlike dot's).
        # The MXU still accumulates f32 internally; a narrow policy's
        # output pays ONE bf16 rounding at the conv boundary before the
        # upcast — the same magnitude of rounding the policy already
        # accepts at every cast_in
        y = jax.lax.conv_general_dilated(
            xc, wc,
            window_strides=(self.sliding[1], self.sliding[0]),
            padding=self._pad_pairs(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = y.astype(pol.accum_dtype)
        if "bias" in params:
            y = y + params["bias"]
        return pol.cast_out(get_activation(self.activation_name)(y))


class ConvTanh(Conv):
    ACTIVATION = "tanh"


class ConvRELU(Conv):
    ACTIVATION = "relu"


class ConvStrictRELU(Conv):
    ACTIVATION = "strict_relu"


class ConvSigmoid(Conv):
    ACTIVATION = "sigmoid"


class Deconv(ForwardBase):
    """Transposed convolution (Znicz Deconv, used by conv autoencoders)."""

    def __init__(self, workflow, n_kernels=None, kx=None, ky=None, **kwargs):
        if None in (n_kernels, kx, ky):
            raise ValueError("Deconv needs n_kernels, kx, ky")
        self.n_kernels = n_kernels  # = channels of the OUTPUT
        self.kx, self.ky = kx, ky
        self.sliding = tuple(kwargs.pop("sliding", (1, 1)))
        self.padding = kwargs.pop("padding", "VALID")
        kwargs.setdefault("include_bias", False)
        super(Deconv, self).__init__(workflow, **kwargs)

    def weights_shape_for(self, input_shape):
        return (self.ky, self.kx, self.n_kernels, input_shape[3]
                if len(input_shape) == 4 else 1)

    def bias_shape_for(self, input_shape):
        return (self.n_kernels,)

    def output_shape_for(self, input_shape):
        import jax
        x = jax.ShapeDtypeStruct((1,) + tuple(input_shape[1:]),
                                 jnp.float32)
        w = jax.ShapeDtypeStruct(self.weights_shape_for(input_shape),
                                 jnp.float32)
        y = jax.eval_shape(self.apply, {"weights": w}, x)
        return (input_shape[0],) + tuple(y.shape[1:])

    def apply(self, params, x):
        if x.ndim == 3:
            x = x[..., None]
        pol = get_policy()
        xc, wc = pol.cast_in(x, params["weights"])
        y = jax.lax.conv_transpose(
            xc, wc,
            strides=(self.sliding[1], self.sliding[0]),
            padding=self.padding if isinstance(self.padding, str)
            else [(p, p) for p in (self.padding, self.padding)]
            if isinstance(self.padding, int) else self.padding,
            dimension_numbers=("NHWC", "HWOI", "NHWC"))
        y = y.astype(pol.accum_dtype)
        if "bias" in params:
            y = y + params["bias"]
        return pol.cast_out(y)
