"""Convolutional forward units.

Znicz Conv (+Tanh/RELU/Sigmoid variants): NHWC layout (the TPU-native
layout — channels last rides the 128-lane dimension), weights HWIO,
lowered through ``lax.conv_general_dilated`` so XLA tiles it onto the
MXU. Supports stride, symmetric padding, and channel-preserving groups.
"""

import jax.lax
import jax.numpy as jnp
import numpy

from veles_tpu.nn.activation import get_activation
from veles_tpu.nn.base import ForwardBase
from veles_tpu.nn.precision import get_policy


class Conv(ForwardBase):
    """NHWC convolution: y = act(conv(x, W) + b)."""

    ACTIVATION = "linear"

    def __init__(self, workflow, n_kernels=None, kx=None, ky=None, **kwargs):
        if None in (n_kernels, kx, ky):
            raise ValueError("Conv needs n_kernels, kx, ky")
        self.n_kernels = n_kernels
        self.kx, self.ky = kx, ky
        self.sliding = tuple(kwargs.pop("sliding", (1, 1)))
        self.padding = kwargs.pop("padding", "VALID")
        self.activation_name = kwargs.pop("activation", self.ACTIVATION)
        #: space-to-depth execution (the classic TPU entry-conv trick):
        #: a large-stride conv over few channels (AlexNet conv1:
        #: 11x11 stride 4 over 3 channels) feeds the MXU a 3-deep
        #: reduction axis; rearranging stride x stride input patches
        #: into channels runs the SAME math (exact to float rounding,
        #: weights layout unchanged) as a stride-1 conv with
        #: stride^2 x channels depth — measured 5.56 -> 3.37 ms
        #: fwd+bwd at the conv1 bench shape (docs/PERF.md)
        self.space_to_depth = bool(kwargs.pop("space_to_depth", False))
        if self.space_to_depth:
            if self.sliding[0] != self.sliding[1] or self.sliding[0] < 2:
                raise ValueError(
                    "space_to_depth needs a square stride >= 2 "
                    "(got %r)" % (self.sliding,))
            if not (isinstance(self.padding, int) or
                    self.padding == "VALID"):
                raise ValueError(
                    "space_to_depth supports int or VALID padding "
                    "(got %r)" % (self.padding,))
        super(Conv, self).__init__(workflow, **kwargs)

    def _channels(self, input_shape):
        if len(input_shape) == 3:
            return 1
        return input_shape[3]

    def weights_shape_for(self, input_shape):
        # HWIO
        return (self.ky, self.kx, self._channels(input_shape),
                self.n_kernels)

    def bias_shape_for(self, input_shape):
        return (self.n_kernels,)

    def _pad_pairs(self):
        if isinstance(self.padding, str):
            return self.padding
        if isinstance(self.padding, int):
            p = self.padding
            return ((p, p), (p, p))
        if len(self.padding) == 2:
            return ((self.padding[0], self.padding[0]),
                    (self.padding[1], self.padding[1]))
        # reference 4-tuple (left, top, right, bottom)
        left, top, right, bottom = self.padding
        return ((top, bottom), (left, right))

    def output_shape_for(self, input_shape):
        # abstract evaluation only: no compilation, no execution
        import jax
        x = jax.ShapeDtypeStruct((1,) + tuple(input_shape[1:]),
                                 jnp.float32)
        w = jax.ShapeDtypeStruct(self.weights_shape_for(input_shape),
                                 jnp.float32)
        y = jax.eval_shape(self.apply, {"weights": w}, x)
        return (input_shape[0],) + tuple(y.shape[1:])

    def _s2d_geom(self, length, k):
        """(out, taps, rows, right_pad) of the patch-channel regroup
        along one spatial axis. ``right_pad`` can be negative when the
        strided conv drops trailing pixels — callers crop, not pad."""
        s = self.sliding[0]
        p = self.padding if isinstance(self.padding, int) else 0
        out = (length + 2 * p - k) // s + 1
        taps = -(-k // s)
        rows = out + taps - 1
        return out, taps, rows, s * rows - length - p

    def s2d_pack_input(self, x):
        """(n, h, w, c) -> (n, rows_y, rows_x, s*s*c) patch channels.

        Row-wise and linear, so it commutes with minibatch gathering
        and zero-masking — which is what lets a fullbatch dataset be
        packed ONCE at staging time (FusedTrainer) instead of per step.
        """
        if x.ndim == 3:
            x = x[..., None]
        s = self.sliding[0]
        p = self.padding if isinstance(self.padding, int) else 0
        n, h, wdt, c = x.shape
        _, _, rows_y, right_y = self._s2d_geom(h, self.ky)
        _, _, rows_x, right_x = self._s2d_geom(wdt, self.kx)
        # right can be NEGATIVE when the strided conv drops trailing
        # pixels (e.g. 17-wide input, kx=4, s=4, VALID): those pixels
        # are never read by any window, so cropping to s*rows before
        # the patch regroup is exact — and jnp.pad rejects negatives
        xp = jnp.pad(x, [(0, 0), (p, max(right_y, 0)),
                         (p, max(right_x, 0)), (0, 0)])
        xp = xp[:, :s * rows_y, :s * rows_x, :]
        return xp.reshape(n, rows_y, s, rows_x, s, c).transpose(
            0, 1, 3, 2, 4, 5).reshape(n, rows_y, rows_x, s * s * c)

    def s2d_packed_shape(self, input_shape):
        """Per-sample packed shape for a raw (h, w[, c]) sample shape."""
        h, wdt = input_shape[0], input_shape[1]
        c = input_shape[2] if len(input_shape) > 2 else 1
        s = self.sliding[0]
        _, _, rows_y, _ = self._s2d_geom(h, self.ky)
        _, _, rows_x, _ = self._s2d_geom(wdt, self.kx)
        return (rows_y, rows_x, s * s * c)

    def _s2d_pack_weights(self, w):
        """(ky, kx, c, o) -> (taps_y, taps_x, s*s*c, o): the kernel
        regrouped (zero-extended to whole taps) to match packed input."""
        s = self.sliding[0]
        _, taps_y, _, _ = self._s2d_geom(0, self.ky)
        _, taps_x, _, _ = self._s2d_geom(0, self.kx)
        c = w.shape[2]
        wp = jnp.pad(w, [(0, taps_y * s - self.ky),
                         (0, taps_x * s - self.kx), (0, 0), (0, 0)])
        return wp.reshape(taps_y, s, taps_x, s, c, -1).transpose(
            0, 2, 1, 3, 4, 5).reshape(taps_y, taps_x, s * s * c, -1)

    def _s2d_conv(self, x, w):
        """Equivalent stride-1 conv on stride x stride patch-channels.

        Exact restatement of the strided conv (same float math, the
        window sums just regroup): with a = s*da + r,
        y[i,j,o] = sum x[s*i + a - p] w[a] =
                   sum_{da,r} xs[i + da, (r, ...)] w2[da, (r, ...)]
        where xs packs each s-row block's rows into channels and w2 is
        the identically-regrouped (zero-extended) kernel."""
        return jax.lax.conv_general_dilated(
            self.s2d_pack_input(x), self._s2d_pack_weights(w),
            window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def apply_staged(self, params, xs):
        """Forward on input ALREADY in ``s2d_pack_input`` layout.

        The fused trainer packs the whole dataset once at staging and
        calls this for the entry conv, eliminating the per-step
        rearrange (docs/PERF.md: ~1.5 ms/step on the AlexNet flagship).
        Float math is identical to ``apply``."""
        pol = get_policy()
        xc, wc = pol.cast_in(xs, params["weights"])
        y = jax.lax.conv_general_dilated(
            xc, self._s2d_pack_weights(wc), window_strides=(1, 1),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = y.astype(pol.accum_dtype)
        if "bias" in params:
            y = y + params["bias"]
        return pol.cast_out(get_activation(self.activation_name)(y))

    def apply(self, params, x):
        if x.ndim == 3:
            x = x[..., None]  # grayscale -> NHWC
        pol = get_policy()
        xc, wc = pol.cast_in(x, params["weights"])
        # no preferred_element_type: lax.conv's vjp rejects the widened
        # output dtype (cotangent f32 vs bf16 operands — unlike dot's).
        # The MXU still accumulates f32 internally; a narrow policy's
        # output pays ONE bf16 rounding at the conv boundary before the
        # upcast — the same magnitude of rounding the policy already
        # accepts at every cast_in
        if getattr(self, "space_to_depth", False):
            y = self._s2d_conv(xc, wc)
        else:
            y = jax.lax.conv_general_dilated(
                xc, wc,
                window_strides=(self.sliding[1], self.sliding[0]),
                padding=self._pad_pairs(),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = y.astype(pol.accum_dtype)
        if "bias" in params:
            y = y + params["bias"]
        return pol.cast_out(get_activation(self.activation_name)(y))


class ConvTanh(Conv):
    ACTIVATION = "tanh"


class ConvRELU(Conv):
    ACTIVATION = "relu"


class ConvStrictRELU(Conv):
    ACTIVATION = "strict_relu"


class ConvSigmoid(Conv):
    ACTIVATION = "sigmoid"


class Deconv(ForwardBase):
    """Transposed convolution (Znicz Deconv, used by conv autoencoders)."""

    def __init__(self, workflow, n_kernels=None, kx=None, ky=None, **kwargs):
        if None in (n_kernels, kx, ky):
            raise ValueError("Deconv needs n_kernels, kx, ky")
        self.n_kernels = n_kernels  # = channels of the OUTPUT
        self.kx, self.ky = kx, ky
        self.sliding = tuple(kwargs.pop("sliding", (1, 1)))
        self.padding = kwargs.pop("padding", "VALID")
        kwargs.setdefault("include_bias", False)
        super(Deconv, self).__init__(workflow, **kwargs)

    def weights_shape_for(self, input_shape):
        return (self.ky, self.kx, self.n_kernels, input_shape[3]
                if len(input_shape) == 4 else 1)

    def bias_shape_for(self, input_shape):
        return (self.n_kernels,)

    def output_shape_for(self, input_shape):
        import jax
        x = jax.ShapeDtypeStruct((1,) + tuple(input_shape[1:]),
                                 jnp.float32)
        w = jax.ShapeDtypeStruct(self.weights_shape_for(input_shape),
                                 jnp.float32)
        y = jax.eval_shape(self.apply, {"weights": w}, x)
        return (input_shape[0],) + tuple(y.shape[1:])

    def apply(self, params, x):
        if x.ndim == 3:
            x = x[..., None]
        pol = get_policy()
        xc, wc = pol.cast_in(x, params["weights"])
        y = jax.lax.conv_transpose(
            xc, wc,
            strides=(self.sliding[1], self.sliding[0]),
            padding=self.padding if isinstance(self.padding, str)
            else [(p, p) for p in (self.padding, self.padding)]
            if isinstance(self.padding, int) else self.padding,
            dimension_numbers=("NHWC", "HWOI", "NHWC"))
        y = y.astype(pol.accum_dtype)
        if "bias" in params:
            y = y + params["bias"]
        return pol.cast_out(y)
