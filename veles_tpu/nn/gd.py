"""Gradient-descent (backward) units.

The Znicz GD family (GradientDescent, GDTanh, GDRELU, GDSigmoid,
GDSoftmax, GDConv, GDPooling, ...) hand-wrote every backward kernel. Here
ONE implementation serves them all: the paired forward unit's pure
``apply_for_grad`` is differentiated with ``jax.vjp``, the optimizer
rule updates the (shared) parameter Arrays in place, and ``err_input``
propagates to the previous layer's GD unit. The class aliases survive so
reference workflow topologies translate one-to-one.

For the softmax head the evaluator already supplies the gradient w.r.t.
the *logits* (see evaluator.py), so ``All2AllSoftmax.apply_for_grad``
returns logits and GDSoftmax is literally the base class.
"""

import jax
import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.nn.optim import get_solver


class GradientDescentBase(AcceleratedUnit):
    """Backward unit for any ForwardBase via jax.vjp."""

    hide_from_registry = True
    view_group = "TRAINER"

    def __init__(self, workflow, forward=None, **kwargs):
        self.learning_rate = kwargs.pop("learning_rate", 0.01)
        self.learning_rate_bias = kwargs.pop("learning_rate_bias", None)
        self.weights_decay = kwargs.pop("weights_decay", 0.0)
        self.gradient_moment = kwargs.pop("momentum",
                                          kwargs.pop("gradient_moment",
                                                     0.0))
        self.solver_name = kwargs.pop("solver", "sgd")
        self._solver_hp = dict(kwargs.pop("solver_hp", {}))
        self.need_err_input = kwargs.pop("need_err_input", True)
        super(GradientDescentBase, self).__init__(workflow, **kwargs)
        self.forward = forward
        self.err_output = None      # linked: next GD's err_input / evaluator
        self.err_input = Array()    # produced for the previous layer
        self.opt_state = None
        self.demand("err_output")

    @property
    def hyper(self):
        hp = {"learning_rate": self.learning_rate,
              "weight_decay": self.weights_decay,
              "momentum": self.gradient_moment}
        if self.learning_rate_bias is not None:
            hp["lr_overrides"] = {"bias": self.learning_rate_bias}
        hp.update(getattr(self, "_solver_hp", {}))
        return hp

    def initialize(self, device=None, **kwargs):
        if self.forward is None:
            raise ValueError("%s needs its paired forward unit" % self.name)
        super(GradientDescentBase, self).initialize(device=device, **kwargs)
        if self.need_err_input:
            in_mem = (self.forward.input.mem
                      if isinstance(self.forward.input, Array)
                      else self.forward.input)
            self.err_input.reset(numpy.zeros(in_mem.shape, numpy.float32))
            self.init_vectors(self.err_input)
        solver = get_solver(self.solver_name)
        params = {k: numpy.asarray(v.mem)
                  for k, v in self.forward.param_arrays().items()}
        if self.opt_state is None and params:
            import jax.numpy as jnp
            self.opt_state = jax.tree_util.tree_map(
                jnp.asarray, solver.init(
                    {k: jnp.asarray(v) for k, v in params.items()}))

    def _bwd_fn(self):
        """Builds the jitted (params, x, err_out, state, hp) -> ... fn."""
        fwd = self.forward
        solver = get_solver(self.solver_name)
        has_params = bool(fwd.param_arrays())

        def step(params, x, err_out, state, hp):
            def f(p, xin):
                return fwd.apply_for_grad(p, xin)
            _, vjp = jax.vjp(f, params, x)
            gparams, gx = vjp(err_out)
            if has_params:
                new_params, new_state = solver.update(params, gparams,
                                                      state, hp)
            else:
                new_params, new_state = params, state
            return new_params, gx, new_state

        return step

    def jax_run(self):
        fwd = self.forward
        self.unmap_vectors(self.err_output, fwd.weights, fwd.bias)
        params = fwd.param_values()
        # _input_devmem / place_for_grad: mesh-running forwards
        # (ring-attention units) re-place committed single-device
        # buffers so the jitted step sees one consistent device set
        x = fwd._input_devmem()
        err_out = (self.err_output.devmem
                   if isinstance(self.err_output, Array)
                   else self.err_output)
        err_out = fwd.place_for_grad(err_out)
        state = fwd.place_for_grad(self.opt_state or {})
        step = self.jit(self._get_step())
        new_params, gx, new_state = step(params, x, err_out,
                                         state, self.hyper)
        for k, arr in fwd.param_arrays().items():
            arr.assign_devmem(new_params[k])
        self.opt_state = new_state
        if self.need_err_input:
            self.err_input.assign_devmem(gx)

    def _get_step(self):
        if not hasattr(self, "_step_fn_") or self._step_fn_ is None:
            self._step_fn_ = self._bwd_fn()
        return self._step_fn_

    def init_unpickled(self):
        super(GradientDescentBase, self).init_unpickled()
        self._step_fn_ = None

    def numpy_run(self):
        self.jax_run()  # same pure math on host buffers

    # -- distribution (the Znicz GD protocol re-imagined): master sends
    # canonical weights with each job, the slave's local step produces a
    # delta that the master merges additively — a point-to-point
    # parameter-server exchange, exactly the reference's only training
    # parallelism (SURVEY.md §2.4; hooks at ``units.py:157-164``) -------

    def generate_data_for_slave(self, slave=None):
        params = {k: numpy.array(v.map_read())
                  for k, v in self.forward.param_arrays().items()}
        return params or None

    def apply_data_from_master(self, data):
        base = {}
        for k, value in (data or {}).items():
            target = self.forward.param_arrays()[k]
            mem = target.map_invalidate()
            mem[...] = value
            base[k] = value  # freshly unpickled: this frame owns it
        self._job_base_params_ = base

    def generate_data_for_master(self):
        base = getattr(self, "_job_base_params_", None) or {}
        out = {}
        for k, arr in self.forward.param_arrays().items():
            new = numpy.array(arr.map_read())
            out[k] = new - base[k] if k in base else new
        return out or None

    def apply_data_from_slave(self, data, slave=None):
        for k, delta in (data or {}).items():
            target = self.forward.param_arrays()[k]
            mem = target.map_write()
            mem += delta


# -- reference-parity aliases ------------------------------------------------

class GradientDescent(GradientDescentBase):
    """For All2All (linear)."""
    hide_from_registry = False


class GDTanh(GradientDescentBase):
    pass


class GDRELU(GradientDescentBase):
    pass


class GDStrictRELU(GradientDescentBase):
    pass


class GDSigmoid(GradientDescentBase):
    pass


class GDSoftmax(GradientDescentBase):
    """err_output is already d(loss)/d(logits) — see EvaluatorSoftmax."""


class GDConv(GradientDescentBase):
    pass


class GDPooling(GradientDescentBase):
    """No parameters: pure gradient routing through the pooling vjp."""


class GDActivation(GradientDescentBase):
    pass
