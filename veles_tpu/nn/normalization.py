"""Local response normalization (Znicz normalization.py — the AlexNet
cross-channel LRN).

Two formulations:

* **XLA slices** (the default): n shifted slices — n is tiny, XLA
  fuses them into the surrounding graph, and the generic vjp applies.
* **fused Pallas forward+backward** (:mod:`veles_tpu.ops.lrn`,
  ``VELES_LRN=pallas``): window sums as a banded matmul on the MXU,
  the vjp's only residual is ``x`` (denominator recomputed in VMEM).
  Kept as a measured NEGATIVE result: parity in isolation, −22%
  end-to-end because the opaque kernel blocks fusion (docs/PERF.md).
"""

import jax
import jax.numpy as jnp

from veles_tpu.nn.base import ForwardBase


def _lrn_slices(x, k=2.0, alpha=1e-4, beta=0.75, n=5):
    """XLA formulation: the channel-window sum as n shifted slices
    (generic-reducer reduce_window has no autodiff rule)."""
    sq = jnp.square(x)
    half = n // 2
    padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    channels = x.shape[-1]
    window = sum(
        jax.lax.slice_in_dim(padded, i, i + channels, axis=x.ndim - 1)
        for i in range(n))
    # plain pow: a beta=0.75 rsqrt(s)*sqrt(rsqrt(s)) specialization was
    # measured r4 at 12.69 vs 12.35 ms/step — the transcendental is NOT
    # the LRN cost (docs/PERF.md: the floor is structural traffic)
    return x / jnp.power(k + alpha * window, beta)


def lrn(x, k=2.0, alpha=1e-4, beta=0.75, n=5):
    """Cross-channel LRN over NHWC: AlexNet formula.

    The default stays on the XLA slices formulation EVERYWHERE — a
    measured decision, not a shortcut: the Pallas custom_vjp pair
    (:mod:`veles_tpu.ops.lrn`) reaches parity on isolated shapes but
    LOSES 22% end-to-end in the AlexNet fused step (9,660 -> 7,526
    samples/s, docs/PERF.md r3 ablation), because an opaque kernel cuts
    the fusion graph XLA otherwise builds around the LRN. Set
    ``VELES_LRN=pallas`` to re-run that ablation — the kernels' row
    blocking is now shape-tuned through the autotune cache
    (``lrn_fwd``/``lrn_bwd`` entries), so re-runs of the ablation pick
    each shape's measured best block instead of the fixed 512."""
    from veles_tpu.envknob import env_knob
    force = env_knob("VELES_LRN", "xla")
    on_tpu = jax.default_backend() == "tpu"
    if x.ndim == 4 and n % 2 == 1 and force == "pallas":
        from veles_tpu.ops.lrn import lrn_fused
        return lrn_fused(x, k, alpha, beta, n, interpret=not on_tpu)
    if force == "cumsum" and n % 2 == 1 and x.shape[-1] > n // 2:
        # same odd-n guard as the Pallas branch (even n is an
        # asymmetric window the symmetric cumsum form cannot express);
        # tiny channel counts fall back too
        return _lrn_cumsum(x, k, alpha, beta, n)
    return _lrn_slices(x, k, alpha, beta, n)


def _lrn_cumsum(x, k=2.0, alpha=1e-4, beta=0.75, n=5):
    """Prefix-sum formulation: window = cs[c+half] - cs[c-half-1] — one
    channel cumsum + a subtract instead of n shifted adds (backward is
    a reverse cumsum). Float rounding differs from the slices form by
    association only (1e-7 measured).

    Kept as the THIRD measured negative result for the LRN floor
    (``VELES_LRN=cumsum`` to re-run): 16.43 vs 12.35 ms/step on the
    staged AlexNet — a cumsum over the minor (lane) axis is a
    sequential scan on TPU, far worse than n fusable shifted adds.
    With Pallas fusion (−22%) and the pow specialization (flat) also
    ruled out, the slices form stands as measured-best (docs/PERF.md).
    """
    sq = jnp.square(x)
    cs = jnp.cumsum(sq, axis=-1)
    half = n // 2
    channels = x.shape[-1]
    if channels <= half:
        raise ValueError(
            "cumsum LRN needs channels (%d) > n//2 (%d) — the "
            "dispatcher falls back to slices below that" %
            (channels, half))
    upper = jnp.concatenate(
        [cs[..., half:],
         jnp.broadcast_to(cs[..., -1:], cs.shape[:-1] + (half,))], -1)
    lower = jnp.concatenate(
        [jnp.zeros_like(cs[..., :half + 1]),
         cs[..., :channels - half - 1]], -1)
    return x / jnp.power(k + alpha * (upper - lower), beta)


class LRNormalizerForward(ForwardBase):
    def __init__(self, workflow, k=2.0, alpha=1e-4, beta=0.75, n=5,
                 **kwargs):
        kwargs.setdefault("include_bias", False)
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.k, self.alpha, self.beta, self.n = k, alpha, beta, n

    @property
    def has_weights(self):
        return False

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def apply(self, params, x):
        return lrn(x, self.k, self.alpha, self.beta, self.n)
