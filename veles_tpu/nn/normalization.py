"""Local response normalization (Znicz normalization.py — the AlexNet
cross-channel LRN). Pure function, so the generic vjp backward applies.
"""

import jax
import jax.numpy as jnp

from veles_tpu.nn.base import ForwardBase


def lrn(x, k=2.0, alpha=1e-4, beta=0.75, n=5):
    """Cross-channel LRN over NHWC: AlexNet formula.

    The channel-window sum is n shifted slices (n is tiny, XLA fuses
    them) — generic-reducer reduce_window has no autodiff rule."""
    sq = jnp.square(x)
    half = n // 2
    padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    channels = x.shape[-1]
    window = sum(
        jax.lax.slice_in_dim(padded, i, i + channels, axis=x.ndim - 1)
        for i in range(n))
    return x / jnp.power(k + alpha * window, beta)


class LRNormalizerForward(ForwardBase):
    def __init__(self, workflow, k=2.0, alpha=1e-4, beta=0.75, n=5,
                 **kwargs):
        kwargs.setdefault("include_bias", False)
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.k, self.alpha, self.beta, self.n = k, alpha, beta, n

    @property
    def has_weights(self):
        return False

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def apply(self, params, x):
        return lrn(x, self.k, self.alpha, self.beta, self.n)
