"""Base classes for NN forward units.

A forward unit owns parameters (``weights``/``bias`` as
:class:`~veles_tpu.memory.Array`) and a **pure** ``apply(params, x)``.
Eager execution jits ``apply`` per static shape; the step compiler
(:mod:`veles_tpu.train`) reuses the same ``apply`` to build one fused
train step — the unit graph is the model *description*, the compiled
step is the model *execution* (the semantic-gap resolution flagged in
SURVEY.md §7 "hard parts").

Weight initialization follows the reference's filler contract
(``weights_stddev``-style uniform fill from the seeded PRNG registry) so
CPU/TPU runs starting from the same seed produce identical curves.
"""

import numpy

from veles_tpu import prng
from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array


class ForwardBase(AcceleratedUnit):
    """Base forward unit: input -> output through pure ``apply``."""

    hide_from_registry = True
    view_group = "WORKER"
    # weight init legitimately advances the global RNG stream — without
    # this, Unit._initialize_wrapped restores the stream and same-shape
    # layers would start bit-identical
    consumes_global_rng_on_init = True

    def __init__(self, workflow, **kwargs):
        self.include_bias = kwargs.pop("include_bias", True)
        self.weights_stddev = kwargs.pop("weights_stddev", None)
        self.bias_stddev = kwargs.pop("bias_stddev", None)
        self.weights_filling = kwargs.pop("weights_filling", "uniform")
        self.bias_filling = kwargs.pop("bias_filling", "uniform")
        self.rand_name = kwargs.pop("rand", "default")
        super(ForwardBase, self).__init__(workflow, **kwargs)
        self.input = None
        self.output = Array()
        self.weights = Array()
        self.bias = Array()
        self.demand("input")

    # -- to override -------------------------------------------------------

    @property
    def has_weights(self):
        return True

    def weights_shape_for(self, input_shape):
        raise NotImplementedError

    def bias_shape_for(self, input_shape):
        raise NotImplementedError

    def output_shape_for(self, input_shape):
        raise NotImplementedError

    def apply(self, params, x):
        """Pure function: params dict + input batch -> output batch."""
        raise NotImplementedError

    def apply_for_grad(self, params, x):
        """The function the paired GD unit differentiates. Defaults to
        :meth:`apply`; softmax heads return logits instead (the
        evaluator seeds the gradient w.r.t. logits)."""
        return self.apply(params, x)

    def _placement_mesh(self):
        """Mesh this unit's ``apply`` runs on, or None. Units whose
        forward is a shard_map (ring attention's seq mesh, MoE's expert
        mesh) return the attached mesh; everything that touches the
        compiled step — params, inputs, err_output, optimizer state —
        is then re-placed onto it (replicated), because a committed
        single-device buffer fails the shard_map's device-set check."""
        return None

    def place_for_grad(self, tree):
        """Re-place committed single-device arrays onto the unit's
        mesh, replicated — identity when no mesh is attached;
        uncommitted host arrays pass through untouched. The paired GD
        step routes err_output / optimizer state through here."""
        mesh = self._placement_mesh()
        if mesh is None:
            return tree
        import jax

        from veles_tpu.parallel.mesh import named_sharding
        repl = named_sharding(mesh)

        def place(v):
            return jax.device_put(v, repl) if hasattr(v, "sharding") \
                else v

        return jax.tree_util.tree_map(place, tree)

    # -- parameter handling ------------------------------------------------

    def fill_weights(self):
        rng = prng.get(self.rand_name)
        shape = self.weights.shape
        fan_in = int(numpy.prod(shape[:-1])) if len(shape) > 1 else shape[0]
        stddev = self.weights_stddev or 1.0 / numpy.sqrt(max(fan_in, 1))
        if self.weights_filling == "gaussian":
            rng.fill_normal(self.weights.mem, 0.0, stddev)
        else:
            rng.fill(self.weights.mem, -stddev, stddev)
        if self.include_bias and self.bias.mem is not None:
            bstd = self.bias_stddev or stddev
            if self.bias_filling == "gaussian":
                rng.fill_normal(self.bias.mem, 0.0, bstd)
            elif self.bias_filling == "constant":
                self.bias.mem[...] = bstd
            else:
                rng.fill(self.bias.mem, -bstd, bstd)

    def param_values(self):
        """Device-side parameter pytree for ``apply`` (re-placed onto
        the unit's mesh when one is attached)."""
        params = {}
        if self.has_weights:
            params["weights"] = self.weights.devmem
            if self.include_bias:
                params["bias"] = self.bias.devmem
        return self.place_for_grad(params)

    def param_arrays(self):
        out = {}
        if self.has_weights:
            out["weights"] = self.weights
            if self.include_bias:
                out["bias"] = self.bias
        return out

    @property
    def input_shape(self):
        mem = self.input.mem if isinstance(self.input, Array) else self.input
        return tuple(mem.shape)

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, device=None, **kwargs):
        super(ForwardBase, self).initialize(device=device, **kwargs)
        in_shape = self.input_shape
        dtype = numpy.float32
        if self.has_weights and self.weights.mem is None:
            self.weights.reset(numpy.zeros(self.weights_shape_for(in_shape),
                                           dtype))
            if self.include_bias:
                self.bias.reset(numpy.zeros(self.bias_shape_for(in_shape),
                                            dtype))
            self.fill_weights()
        out_shape = self.output_shape_for(in_shape)
        if self.output.mem is None or tuple(self.output.shape) != out_shape:
            self.output.reset(numpy.zeros(out_shape, dtype))
        self.init_vectors(self.input, self.output, self.weights, self.bias)

    # -- execution ---------------------------------------------------------

    def _input_devmem(self):
        return self.place_for_grad(
            self.input.devmem if isinstance(self.input, Array)
            else self.input)

    def jax_run(self):
        self.unmap_vectors(self.input, self.weights, self.bias)
        fwd = self.jit(self.apply)
        self.output.assign_devmem(fwd(self.param_values(),
                                      self._input_devmem()))

    def numpy_run(self):
        # the numpy pseudo-device evaluates the same pure function on
        # host buffers (jax-on-CPU under the hood): one math source
        params = {k: v.mem for k, v in self.param_arrays().items()}
        x = self.input.mem if isinstance(self.input, Array) else self.input
        self.output.map_invalidate()[...] = numpy.asarray(
            self.apply(params, x))
