"""Dropout units (Znicz dropout.py: DropoutForward/DropoutBackward).

The forward draws an inverted-dropout mask from the unit's deterministic
JAX key chain (so snapshots resume the exact stream — the reference kept
xorshift states for the same reason); the backward reuses the *stored*
mask, which is why these two override the generic vjp machinery.
"""

import jax.numpy as jnp
import numpy

from veles_tpu import prng
from veles_tpu.memory import Array
from veles_tpu.nn.base import ForwardBase
from veles_tpu.nn.gd import GradientDescentBase
from veles_tpu.ops.random import uniform


class DropoutForward(ForwardBase):
    """Inverted dropout: y = x * mask / (1 - p); identity when testing."""

    def __init__(self, workflow, dropout_ratio=0.5, **kwargs):
        kwargs.setdefault("include_bias", False)
        super(DropoutForward, self).__init__(workflow, **kwargs)
        self.dropout_ratio = dropout_ratio
        self.testing = False
        self.last_mask = None

    @property
    def has_weights(self):
        return False

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def apply(self, params, x):
        if self.testing or self.last_mask is None:
            return x
        return x * self.last_mask

    def apply_with_key(self, params, x, key):
        """Functional (key-driven) form for fused/pipelined trainers:
        the mask is drawn from ``key`` instead of the unit's stateful
        stream, so the same key reproduces the same mask anywhere in a
        jitted program (the hetero pipeline threads per-(stage,
        microbatch) keys through this — VERDICT r4 weak #4)."""
        if self.testing:
            return x
        keep = 1.0 - self.dropout_ratio
        u = uniform(key, tuple(x.shape))
        return x * (u < keep).astype(x.dtype) / keep

    def _draw_mask(self, shape):
        key = prng.get(self.rand_name).jax_key()
        keep = 1.0 - self.dropout_ratio
        u = uniform(key, tuple(shape))
        return (u < keep).astype(jnp.float32) / keep

    def jax_run(self):
        x = self._input_devmem()
        if self.testing:
            self.last_mask = None
            self.output.assign_devmem(x)
            return
        self.last_mask = self._draw_mask(x.shape)
        self.output.assign_devmem(x * self.last_mask)

    def numpy_run(self):
        x = self.input.mem if isinstance(self.input, Array) else self.input
        if self.testing:
            self.last_mask = None
            self.output.map_invalidate()[...] = x
            return
        self.last_mask = numpy.asarray(self._draw_mask(x.shape))
        self.output.map_invalidate()[...] = x * self.last_mask


class DropoutBackward(GradientDescentBase):
    """err_input = err_output * stored forward mask.

    NOT the generic vjp path: the mask changes every forward run, so it
    must be read at run time, never baked into a jitted closure."""

    def jax_run(self):
        fwd = self.forward
        err_out = (self.err_output.devmem
                   if isinstance(self.err_output, Array)
                   else self.err_output)
        if fwd.last_mask is None:
            self.err_input.assign_devmem(err_out)
        else:
            self.err_input.assign_devmem(err_out * fwd.last_mask)

    numpy_run = jax_run
