"""Mixture-of-experts FFN unit (``{"type": "moe"}`` layer).

Wraps :func:`veles_tpu.parallel.ep.moe_ffn` the way the attention unit
wraps ring attention: a plain ForwardBase whose ``apply`` is pure, so
the fused step compiler, the eager scheduler, and the generic vjp GD
unit all drive it unchanged. Without a mesh it computes the dense
single-device math; ``use_experts(mesh)`` switches to the
expert-parallel all_to_all schedule (transient state — reattach after
snapshot resume, like ``MultiHeadAttentionForward.use_ring``).

The 2015 reference predates MoE; this extends the Znicz layer family
per the task brief's first-class-parallelism requirement.
"""

import numpy

from veles_tpu import prng
from veles_tpu.memory import Array
from veles_tpu.nn.base import ForwardBase


class MoEForward(ForwardBase):
    """Switch-style top-1 MoE FFN over (batch, seq, dim) or (n, dim).

    Parameters: ``weights`` is the ROUTER (dim, n_experts) — reusing
    the base class's allocation/filling — plus per-expert ``up``
    (E, dim, hidden) and ``down`` (E, hidden, dim) stacks.
    """

    def __init__(self, workflow, n_experts=8, hidden=None,
                 capacity_factor=1.25, residual=True,
                 aux_loss_weight=0.0, **kwargs):
        kwargs.setdefault("include_bias", False)
        super(MoEForward, self).__init__(workflow, **kwargs)
        self.n_experts = int(n_experts)
        self.hidden = hidden  # default: 4 * dim, set at initialize
        self.capacity_factor = float(capacity_factor)
        self.residual = residual
        #: Switch load-balancing aux-loss weight, added to the FUSED
        #: training loss (opt-in: 0.0 keeps fused == eager numerics)
        self.aux_loss_weight = float(aux_loss_weight)
        self.up = Array()
        self.down = Array()
        self._ep_mesh_ = None
        self._ep_axis_ = "expert"

    def use_experts(self, mesh, axis="expert"):
        """Attach an expert mesh: apply() switches to the all_to_all
        expert-parallel schedule (per-shard capacity semantics)."""
        if mesh.shape[axis] != self.n_experts:
            raise ValueError(
                "%d experts cannot shard over a %d-wide %r axis" %
                (self.n_experts, mesh.shape[axis], axis))
        self._ep_mesh_ = mesh
        self._ep_axis_ = axis
        return self

    def init_unpickled(self):
        super(MoEForward, self).init_unpickled()
        self._ep_mesh_ = None
        self._ep_axis_ = "expert"

    def _placement_mesh(self):
        # base place_for_grad/param_values/_input_devmem re-place every
        # committed buffer onto the expert mesh (the all_to_all
        # shard_map rejects device-set mismatches otherwise)
        return self._ep_mesh_

    def weights_shape_for(self, input_shape):
        return (input_shape[-1], self.n_experts)

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def initialize(self, device=None, **kwargs):
        super(MoEForward, self).initialize(device=device, **kwargs)
        dim = self.input_shape[-1]
        if self.hidden is None:
            self.hidden = 4 * dim
        if self.up.mem is None:
            rng = prng.get(self.rand_name)
            stddev = 1.0 / numpy.sqrt(dim)
            self.up.reset(numpy.zeros(
                (self.n_experts, dim, self.hidden), numpy.float32))
            rng.fill(self.up.mem, -stddev, stddev)
            stddev = 1.0 / numpy.sqrt(self.hidden)
            self.down.reset(numpy.zeros(
                (self.n_experts, self.hidden, dim), numpy.float32))
            rng.fill(self.down.mem, -stddev, stddev)
        self.init_vectors(self.up, self.down)

    def param_arrays(self):
        out = super(MoEForward, self).param_arrays()
        out["up"] = self.up
        out["down"] = self.down
        return out

    def param_values(self):
        out = super(MoEForward, self).param_values()
        out.update(self.place_for_grad({"up": self.up.devmem,
                                        "down": self.down.devmem}))
        return out

    def apply(self, params, x):
        from veles_tpu.parallel.ep import moe_ffn, moe_ffn_reference

        if self._ep_mesh_ is not None:
            tokens = x.reshape(-1, x.shape[-1])
            y = moe_ffn(tokens, params["weights"], params["up"],
                        params["down"], self._ep_mesh_, self._ep_axis_,
                        capacity_factor=self.capacity_factor
                        ).reshape(x.shape)
        else:
            # dense path: capacity pools PER SAMPLE, so inference is
            # batch-composition-independent (the same sample routes
            # identically whatever it shares a batch with) — matching
            # the native runtime exactly. Consequence: on 2D (n, dim)
            # inputs every sample is a single token and capacity
            # (>= 1) never drops anything — deliberate; capacity is a
            # sequence-length concept. The expert-parallel path above
            # pools per device shard instead (the Switch training
            # contract).
            import jax

            per_sample = x.reshape(x.shape[0], -1, x.shape[-1])
            y = jax.vmap(lambda s: moe_ffn_reference(
                s, params["weights"], params["up"], params["down"],
                self.n_experts, capacity_factor=self.capacity_factor,
                n_shards=1))(per_sample).reshape(x.shape)
        if self.residual:
            y = y + x
        return y.astype(x.dtype)

    def aux_loss(self, params, x, valid=None):
        """weight * Switch load-balance loss over this batch's router
        probabilities — the FusedTrainer adds it to the training loss
        when ``aux_loss_weight`` > 0. Router math identical to the
        dispatch path, so the nudged distribution is the served one;
        ``valid`` (per-SAMPLE mask) keeps a tail batch's zero padding
        rows out of the balance statistics."""
        import jax
        import jax.numpy as jnp

        from veles_tpu.parallel.ep import load_balance_loss
        tokens = x.reshape(-1, x.shape[-1])
        probs = jax.nn.softmax(tokens @ params["weights"], axis=-1)
        weights = None
        if valid is not None:
            per_sample = tokens.shape[0] // x.shape[0]
            weights = jnp.repeat(valid.astype(probs.dtype), per_sample)
        return self.aux_loss_weight * load_balance_loss(probs, weights)
