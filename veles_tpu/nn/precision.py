"""Mixed-precision policy for the NN compute path.

The reference exposed ``--precision-level`` 0/1/2 to trade GEMM speed
against summation accuracy on GPUs (``veles/config.py``,
``ocl/gemm.cl``); on TPU the equivalent lever points the other way:
the MXU natively multiplies bfloat16 with float32 accumulation, so the
policy here selects the COMPUTE dtype while parameters and accumulation
stay float32 — the standard TPU mixed-precision recipe.

Policies (select with ``--precision`` / ``VELES_PRECISION`` /
``root.common.engine.precision``):

* ``float32``        — everything f32 (default; bit-stable baseline);
* ``bfloat16_mixed`` — activations/weights cast to bf16 at each
  matmul/conv, accumulation and stored parameters f32. Halves the HBM
  traffic of the bandwidth-bound layers and engages the MXU's native
  bf16 path; solver updates still see f32 gradients (the cast's vjp
  casts back);
* ``bfloat16``       — activations stay bf16 between layers too (most
  aggressive; evaluator losses still reduce in f32).

The policy is read at TRACE time: changing it invalidates jit caches
naturally (the dtypes in the traced program change), no manual flush
needed — but a FusedTrainer built under one policy keeps it for its
lifetime, matching how the reference pinned precision per run.
"""

import jax.numpy as jnp

from veles_tpu.cmdline import CommandLineArgumentsRegistry
from veles_tpu.config import root
from veles_tpu.envknob import env_knob


class Policy(object):
    """(compute, accum, keep) dtypes: inputs cast to ``compute``,
    matmul/conv accumulate in ``accum``, layer outputs cast to
    ``keep`` (None = leave at accum dtype)."""

    def __init__(self, name, compute, accum, keep):
        self.name = name
        self.compute_dtype = compute
        self.accum_dtype = accum
        self.keep_dtype = keep

    def cast_in(self, *arrays):
        """Cast matmul/conv operands to the compute dtype."""
        out = tuple(a.astype(self.compute_dtype) if a is not None else None
                    for a in arrays)
        return out if len(out) > 1 else out[0]

    def cast_out(self, y):
        """Dtype a layer hands to the NEXT layer."""
        if self.keep_dtype is not None and y.dtype != self.keep_dtype:
            return y.astype(self.keep_dtype)
        return y


POLICIES = {
    "float32": Policy("float32", jnp.float32, jnp.float32, jnp.float32),
    "bfloat16_mixed": Policy("bfloat16_mixed", jnp.bfloat16, jnp.float32,
                             jnp.float32),
    "bfloat16": Policy("bfloat16", jnp.bfloat16, jnp.float32,
                       jnp.bfloat16),
}

_forced = None


def get_policy():
    """Resolve the active policy: explicit ``set_policy`` > env var >
    config tree > float32."""
    if _forced is not None:
        return _forced
    name = env_knob("VELES_PRECISION") or \
        root.common.engine.get("precision", "float32")
    try:
        return POLICIES[name]
    except KeyError:
        raise ValueError("unknown precision policy %r (have %s)" %
                         (name, sorted(POLICIES)))


def set_policy(name):
    """Pin the process-wide policy (None = back to config/env)."""
    global _forced
    _forced = None if name is None else POLICIES[name]


class _Args(metaclass=CommandLineArgumentsRegistry):
    @staticmethod
    def init_parser(parser, **kwargs):
        parser.add_argument(
            "--precision", default=None, choices=sorted(POLICIES),
            help="NN compute precision policy (default float32; "
                 "bfloat16_mixed = bf16 MXU math, f32 params/accum)")
        return parser
