"""Kohonen self-organizing map units (the reference's Kohonen sample —
``manualrst_veles_algorithms.rst`` and ``.coveragerc:51-66``).

Forward: winner index per sample (argmin distance to codebook).
Trainer: classic SOM update with a Gaussian neighborhood over the 2-D
grid and decaying radius/learning rate — expressed as one jitted batch
update (winner search + neighborhood-weighted pull in a single XLA
computation) instead of the reference's per-sample kernel loop.
"""

import functools

import jax
import jax.numpy as jnp
import numpy

from veles_tpu import prng
from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array


def _make_grid(sx, sy):
    """(sx*sy, 2) float32 unit-grid coordinates — the ONE layout shared
    by the trainer's neighborhood and som_quality's adjacency (a
    divergence here would silently break the topographic error)."""
    gx, gy = numpy.meshgrid(numpy.arange(sx), numpy.arange(sy))
    return numpy.stack([gx.ravel(), gy.ravel()],
                       axis=1).astype(numpy.float32)


@jax.jit
def _som_quality(codebook, grid, x):
    dots = jnp.dot(x, codebook.T, preferred_element_type=jnp.float32)
    c2 = jnp.sum(jnp.square(codebook), axis=1)
    x2 = jnp.sum(jnp.square(x), axis=1)
    d2 = jnp.maximum(x2[:, None] + c2[None, :] - 2.0 * dots, 0.0)
    _, best2 = jax.lax.top_k(-d2, 2)              # (batch, 2) BMU pair
    qe = jnp.mean(jnp.sqrt(jnp.take_along_axis(
        d2, best2[:, :1], axis=1)))
    p1 = jnp.take(grid, best2[:, 0], axis=0)
    p2 = jnp.take(grid, best2[:, 1], axis=0)
    cheb = jnp.max(jnp.abs(p1 - p2), axis=1)
    te = jnp.mean((cheb > 1.0).astype(jnp.float32))
    return qe, te


def som_quality(weights, sx, sy, data):
    """Standard SOM quality metrics (docs/PARITY_RUNS.md config 4 bar).

    * quantization error — mean Euclidean distance from each sample to
      its best-matching unit's codebook vector;
    * topographic error — fraction of samples whose first and second
      BMUs are NOT 8-neighbourhood-adjacent on the sx × sy grid (map
      topology preservation).

    The reference published no Kohonen quality number
    (``manualrst_veles_algorithms.rst`` Kohonen section lists status
    only), so these two classic metrics define the tracked bar.
    """
    grid = jnp.asarray(_make_grid(sx, sy))
    x = jnp.asarray(numpy.asarray(data, numpy.float32).reshape(
        len(data), -1))
    qe, te = _som_quality(jnp.asarray(weights), grid, x)
    return {"quantization_error": float(qe),
            "topographic_error": float(te)}


@functools.partial(jax.jit, static_argnames=())
def _winners(codebook, x):
    # pairwise squared distances: |c|^2 - 2 x.c  (|x|^2 constant per row)
    dots = jnp.dot(x, codebook.T, preferred_element_type=jnp.float32)
    c2 = jnp.sum(jnp.square(codebook), axis=1)
    return jnp.argmin(c2[None, :] - 2.0 * dots, axis=1)


@jax.jit
def _som_update(codebook, x, grid, sigma, lr):
    win = _winners(codebook, x)                       # (batch,)
    win_pos = jnp.take(grid, win, axis=0)             # (batch, 2)
    d2 = jnp.sum(jnp.square(grid[None, :, :] -
                            win_pos[:, None, :]), axis=2)
    h = jnp.exp(-d2 / (2.0 * sigma * sigma))          # (batch, units)
    num = jnp.dot(h.T, x, preferred_element_type=jnp.float32)
    den = jnp.sum(h, axis=0)[:, None]
    delta = num - den * codebook
    return codebook + lr * delta / x.shape[0], win


class KohonenForward(AcceleratedUnit):
    """Maps each input sample to its best-matching unit index."""

    def __init__(self, workflow, **kwargs):
        super(KohonenForward, self).__init__(workflow, **kwargs)
        self.input = None
        self.weights = None  # linked from the trainer
        self.output = Array()
        self.demand("input", "weights")

    def initialize(self, device=None, **kwargs):
        super(KohonenForward, self).initialize(device=device, **kwargs)
        batch = (self.input.shape if isinstance(self.input, Array)
                 else self.input.shape)[0]
        self.output.reset(numpy.zeros(batch, numpy.int32))

    def jax_run(self):
        x = (self.input.devmem if isinstance(self.input, Array)
             else self.input)
        w = (self.weights.devmem if isinstance(self.weights, Array)
             else self.weights)
        batch = x.shape[0]
        self.output.assign_devmem(_winners(w, x.reshape(batch, -1)))

    numpy_run = jax_run


class KohonenTrainer(AcceleratedUnit):
    """SOM codebook trainer over an sx × sy grid."""

    consumes_global_rng_on_init = True  # codebook init advances the stream

    def __init__(self, workflow, sx=8, sy=8, **kwargs):
        self.sx, self.sy = sx, sy
        self.sigma0 = kwargs.pop("sigma", max(sx, sy) / 2.0)
        self.learning_rate = kwargs.pop("learning_rate", 0.5)
        self.decay = kwargs.pop("decay", 0.005)
        self.rand_name = kwargs.pop("rand", "default")
        super(KohonenTrainer, self).__init__(workflow, **kwargs)
        self.input = None
        self.weights = Array()
        self.winners = Array()
        self.time = 0
        self.demand("input")

    @property
    def neurons_number(self):
        return self.sx * self.sy

    def initialize(self, device=None, **kwargs):
        super(KohonenTrainer, self).initialize(device=device, **kwargs)
        mem = (self.input.mem if isinstance(self.input, Array)
               else self.input)
        features = int(numpy.prod(mem.shape[1:]))
        if self.weights.mem is None:
            w = numpy.zeros((self.neurons_number, features), numpy.float32)
            prng.get(self.rand_name).fill(w, -0.1, 0.1)
            self.weights.reset(w)
        self._grid = _make_grid(self.sx, self.sy)
        self.winners.reset(numpy.zeros(mem.shape[0], numpy.int32))
        self.init_vectors(self.weights, self.winners)

    def _schedule(self):
        t = self.time
        sigma = max(self.sigma0 * numpy.exp(-self.decay * t), 0.5)
        lr = max(self.learning_rate * numpy.exp(-self.decay * t), 0.01)
        return numpy.float32(sigma), numpy.float32(lr)

    def jax_run(self):
        x = (self.input.devmem if isinstance(self.input, Array)
             else self.input)
        batch = x.shape[0]
        sigma, lr = self._schedule()
        new_w, win = _som_update(self.weights.devmem,
                                 x.reshape(batch, -1),
                                 jnp.asarray(self._grid), sigma, lr)
        self.weights.assign_devmem(new_w)
        self.winners.assign_devmem(win)
        self.time += 1

    numpy_run = jax_run
