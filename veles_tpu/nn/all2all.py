"""Fully-connected (All2All) forward units.

The Znicz All2All family: linear, Tanh (LeCun-scaled), RELU (softplus),
Sigmoid, Softmax heads over ``y = act(x @ W + b)``. Input is flattened
to (batch, features); weights are stored (in_features, out_features) so
the matmul lands on the MXU untransposed.

When the autotuner (:mod:`veles_tpu.ops.autotune`) holds a measured
winner for a layer's ``(M, N, K, dtype, activation)``, the forward
runs :func:`veles_tpu.ops.gemm.fused_linear` — the GEMM epilogue
absorbs bias + activation while the output block is still in VMEM
instead of a separate HBM pass, which is where the flagship profile
showed the MXU idling (docs/PERF.md r5). ``VELES_AUTOTUNE=off`` (or
any cache miss) keeps the exact XLA chain below.
"""

import jax.numpy as jnp
import numpy

from veles_tpu.nn.activation import get_activation
from veles_tpu.nn.base import ForwardBase
from veles_tpu.nn.precision import get_policy


class All2All(ForwardBase):
    """y = activation(flatten(x) @ W + b)."""

    ACTIVATION = "linear"

    def __init__(self, workflow, output_sample_shape=None, **kwargs):
        if output_sample_shape is None:
            output_sample_shape = kwargs.pop("output_shape", None)
        if output_sample_shape is None:
            raise ValueError("All2All needs output_sample_shape")
        if isinstance(output_sample_shape, int):
            output_sample_shape = (output_sample_shape,)
        self.output_sample_shape = tuple(output_sample_shape)
        self.activation_name = kwargs.pop("activation", self.ACTIVATION)
        super(All2All, self).__init__(workflow, **kwargs)

    @property
    def neurons_number(self):
        return int(numpy.prod(self.output_sample_shape))

    def weights_shape_for(self, input_shape):
        in_features = int(numpy.prod(input_shape[1:]))
        return (in_features, self.neurons_number)

    def bias_shape_for(self, input_shape):
        return (self.neurons_number,)

    def output_shape_for(self, input_shape):
        return (input_shape[0],) + self.output_sample_shape

    def apply(self, params, x):
        batch = x.shape[0]
        pol = get_policy()
        xc, wc = pol.cast_in(x.reshape(batch, -1), params["weights"])
        fused = self._fused_apply(pol, xc, wc, params)
        if fused is not None:
            return fused.reshape((batch,) + self.output_sample_shape)
        # preferred_element_type keeps the MXU's f32 accumulator all
        # the way to the output (uniform operand dtypes, so the dot vjp
        # accepts it — unlike conv's)
        y = jnp.dot(xc, wc, preferred_element_type=pol.accum_dtype)
        if "bias" in params:
            y = y + params["bias"]
        y = pol.cast_out(get_activation(self.activation_name)(y))
        return y.reshape((batch,) + self.output_sample_shape)

    def _fused_apply(self, pol, xc, wc, params):
        """The autotuned GEMM-epilogue seam: when the per-shape cache
        says the fused Pallas kernel (bias + activation absorbed into
        the GEMM's output step) wins, use it — its custom VJP routes
        the dgrad/wgrad dots back through the same shape-aware
        dispatch. Returns None (→ the XLA chain, today's exact path)
        when the tuner is off, the shape is untuned/unfused-worthy, or
        the layer has no bias/fusable activation."""
        from veles_tpu.ops import autotune
        from veles_tpu.ops.gemm import (
            fusable_activation, fused_linear, fused_linear_cfg)
        bias = params.get("bias")
        if bias is None or not fusable_activation(self.activation_name):
            return None
        out_dtype = pol.keep_dtype or pol.accum_dtype
        impl, cfg = autotune.linear_plan(
            xc.shape[0], wc.shape[1], xc.shape[1], str(xc.dtype),
            self.activation_name, str(jnp.dtype(out_dtype)))
        if impl != "pallas" or not cfg:
            return None
        return fused_linear(
            xc, wc, bias.astype(jnp.float32), self.activation_name,
            out_dtype, fused_linear_cfg(cfg))


class All2AllTanh(All2All):
    ACTIVATION = "tanh"


class All2AllRELU(All2All):
    ACTIVATION = "relu"


class All2AllStrictRELU(All2All):
    ACTIVATION = "strict_relu"


class All2AllSigmoid(All2All):
    ACTIVATION = "sigmoid"


class All2AllSoftmax(All2All):
    """Softmax head: output is the probability simplex; ``max_idx`` is
    kept for the evaluator (the reference stores it device-side)."""

    ACTIVATION = "linear"

    def _logits(self, params, x):
        """Head logits, always float32 (softmax/CE numerics need it
        regardless of the compute policy)."""
        batch = x.shape[0]
        pol = get_policy()
        xc, wc = pol.cast_in(x.reshape(batch, -1), params["weights"])
        logits = jnp.dot(xc, wc, preferred_element_type=jnp.float32)
        if "bias" in params:
            logits = logits + params["bias"]
        return logits

    def apply(self, params, x):
        batch = x.shape[0]
        logits = self._logits(params, x)
        # max-subtracted for stability, matches reference's softmax kernel
        z = logits - jnp.max(logits, axis=1, keepdims=True)
        e = jnp.exp(z)
        return (e / jnp.sum(e, axis=1, keepdims=True)).reshape(
            (batch,) + self.output_sample_shape)

    def apply_for_grad(self, params, x):
        """Logits only: EvaluatorSoftmax's err_output is already the
        gradient w.r.t. logits (softmax+CE fused), so GDSoftmax must not
        differentiate through the softmax again."""
        batch = x.shape[0]
        return self._logits(params, x).reshape(
            (batch,) + self.output_sample_shape)
