"""Pooling units (Znicz MaxPooling / AvgPooling / MaxAbsPooling +
Depooling for autoencoders), lowered via ``lax.reduce_window``. The
reference records ``input_offset`` (argmax positions) for the backward
pass; here ``jax.vjp`` of the same forward routes gradients to the max
positions automatically, so no offset bookkeeping survives.
"""

import jax.lax
import jax.numpy as jnp
import numpy

from veles_tpu.nn.base import ForwardBase


class PoolingBase(ForwardBase):
    def __init__(self, workflow, kx=2, ky=2, **kwargs):
        self.kx, self.ky = kx, ky
        sliding = kwargs.pop("sliding", None)
        self.sliding = tuple(sliding) if sliding else (kx, ky)
        kwargs.setdefault("include_bias", False)
        super(PoolingBase, self).__init__(workflow, **kwargs)

    @property
    def has_weights(self):
        return False

    def output_shape_for(self, input_shape):
        import jax
        x = jax.ShapeDtypeStruct((1,) + tuple(input_shape[1:]),
                                 jnp.float32)
        y = jax.eval_shape(self.apply, {}, x)
        return (input_shape[0],) + tuple(y.shape[1:])

    def _window(self):
        return (1, self.ky, self.kx, 1)

    def _strides(self):
        return (1, self.sliding[1], self.sliding[0], 1)


class MaxPooling(PoolingBase):
    def apply(self, params, x):
        if x.ndim == 3:
            x = x[..., None]
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, self._window(), self._strides(),
            "VALID")


class MaxAbsPooling(PoolingBase):
    """Picks the value with max |value| in each window (Znicz variant).

    Expressed through max/min windows (both autodiff-supported) instead
    of a custom reducer, which XLA cannot differentiate."""

    def apply(self, params, x):
        if x.ndim == 3:
            x = x[..., None]
        mx = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, self._window(), self._strides(),
            "VALID")
        mn = jax.lax.reduce_window(
            x, jnp.inf, jax.lax.min, self._window(), self._strides(),
            "VALID")
        return jnp.where(mx >= -mn, mx, mn)


class AvgPooling(PoolingBase):
    """Sum-window as a depthwise ones-kernel conv: differentiable and
    MXU-lowerable (generic-reducer reduce_window has no vjp)."""

    def apply(self, params, x):
        if x.ndim == 3:
            x = x[..., None]
        channels = x.shape[-1]
        kernel = jnp.ones((self.ky, self.kx, 1, channels), x.dtype)
        # no preferred_element_type: lax.conv's vjp rejects a widened
        # output dtype (f32 cotangent conv'd against bf16 operands
        # crashes the backward pass under the bfloat16 policy — same
        # constraint as nn/conv.py apply; found by bench_all r5). The
        # window sum of <=few dozen elements loses at most one bf16
        # rounding, which the policy already accepts per layer.
        summed = jax.lax.conv_general_dilated(
            x, kernel, window_strides=(self.sliding[1], self.sliding[0]),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=channels)
        return summed / jnp.asarray(self.kx * self.ky, x.dtype)


class Depooling(PoolingBase):
    """Nearest-neighbor upsampling — the AE inverse of AvgPooling."""

    def apply(self, params, x):
        if x.ndim == 3:
            x = x[..., None]
        x = jnp.repeat(x, self.ky, axis=1)
        return jnp.repeat(x, self.kx, axis=2)
