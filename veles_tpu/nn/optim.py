"""Parameter update rules (the reference GD units' "solvers": plain SGD
with momentum/weight-decay, AdaGrad, AdaDelta — ``manualrst_veles_
algorithms.rst`` Extras — plus Adam, which the 2015 reference predates).

All rules are pure functions over flat ``{name: array}`` dicts so they
jit into the fused train step unchanged:
``init(params) -> state``;
``update(params, grads, state, hp) -> (new_params, new_state)``.
"""

import jax.numpy as jnp


def _zeros_like(params):
    return {k: jnp.zeros_like(v) for k, v in params.items()}


def _lr_for(hp, key):
    """Per-parameter learning rate: Znicz GD exposes a separate
    ``learning_rate_bias``; generalized as hp['lr_overrides'][name]."""
    overrides = hp.get("lr_overrides")
    if overrides and key in overrides and overrides[key] is not None:
        return overrides[key]
    return hp["learning_rate"]


class Solver(object):
    name = None

    @staticmethod
    def init(params):
        raise NotImplementedError

    @staticmethod
    def update(params, grads, state, hp):
        raise NotImplementedError


class SGD(Solver):
    """lr * grad with classical momentum and L2 weight decay —
    the reference's default GradientDescent rule.

    ``hp['lr_decay']`` (optional, default 1.0) multiplies the learning
    rate by ``lr_decay**step`` — the classic exponential schedule; it
    rides a step counter in the solver state so the whole schedule jits
    into one compiled train segment (no per-epoch recompiles)."""

    name = "sgd"

    @staticmethod
    def init(params):
        return {"velocity": _zeros_like(params),
                "step": jnp.zeros((), jnp.float32)}

    @staticmethod
    def update(params, grads, state, hp):
        wd = hp.get("weight_decay", 0.0)
        mom = hp.get("momentum", 0.0)
        step = state.get("step", 0.0)
        scale = jnp.power(hp["lr_decay"], step) \
            if hp.get("lr_decay", 1.0) != 1.0 else 1.0
        new_p, new_v = {}, {}
        for k, p in params.items():
            g = grads[k] + wd * p
            v = mom * state["velocity"][k] - _lr_for(hp, k) * scale * g
            new_p[k] = p + v
            new_v[k] = v
        new_state = {"velocity": new_v}
        if "step" in state:
            # output structure must MIRROR the input's: a pre-r4
            # snapshot's state has no counter, and adding one here
            # would break the lax.scan carry pytree (such snapshots
            # predate lr_decay, so the schedule loses nothing)
            new_state["step"] = step + 1.0
        elif hp.get("lr_decay", 1.0) != 1.0:
            # runs at trace time (static dict structure), so once per
            # compile, not per step
            import logging
            logging.getLogger("SGD").warning(
                "lr_decay=%s configured but the restored solver state "
                "has no step counter (pre-r4 snapshot): the decay "
                "scale is pinned to 1.0", hp["lr_decay"])
        return new_p, new_state


class AdaGrad(Solver):
    name = "adagrad"

    @staticmethod
    def init(params):
        return {"accum": _zeros_like(params)}

    @staticmethod
    def update(params, grads, state, hp):
        wd = hp.get("weight_decay", 0.0)
        eps = hp.get("epsilon", 1e-8)
        new_p, new_a = {}, {}
        for k, p in params.items():
            g = grads[k] + wd * p
            a = state["accum"][k] + jnp.square(g)
            new_p[k] = p - _lr_for(hp, k) * g / (jnp.sqrt(a) + eps)
            new_a[k] = a
        return new_p, {"accum": new_a}


class AdaDelta(Solver):
    name = "adadelta"

    @staticmethod
    def init(params):
        return {"accum_g": _zeros_like(params),
                "accum_dx": _zeros_like(params)}

    @staticmethod
    def update(params, grads, state, hp):
        rho = hp.get("rho", 0.95)
        eps = hp.get("epsilon", 1e-6)
        wd = hp.get("weight_decay", 0.0)
        new_p, new_g, new_dx = {}, {}, {}
        for k, p in params.items():
            g = grads[k] + wd * p
            ag = rho * state["accum_g"][k] + (1 - rho) * jnp.square(g)
            dx = -jnp.sqrt(state["accum_dx"][k] + eps) / \
                jnp.sqrt(ag + eps) * g
            new_p[k] = p + dx
            new_g[k] = ag
            new_dx[k] = rho * state["accum_dx"][k] + \
                (1 - rho) * jnp.square(dx)
        return new_p, {"accum_g": new_g, "accum_dx": new_dx}


class Adam(Solver):
    name = "adam"

    @staticmethod
    def init(params):
        return {"m": _zeros_like(params), "v": _zeros_like(params),
                "t": jnp.zeros((), jnp.float32)}

    @staticmethod
    def update(params, grads, state, hp):
        b1 = hp.get("beta1", 0.9)
        b2 = hp.get("beta2", 0.999)
        eps = hp.get("epsilon", 1e-8)
        wd = hp.get("weight_decay", 0.0)
        t = state["t"] + 1.0
        correction = jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        new_p, new_m, new_v = {}, {}, {}
        for k, p in params.items():
            g = grads[k] + wd * p
            m = b1 * state["m"][k] + (1 - b1) * g
            v = b2 * state["v"][k] + (1 - b2) * jnp.square(g)
            new_p[k] = p - _lr_for(hp, k) * correction * m / \
                (jnp.sqrt(v) + eps)
            new_m[k] = m
            new_v[k] = v
        return new_p, {"m": new_m, "v": new_v, "t": t}


SOLVERS = {cls.name: cls for cls in (SGD, AdaGrad, AdaDelta, Adam)}


def get_solver(name):
    if isinstance(name, type) and issubclass(name, Solver):
        return name
    try:
        return SOLVERS[name]
    except KeyError:
        raise ValueError("unknown solver %r (have %s)" %
                         (name, sorted(SOLVERS)))
