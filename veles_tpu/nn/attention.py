"""Trainable multi-head self-attention.

The 2015 reference has no attention anywhere (SURVEY.md §5 records it
absent) — this unit is the beyond-reference long-context building
block the TPU build treats as first-class: single-device it runs the
flash-style streaming softmax (:func:`local_attention`), and with a
``seq`` mesh attached the SAME unit computes exact attention over a
sequence sharded across devices via ring attention
(:mod:`veles_tpu.parallel.sequence`) — K/V blocks rotate on ICI while
each chip accumulates its query block. Both paths are pure ``apply``
functions, so the generic vjp GD unit trains them with no bespoke
backward (the ring's scan + ppermute transpose IS the backward ring).

Parameters pack as one ``weights`` tensor (4, dim, dim) — rows are the
Q/K/V/output projections — so every existing mechanism (filler,
snapshots, param-server deltas, solvers) applies unchanged.
"""

import jax.numpy as jnp

from veles_tpu.nn.base import ForwardBase
from veles_tpu.nn.gd import GradientDescentBase
from veles_tpu.parallel.sequence import (local_attention, ring_attention,
                                         ulysses_attention)


class MultiHeadAttentionForward(ForwardBase):
    """Self-attention over (batch, seq, dim) inputs, residual output."""

    hide_from_registry = False

    def __init__(self, workflow, heads=4, causal=True, residual=True,
                 **kwargs):
        super(MultiHeadAttentionForward, self).__init__(workflow,
                                                        **kwargs)
        self.heads = int(heads)
        self.causal = causal
        #: add x to the attention output (the transformer block wiring;
        #: also keeps deep stacks trainable at plain-SGD rates)
        self.residual = residual
        self._seq_mesh_ = None
        self._seq_axis_ = "seq"

    def use_ring(self, mesh, axis="seq", schedule="ring"):
        """Attach a sequence mesh: apply() switches to the sharded
        plan — ``schedule="ring"`` (ppermute streaming-softmax hops) or
        ``"ulysses"`` (two all_to_alls, exact full-sequence attention
        per head slice; needs heads divisible by the axis).

        Runtime configuration (meshes are process-local device handles,
        so this is transient state — reattach after a snapshot resume).
        """
        if schedule not in ("ring", "ulysses"):
            raise ValueError("unknown sp schedule %r" % (schedule,))
        if schedule == "ulysses" and self.heads % mesh.shape[axis]:
            # both operands are known NOW — reject at the call that
            # causes it, not deep into the first forward trace
            raise ValueError(
                "ulysses needs heads (%d) divisible by the %r axis "
                "(%d)" % (self.heads, axis, mesh.shape[axis]))
        self._seq_mesh_ = mesh
        self._seq_axis_ = axis
        self._seq_schedule_ = schedule
        return self

    def init_unpickled(self):
        super(MultiHeadAttentionForward, self).init_unpickled()
        self._seq_mesh_ = None
        self._seq_axis_ = "seq"
        self._seq_schedule_ = "ring"

    def _placement_mesh(self):
        # base place_for_grad/param_values/_input_devmem re-place every
        # committed buffer onto the seq mesh (the ring's shard_map
        # rejects device-set mismatches otherwise)
        return self._seq_mesh_

    def weights_shape_for(self, input_shape):
        dim = input_shape[-1]
        if dim % self.heads:
            raise ValueError("dim %d not divisible by %d heads"
                             % (dim, self.heads))
        return (4, dim, dim)

    def bias_shape_for(self, input_shape):
        return (4, input_shape[-1])

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def apply(self, params, x):
        w = params["weights"]
        b = params.get("bias")
        batch, seq, dim = x.shape
        heads, head_dim = self.heads, dim // self.heads

        def proj(i, t):
            y = jnp.einsum("bsd,de->bse", t, w[i],
                           preferred_element_type=jnp.float32)
            if b is not None:
                y = y + b[i]
            return y

        def split(t):  # (B, S, D) -> (B, H, S, hd)
            return t.reshape(batch, seq, heads, head_dim).transpose(
                0, 2, 1, 3)

        q, k, v = (split(proj(i, x)) for i in range(3))
        if self._seq_mesh_ is not None:
            if self._seq_schedule_ == "ulysses":
                ctx = ulysses_attention(q, k, v, self._seq_mesh_,
                                        self._seq_axis_,
                                        causal=self.causal)
            else:
                ctx = ring_attention(q, k, v, self._seq_mesh_,
                                     self._seq_axis_,
                                     causal=self.causal)
        else:
            ctx = local_attention(q, k, v, causal=self.causal)
        merged = ctx.transpose(0, 2, 1, 3).reshape(batch, seq, dim)
        out = proj(3, merged)
        if self.residual:
            out = out + x
        return out.astype(x.dtype)


class GDAttention(GradientDescentBase):
    """Backward for the attention block: the generic vjp covers it —
    including THROUGH the ring (scan of ppermutes transposes to the
    reverse ring)."""
