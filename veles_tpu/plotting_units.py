"""Concrete plotter units.

Re-designs ``veles/plotting_units.py``: the accumulating metric curve,
the confusion-matrix plot, histograms and image mosaics — the set the
reference samples wire into every workflow. Each captures data in
``fill()`` (host-side, one sync point) and renders in ``redraw()``
inside the graphics client.
"""

import numpy

from veles_tpu.plotter import Plotter


def _to_host(value):
    """Any array-ish (jax.Array, veles Array, number) → numpy/float."""
    devmem = getattr(value, "devmem", None)
    if devmem is not None:
        value = devmem
    return numpy.asarray(value)


class AccumulatingPlotter(Plotter):
    """Curve of a scalar metric over time (AccumulatingPlotter).

    ``input`` is a linked attribute; ``input_field`` optionally selects
    a key/index inside it. Appends one point per run.
    """

    def __init__(self, workflow, **kwargs):
        super(AccumulatingPlotter, self).__init__(workflow, **kwargs)
        self.input_field = kwargs.get("input_field", None)
        self.label = kwargs.get("label", self.name)
        self.plot_style = kwargs.get("plot_style", "-")
        self.values = []
        self.demand("input")

    def fill(self):
        value = self.input
        if self.input_field is not None:
            try:
                value = value[self.input_field]
            except TypeError:
                value = getattr(value, self.input_field)
        if self.clear_plot:
            del self.values[:]
        self.values.append(float(_to_host(value)))

    def redraw(self, figure):
        axes = figure.add_subplot(111)
        axes.plot(self.values, self.plot_style, label=self.label)
        axes.set_xlabel("updates")
        axes.set_ylabel(self.label)
        axes.grid(True)
        if len(self.values) > 1:
            axes.legend(loc="best")
        figure.suptitle(self.name)


class EpochMetricPlotter(AccumulatingPlotter):
    """Per-epoch normalized metric curve from a Decision unit.

    ``input`` links to the decision's ``epoch_history``; ``klass``
    selects which sample-class curve to plot ("train"/"validation"/
    "test").
    """

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("input_field", None)
        super(EpochMetricPlotter, self).__init__(workflow, **kwargs)
        self.klass = kwargs.get("klass", "validation")
        self.label = kwargs.get("label", self.klass)

    def fill(self):
        history = self.input
        if not history:
            return
        stats = history[-1].get(self.klass)
        if stats and "normalized" in stats:
            self.values.append(float(stats["normalized"]))


class MatrixPlotter(Plotter):
    """Confusion-matrix heatmap with per-cell counts (MatrixPlotter)."""

    def __init__(self, workflow, **kwargs):
        super(MatrixPlotter, self).__init__(workflow, **kwargs)
        self.matrix = None
        self.reversed_labels_mapping = kwargs.get(
            "reversed_labels_mapping", None)
        self.demand("input")

    def fill(self):
        matrix = _to_host(self.input).copy()
        if matrix.ndim == 1:  # evaluator ships it flattened
            side = int(round(numpy.sqrt(matrix.size)))
            matrix = matrix.reshape(side, side)
        self.matrix = matrix

    def redraw(self, figure):
        axes = figure.add_subplot(111)
        num = self.matrix.shape[0]
        axes.imshow(self.matrix, interpolation="nearest", cmap="Blues")
        threshold = self.matrix.max() / 2.0 if self.matrix.size else 0
        for (row, col), count in numpy.ndenumerate(self.matrix):
            axes.text(col, row, "%d" % count, ha="center", va="center",
                      color="white" if count > threshold else "black")
        labels = (self.reversed_labels_mapping or
                  [str(i) for i in range(num)])
        axes.set_xticks(range(num))
        axes.set_yticks(range(num))
        axes.set_xticklabels(labels)
        axes.set_yticklabels(labels)
        axes.set_xlabel("predicted")
        axes.set_ylabel("target")
        figure.suptitle(self.name)


class SimpleHistogram(Plotter):
    """Histogram of a flat array (Histogram / MultiHistogram family)."""

    def __init__(self, workflow, **kwargs):
        super(SimpleHistogram, self).__init__(workflow, **kwargs)
        self.bins = kwargs.get("bins", 50)
        self.data = None
        self.demand("input")

    def fill(self):
        self.data = _to_host(self.input).ravel().copy()

    def redraw(self, figure):
        axes = figure.add_subplot(111)
        axes.hist(self.data, bins=self.bins)
        axes.grid(True)
        figure.suptitle(self.name)


class ImagePlotter(Plotter):
    """Mosaic of 2D slices (ImagePlotter / Weights2D).

    ``input`` is an array whose first axis indexes samples/filters; up
    to ``limit`` slices are tiled into a square grid.
    """

    def __init__(self, workflow, **kwargs):
        super(ImagePlotter, self).__init__(workflow, **kwargs)
        self.limit = kwargs.get("limit", 16)
        self.color = kwargs.get("color", False)
        self.images = None
        self.demand("input")

    def fill(self):
        data = _to_host(self.input)
        if data.ndim == 1:
            data = data[numpy.newaxis]
        count = min(self.limit, data.shape[0])
        images = []
        for i in range(count):
            img = data[i]
            if img.ndim == 1:  # flat sample → squarest 2D reshape
                side = int(numpy.sqrt(img.size))
                while img.size % side:
                    side -= 1
                img = img.reshape(side, img.size // side)
            images.append(numpy.array(img, dtype=numpy.float32))
        self.images = images

    def redraw(self, figure):
        count = len(self.images)
        side = int(numpy.ceil(numpy.sqrt(count)))
        for i, img in enumerate(self.images):
            axes = figure.add_subplot(side, side, i + 1)
            if img.ndim == 3 and self.color:
                lo, hi = img.min(), img.max()
                axes.imshow((img - lo) / max(hi - lo, 1e-30))
            else:
                if img.ndim == 3:
                    img = img.mean(axis=-1)
                axes.imshow(img, interpolation="nearest", cmap="gray")
            axes.axis("off")
        figure.suptitle(self.name)


class ImmediatePlotter(Plotter):
    """N curves rendered together on one axes (reference
    ``veles/plotting_units.py:480-530``).

    ``inputs`` is a list of array-ish series; ``input_fields[i]``
    optionally selects an int index or attribute inside ``inputs[i]``;
    ``input_styles[i]`` is the matplotlib line style. Unlike the
    reference (which redrew from live unit attributes), ``fill()``
    captures every series host-side so the snapshot travels the PUB
    pipe self-contained.
    """

    DEFAULT_STYLES = ["k-", "g-", "b-", "r-", "c-", "m-"]

    def __init__(self, workflow, **kwargs):
        super(ImmediatePlotter, self).__init__(workflow, **kwargs)
        self.inputs = list(kwargs.get("inputs", []))
        self.input_fields = list(kwargs.get("input_fields", []))
        self.input_styles = list(kwargs.get("input_styles", []))
        self.ylim = kwargs.get("ylim")
        self.series = []

    def fill(self):
        self.series = []
        for i, value in enumerate(self.inputs):
            field = (self.input_fields[i]
                     if i < len(self.input_fields) else None)
            if field is not None:
                if isinstance(field, int):
                    value = value[field]
                else:
                    value = getattr(value, field)
            self.series.append(
                numpy.asarray(_to_host(value), numpy.float64).ravel())

    def redraw(self, figure):
        axes = figure.add_subplot(111)
        if self.ylim is not None:
            axes.set_ylim(self.ylim[0], self.ylim[1])
        for i, series in enumerate(self.series):
            style = (self.input_styles[i] if i < len(self.input_styles)
                     else self.DEFAULT_STYLES[i % len(self.DEFAULT_STYLES)])
            axes.plot(series, style)
        axes.grid(True)
        figure.suptitle(self.name)


class AutoHistogramPlotter(SimpleHistogram):
    """Histogram of a 1D series with the bin count chosen by the
    Freedman-Diaconis rule (reference ``plotting_units.py:629-678``)."""

    def fill(self):
        super(AutoHistogramPlotter, self).fill()
        data = self.data
        if data is None or data.size < 2:
            self.bins = None
            return
        data = data.astype(numpy.float64)
        iqr = (numpy.percentile(data, 75, method="higher") -
               numpy.percentile(data, 25, method="lower"))
        span = float(data.max() - data.min())
        if iqr <= 0 or span <= 0:
            self.bins = 3
            return
        width = 2.0 * iqr * data.size ** (-1.0 / 3.0)
        self.bins = max(3, int(round(span / width)))

    def redraw(self, figure):
        if self.bins is None:
            return  # <2 points: nothing meaningful to draw (reference
            # AutoHistogramPlotter.redraw returned early the same way)
        super(AutoHistogramPlotter, self).redraw(figure)


class MultiHistogram(Plotter):
    """Grid of per-row histograms of a 2D input — one histogram per
    neuron/filter (reference ``plotting_units.py:681-766``).

    ``input`` is (rows, ...); the first ``hist_number`` rows (capped by
    ``limit``) are each binned into ``n_bars`` buckets. Counts are
    computed vectorized in ``fill()``; the snapshot carries only the
    (rows, n_bars) counts plus per-row ranges.
    """

    def __init__(self, workflow, **kwargs):
        super(MultiHistogram, self).__init__(workflow, **kwargs)
        self.limit = kwargs.get("limit", 64)
        self.n_bars = kwargs.get("n_bars", 25)
        self.hist_number = min(kwargs.get("hist_number", 16), self.limit)
        self.counts = None
        self.ranges = None
        self.demand("input")

    def fill(self):
        data = _to_host(self.input)
        rows = min(self.hist_number, data.shape[0])
        counts = numpy.zeros((rows, self.n_bars), numpy.int64)
        ranges = numpy.zeros((rows, 2), numpy.float64)
        for i in range(rows):
            row = numpy.asarray(data[i], numpy.float64).ravel()
            lo, hi = float(row.min()), float(row.max())
            ranges[i] = lo, hi
            if hi > lo:
                counts[i] = numpy.histogram(
                    row, bins=self.n_bars, range=(lo, hi))[0]
        self.counts, self.ranges = counts, ranges

    def redraw(self, figure):
        rows = self.counts.shape[0]
        n_cols = max(1, int(round(numpy.sqrt(rows))))
        n_rows = int(numpy.ceil(rows / n_cols))
        for i in range(rows):
            axes = figure.add_subplot(n_rows, n_cols, i + 1)
            lo, hi = self.ranges[i]
            centers = numpy.linspace(lo, hi, num=self.n_bars,
                                     endpoint=True)
            width = (hi - lo) / self.n_bars * 0.8 if hi > lo else 0.8
            axes.bar(centers, self.counts[i], width=width)
            axes.grid(True)
            if n_rows > 4:
                axes.set_yticklabels([])
            if n_cols > 3:
                axes.set_xticklabels([])
        figure.suptitle(self.name)


class TableMaxMin(Plotter):
    """max/min table over a list of arrays (reference
    ``plotting_units.py:769-819``): one column per watched tensor, two
    rows. ``y`` holds the arrays, ``col_labels`` their names."""

    def __init__(self, workflow, **kwargs):
        super(TableMaxMin, self).__init__(workflow, **kwargs)
        self.y = list(kwargs.get("y", []))
        self.col_labels = list(kwargs.get("col_labels", []))
        self.values = None

    def fill(self):
        if len(self.col_labels) != len(self.y):
            raise ValueError(
                "col_labels (%d) must match y (%d)" %
                (len(self.col_labels), len(self.y)))
        values = numpy.zeros((2, len(self.y)), numpy.float64)
        for i, value in enumerate(self.y):
            arr = _to_host(value)
            values[0, i] = arr.max()
            values[1, i] = arr.min()
        self.values = values

    def redraw(self, figure):
        axes = figure.add_subplot(111)
        axes.axis("off")
        cells = [["%.6f" % v for v in row] for row in self.values]
        table = axes.table(cellText=cells, rowLabels=["max", "min"],
                           colLabels=self.col_labels, loc="center")
        table.set_fontsize(14)
        figure.suptitle(self.name)


class SlaveStats(Plotter):
    """Per-slave load/latency view of a running coordinator (reference
    ``plotting_units.py:822-905`` drew slave iteration timings from
    apply_data_from_slave callbacks).

    Here the master-side coordinator already keeps the authoritative
    registry, so ``fill()`` reads ``server.snapshot_slaves()`` and
    accumulates a per-slave series of job completion rates; no
    protocol hooks needed. The same snapshot feeds the web dashboard.
    """

    def __init__(self, workflow, **kwargs):
        super(SlaveStats, self).__init__(workflow, **kwargs)
        self.period = kwargs.get("period", 100)
        self.server = kwargs.get("server")
        self._last_jobs = {}
        # sid -> list of (jobs_since_last, staleness_s, n_in_flight);
        # redraw() stacks one subplot per element
        self.history = {}
        self.labels = {}   # sid -> "sid (pid)"

    def fill(self):
        import time as _time
        server = self.server
        if server is None:
            return
        now = _time.time()
        snapshot = server.snapshot_slaves()  # ONE consistent copy
        for slave in snapshot:
            done = slave.jobs_done
            if slave.id not in self._last_jobs:
                # first sight: seed the baseline, record no delta — a
                # slave with a lifetime of prior jobs (or one
                # reconnecting) must not spike the per-tick series
                self._last_jobs[slave.id] = done
                self.labels[slave.id] = "%s (pid %s)" % (slave.id,
                                                         slave.pid)
                continue
            delta = done - self._last_jobs[slave.id]
            self._last_jobs[slave.id] = done
            series = self.history.setdefault(slave.id, [])
            series.append((delta, now - slave.last_seen,
                           len(slave.jobs_in_flight)))
            if len(series) > 2 * self.period:
                del series[:len(series) - self.period]
            self.labels[slave.id] = "%s (pid %s)" % (slave.id, slave.pid)
        # forget slaves the coordinator dropped
        alive = {s.id for s in snapshot}
        for sid in list(self.history):
            if sid not in alive:
                self.history.pop(sid)
                self.labels.pop(sid, None)
                self._last_jobs.pop(sid, None)

    def redraw(self, figure):
        if not self.history:
            return
        panes = (("jobs completed per tick", 0),
                 ("staleness (s)", 1),
                 ("jobs in flight", 2))
        for row, (ylabel, elem) in enumerate(panes, start=1):
            axes = figure.add_subplot(len(panes), 1, row)
            for sid in sorted(self.history):
                series = self.history[sid][-self.period:]
                axes.plot([p[elem] for p in series],
                          label=self.labels.get(sid, sid))
            axes.set_ylabel(ylabel)
            axes.set_ylim(bottom=0)
            axes.grid(True)
            if row == 1:
                axes.legend(loc="best")
            if row == len(panes):
                axes.set_xlabel("fill ticks")
        figure.suptitle(self.name)

    def __getstate__(self):
        # the live server handle must not ride the PUB pickle
        state = super(SlaveStats, self).__getstate__()
        state["server"] = None
        return state
