"""Concrete plotter units.

Re-designs ``veles/plotting_units.py``: the accumulating metric curve,
the confusion-matrix plot, histograms and image mosaics — the set the
reference samples wire into every workflow. Each captures data in
``fill()`` (host-side, one sync point) and renders in ``redraw()``
inside the graphics client.
"""

import numpy

from veles_tpu.plotter import Plotter


def _to_host(value):
    """Any array-ish (jax.Array, veles Array, number) → numpy/float."""
    devmem = getattr(value, "devmem", None)
    if devmem is not None:
        value = devmem
    return numpy.asarray(value)


class AccumulatingPlotter(Plotter):
    """Curve of a scalar metric over time (AccumulatingPlotter).

    ``input`` is a linked attribute; ``input_field`` optionally selects
    a key/index inside it. Appends one point per run.
    """

    def __init__(self, workflow, **kwargs):
        super(AccumulatingPlotter, self).__init__(workflow, **kwargs)
        self.input_field = kwargs.get("input_field", None)
        self.label = kwargs.get("label", self.name)
        self.plot_style = kwargs.get("plot_style", "-")
        self.values = []
        self.demand("input")

    def fill(self):
        value = self.input
        if self.input_field is not None:
            try:
                value = value[self.input_field]
            except TypeError:
                value = getattr(value, self.input_field)
        if self.clear_plot:
            del self.values[:]
        self.values.append(float(_to_host(value)))

    def redraw(self, figure):
        axes = figure.add_subplot(111)
        axes.plot(self.values, self.plot_style, label=self.label)
        axes.set_xlabel("updates")
        axes.set_ylabel(self.label)
        axes.grid(True)
        if len(self.values) > 1:
            axes.legend(loc="best")
        figure.suptitle(self.name)


class EpochMetricPlotter(AccumulatingPlotter):
    """Per-epoch normalized metric curve from a Decision unit.

    ``input`` links to the decision's ``epoch_history``; ``klass``
    selects which sample-class curve to plot ("train"/"validation"/
    "test").
    """

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("input_field", None)
        super(EpochMetricPlotter, self).__init__(workflow, **kwargs)
        self.klass = kwargs.get("klass", "validation")
        self.label = kwargs.get("label", self.klass)

    def fill(self):
        history = self.input
        if not history:
            return
        stats = history[-1].get(self.klass)
        if stats and "normalized" in stats:
            self.values.append(float(stats["normalized"]))


class MatrixPlotter(Plotter):
    """Confusion-matrix heatmap with per-cell counts (MatrixPlotter)."""

    def __init__(self, workflow, **kwargs):
        super(MatrixPlotter, self).__init__(workflow, **kwargs)
        self.matrix = None
        self.reversed_labels_mapping = kwargs.get(
            "reversed_labels_mapping", None)
        self.demand("input")

    def fill(self):
        matrix = _to_host(self.input).copy()
        if matrix.ndim == 1:  # evaluator ships it flattened
            side = int(round(numpy.sqrt(matrix.size)))
            matrix = matrix.reshape(side, side)
        self.matrix = matrix

    def redraw(self, figure):
        axes = figure.add_subplot(111)
        num = self.matrix.shape[0]
        axes.imshow(self.matrix, interpolation="nearest", cmap="Blues")
        threshold = self.matrix.max() / 2.0 if self.matrix.size else 0
        for (row, col), count in numpy.ndenumerate(self.matrix):
            axes.text(col, row, "%d" % count, ha="center", va="center",
                      color="white" if count > threshold else "black")
        labels = (self.reversed_labels_mapping or
                  [str(i) for i in range(num)])
        axes.set_xticks(range(num))
        axes.set_yticks(range(num))
        axes.set_xticklabels(labels)
        axes.set_yticklabels(labels)
        axes.set_xlabel("predicted")
        axes.set_ylabel("target")
        figure.suptitle(self.name)


class SimpleHistogram(Plotter):
    """Histogram of a flat array (Histogram / MultiHistogram family)."""

    def __init__(self, workflow, **kwargs):
        super(SimpleHistogram, self).__init__(workflow, **kwargs)
        self.bins = kwargs.get("bins", 50)
        self.data = None
        self.demand("input")

    def fill(self):
        self.data = _to_host(self.input).ravel().copy()

    def redraw(self, figure):
        axes = figure.add_subplot(111)
        axes.hist(self.data, bins=self.bins)
        axes.grid(True)
        figure.suptitle(self.name)


class ImagePlotter(Plotter):
    """Mosaic of 2D slices (ImagePlotter / Weights2D).

    ``input`` is an array whose first axis indexes samples/filters; up
    to ``limit`` slices are tiled into a square grid.
    """

    def __init__(self, workflow, **kwargs):
        super(ImagePlotter, self).__init__(workflow, **kwargs)
        self.limit = kwargs.get("limit", 16)
        self.color = kwargs.get("color", False)
        self.images = None
        self.demand("input")

    def fill(self):
        data = _to_host(self.input)
        if data.ndim == 1:
            data = data[numpy.newaxis]
        count = min(self.limit, data.shape[0])
        images = []
        for i in range(count):
            img = data[i]
            if img.ndim == 1:  # flat sample → squarest 2D reshape
                side = int(numpy.sqrt(img.size))
                while img.size % side:
                    side -= 1
                img = img.reshape(side, img.size // side)
            images.append(numpy.array(img, dtype=numpy.float32))
        self.images = images

    def redraw(self, figure):
        count = len(self.images)
        side = int(numpy.ceil(numpy.sqrt(count)))
        for i, img in enumerate(self.images):
            axes = figure.add_subplot(side, side, i + 1)
            if img.ndim == 3 and self.color:
                lo, hi = img.min(), img.max()
                axes.imshow((img - lo) / max(hi - lo, 1e-30))
            else:
                if img.ndim == 3:
                    img = img.mean(axis=-1)
                axes.imshow(img, interpolation="nearest", cmap="gray")
            axes.axis("off")
        figure.suptitle(self.name)
