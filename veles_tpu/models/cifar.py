"""CIFAR-10 convnet — BASELINE config 2 (reference baseline: 17.21%
validation error with the Caffe-style config,
``manualrst_veles_algorithms.rst:50``).

Caffe cifar10-quick-style conv stack over StandardWorkflow, with the
reference's mean-dispersion input normalization. Reads the standard
CIFAR-10 python pickles when a directory is given; synthetic fallback
for tests.
"""

import os
import pickle

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.standard_workflow import StandardWorkflow

CIFAR_LAYERS = [
    {"type": "conv_str", "n_kernels": 32, "kx": 5, "ky": 5, "padding": 2},
    {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
    {"type": "conv_str", "n_kernels": 32, "kx": 5, "ky": 5, "padding": 2},
    {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
    {"type": "conv_str", "n_kernels": 64, "kx": 5, "ky": 5, "padding": 2},
    {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
    {"type": "all2all", "output_sample_shape": 64},
    {"type": "softmax", "output_sample_shape": 10},
]


class CifarLoader(FullBatchLoader):
    """CIFAR-10 python-pickle loader (batches 1-5 train, test_batch
    validation) with mean_disp normalization, or synthetic data."""

    hide_from_registry = True

    def __init__(self, workflow, directory=None, synthetic_samples=0,
                 provider=None, seed=2, **kwargs):
        kwargs.setdefault("normalization_type", "mean_disp")
        super(CifarLoader, self).__init__(workflow, **kwargs)
        self.directory = directory
        self.synthetic_samples = synthetic_samples
        #: callable -> (train_x, train_y, valid_x, valid_y); the parity
        #: harness plugs datasets.golden_objects here
        self.provider = provider
        self.seed = seed

    def _load_pickles(self):
        def batch(name):
            with open(os.path.join(self.directory, name), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            data = d[b"data"].reshape(-1, 3, 32, 32).transpose(
                0, 2, 3, 1).astype(numpy.float32)
            return data, numpy.asarray(d[b"labels"], numpy.int32)

        train = [batch("data_batch_%d" % i) for i in range(1, 6)]
        valid = batch("test_batch")
        train_x = numpy.concatenate([t[0] for t in train])
        train_y = numpy.concatenate([t[1] for t in train])
        return train_x, train_y, valid[0], valid[1]

    def _synthesize(self):
        rng = numpy.random.RandomState(self.seed)
        n = self.synthetic_samples or 600
        nv = max(n // 5, 1)
        protos = rng.rand(10, 32, 32, 3).astype(numpy.float32)

        def make(count):
            labels = rng.randint(0, 10, count).astype(numpy.int32)
            data = protos[labels] + rng.normal(
                0, 0.25, (count, 32, 32, 3)).astype(numpy.float32)
            return data, labels

        tx, ty = make(n)
        vx, vy = make(nv)
        return tx, ty, vx, vy

    def load_dataset(self):
        if self.provider is not None:
            tx, ty, vx, vy = self.provider()
        elif self.directory and os.path.isdir(self.directory):
            tx, ty, vx, vy = self._load_pickles()
        else:
            tx, ty, vx, vy = self._synthesize()
        self.original_data.reset(numpy.concatenate([vx, tx]))
        self.original_labels.reset(numpy.concatenate([vy, ty]))
        self.class_lengths = [0, len(vx), len(tx)]


class CifarWorkflow(StandardWorkflow):
    hide_from_registry = True

    def __init__(self, workflow=None, directory=None,
                 synthetic_samples=0, provider=None, layers=None,
                 **kwargs):
        kwargs.setdefault("loss", "softmax")
        kwargs.setdefault("learning_rate", 0.01)
        kwargs.setdefault("momentum", 0.9)
        kwargs.setdefault("weights_decay", 4e-3)
        minibatch_size = kwargs.pop("minibatch_size", 100)
        super(CifarWorkflow, self).__init__(
            workflow,
            loader=lambda wf: CifarLoader(
                wf, directory=directory,
                synthetic_samples=synthetic_samples, provider=provider,
                minibatch_size=minibatch_size),
            layers=layers if layers is not None else CIFAR_LAYERS,
            **kwargs)
