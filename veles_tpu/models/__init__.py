"""Model workflows — TPU-native counterparts of the Znicz samples
(MNIST FC, MNIST conv, CIFAR convnet, AlexNet, MNIST autoencoder,
Kohonen SOM; ``.coveragerc:51-66``, ``manualrst_veles_algorithms.rst``).
"""
