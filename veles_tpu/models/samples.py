"""Small sample workflows mirroring the reference's Znicz sample set
(``.coveragerc:50-66``: wine, lines, kanji, channels — the samples the
reference shipped beyond the BASELINE configs).

The original datasets are not fetchable here (zero egress), so each
sample pairs its topology with a committed deterministic generator of
the same shape and difficulty class: ``wine`` (13-feature tabular,
3 classes), ``lines`` (oriented-stroke images, 4 angle classes — the
reference's conv primer), ``kanji`` (100-class warped glyph pairs on
the golden-digit renderer), and ``channels`` (TV-channel LOGO
recognition — the one sample whose distinctive capability is loading
class-per-directory image TREES from disk: ``generate_channels_dataset``
renders synthetic station logos into per-channel directories and
:class:`ChannelsWorkflow` trains through the real
``FileImageLoader``/scanner/decoder path, not an in-memory provider).
All run fused through StandardWorkflow.
"""

import numpy

from veles_tpu.loader.fullbatch import ProviderLoader
from veles_tpu.standard_workflow import StandardWorkflow


class WineProvider(object):
    """Tabular 13-feature, 3-class mixture dataset (UCI wine's shape):
    class-conditional Gaussians with overlapping covariance so a
    linear model errs a few percent, like the original."""

    def __init__(self, n_train=400, n_valid=100, seed=11):
        self.n_train = n_train
        self.n_valid = n_valid
        self.seed = seed

    def __call__(self):
        rng = numpy.random.RandomState(self.seed)
        total = self.n_train + self.n_valid
        labels = rng.randint(0, 3, total).astype(numpy.int32)
        centers = rng.randn(3, 13).astype(numpy.float32) * 1.5
        mix = rng.randn(13, 13).astype(numpy.float32) * 0.4
        data = centers[labels] + rng.randn(total, 13).astype(
            numpy.float32) @ mix
        return (data[:self.n_train], labels[:self.n_train],
                data[self.n_train:], labels[self.n_train:])


class LinesProvider(object):
    """Oriented-stroke images, 4 classes (horizontal / vertical / the
    two diagonals) — the shape of the reference's ``lines`` conv
    sample."""

    def __init__(self, n_train=800, n_valid=200, side=16, seed=5):
        self.n_train = n_train
        self.n_valid = n_valid
        self.side = side
        self.seed = seed

    def _draw(self, rng, klass):
        side = self.side
        img = rng.rand(side, side).astype(numpy.float32) * 0.25
        c = rng.randint(side // 4, 3 * side // 4)
        span = numpy.arange(side)
        if klass == 0:                      # horizontal
            img[c, :] += 1.0
        elif klass == 1:                    # vertical
            img[:, c] += 1.0
        elif klass == 2:                    # main diagonal
            off = rng.randint(-side // 4, side // 4)
            idx = numpy.clip(span + off, 0, side - 1)
            img[span, idx] += 1.0
        else:                               # anti-diagonal
            off = rng.randint(-side // 4, side // 4)
            idx = numpy.clip(side - 1 - span + off, 0, side - 1)
            img[span, idx] += 1.0
        return numpy.clip(img, 0.0, 1.0)

    def __call__(self):
        rng = numpy.random.RandomState(self.seed)
        total = self.n_train + self.n_valid
        labels = rng.randint(0, 4, total).astype(numpy.int32)
        data = numpy.stack([self._draw(rng, int(k)) for k in labels])
        data = data[..., None]  # NHWC
        return (data[:self.n_train], labels[:self.n_train],
                data[self.n_train:], labels[self.n_train:])


class KanjiProvider(object):
    """Many-class glyph classification (the reference ``kanji``
    sample's shape): each class is an ordered PAIR of digit glyphs
    rendered side by side (10×10 = 100 classes), warped per sample
    with the golden-digit renderer — small images, many classes, high
    intra-class variation."""

    def __init__(self, n_train=4000, n_valid=800, seed=17):
        self.n_train = n_train
        self.n_valid = n_valid
        self.seed = seed

    def __call__(self):
        from veles_tpu.datasets import _render
        rng = numpy.random.RandomState(self.seed)
        total = self.n_train + self.n_valid
        labels = rng.randint(0, 100, total).astype(numpy.int32)
        data = numpy.zeros((total, 24, 48), numpy.float32)
        for i, lbl in enumerate(labels):
            left = _render(int(lbl) // 10, rng, size=24)
            right = _render(int(lbl) % 10, rng, size=24)
            data[i, :, :24] = left
            data[i, :, 24:] = right
        return (data[:self.n_train], labels[:self.n_train],
                data[self.n_train:], labels[self.n_train:])


class TabularLoader(ProviderLoader):
    """Device-resident full batch over any (tx, ty, vx, vy) provider,
    mean/dispersion-normalized by default (the wine sample's recipe)."""

    hide_from_registry = True

    def __init__(self, workflow, provider=None, **kwargs):
        kwargs.setdefault("normalization_type", "mean_disp")
        super(TabularLoader, self).__init__(workflow, provider=provider,
                                            **kwargs)


class WineWorkflow(StandardWorkflow):
    """13 → 10 tanh → 3 softmax (the reference wine sample's shape)."""

    hide_from_registry = True

    def __init__(self, workflow=None, provider=None, minibatch_size=50,
                 **kwargs):
        provider = provider or WineProvider()
        kwargs.setdefault("learning_rate", 0.1)
        kwargs.setdefault("loss", "softmax")
        super(WineWorkflow, self).__init__(
            workflow,
            loader=lambda w: TabularLoader(
                w, provider=provider, minibatch_size=minibatch_size),
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 10},
                {"type": "softmax", "output_sample_shape": 3},
            ], **kwargs)


class KanjiWorkflow(StandardWorkflow):
    """Conv net over glyph pairs, 100 classes (reference kanji
    sample's shape class). At the defaults (20k samples, momentum 0.9
    with the learning rate scaled down to keep the same effective
    step) it reaches **3.95%** validation error in 20 epochs on one
    chip — the r3 momentum-free recipe (lr 0.2) plateaued at 7.1%;
    lr-decay variants at this budget undertrain (r4 sweep)."""

    hide_from_registry = True

    def __init__(self, workflow=None, provider=None, minibatch_size=100,
                 **kwargs):
        provider = provider or KanjiProvider(n_train=20000,
                                             n_valid=2000)
        kwargs.setdefault("learning_rate", 0.04)
        kwargs.setdefault("momentum", 0.9)
        kwargs.setdefault("loss", "softmax")
        super(KanjiWorkflow, self).__init__(
            workflow,
            loader=lambda w: TabularLoader(
                w, provider=provider, minibatch_size=minibatch_size,
                normalization_type="none"),
            layers=[
                {"type": "conv_relu", "n_kernels": 16, "kx": 5, "ky": 5},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "conv_relu", "n_kernels": 32, "kx": 3, "ky": 3},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "all2all_relu", "output_sample_shape": 128},
                {"type": "softmax", "output_sample_shape": 100},
            ], **kwargs)


def generate_channels_dataset(directory, n_channels=6, per_class=30,
                              side=32, seed=21):
    """Render a synthetic TV-channel-logo dataset into
    ``<directory>/{train,validation}/<channel-name>/*.png``.

    Each "channel" gets a distinct geometric emblem (bars / disc /
    frame / checker / stripes / cross) with per-image position jitter
    and background noise — the channels problem's shape (small images,
    one logo class per directory) without its unfetchable data. Returns
    the (train_paths, validation_paths) roots for
    :class:`~veles_tpu.loader.image.FileImageLoader`."""
    import os

    from PIL import Image

    rng = numpy.random.RandomState(seed)
    names = ["channel%02d" % i for i in range(n_channels)]

    def emblem(klass, jitter):
        img = (rng.rand(side, side, 3) * 60).astype(numpy.uint8)
        yy, xx = numpy.mgrid[0:side, 0:side]
        cy, cx = side // 2 + jitter[0], side // 2 + jitter[1]
        color = numpy.zeros(3, numpy.uint8)
        color[klass % 3] = 230
        color[(klass + 1) % 3] = 120 if klass >= 3 else 0
        kind = klass % 6
        if kind == 0:
            mask = (xx // 4) % 2 == 0                       # bars
        elif kind == 1:
            mask = (yy - cy) ** 2 + (xx - cx) ** 2 < (side // 3) ** 2
        elif kind == 2:
            border = side // 5
            mask = ((numpy.minimum.reduce([yy, xx, side - 1 - yy,
                                           side - 1 - xx]) > border) &
                    (numpy.minimum.reduce([yy, xx, side - 1 - yy,
                                           side - 1 - xx]) < 2 * border))
        elif kind == 3:
            mask = ((yy // 4) + (xx // 4)) % 2 == 0         # checker
        elif kind == 4:
            mask = (yy // 4) % 2 == 0                       # stripes
        else:
            mask = (abs(yy - cy) < 3) | (abs(xx - cx) < 3)  # cross
        img[mask] = color
        return img

    splits = {"train": per_class, "validation": max(per_class // 4, 2)}
    for split, count in splits.items():
        for klass, name in enumerate(names):
            d = os.path.join(directory, split, name)
            os.makedirs(d, exist_ok=True)
            for i in range(count):
                jitter = rng.randint(-3, 4, size=2)
                Image.fromarray(emblem(klass, jitter)).save(
                    os.path.join(d, "frame%03d.png" % i))
    return ([os.path.join(directory, "train")],
            [os.path.join(directory, "validation")])


class ChannelsWorkflow(StandardWorkflow):
    """Conv net over channel-logo image directories (reference
    ``channels`` sample family): the loader is the real directory-tree
    :class:`~veles_tpu.loader.image.FileImageLoader` — scan, decode,
    resize, normalize — with labels from directory names."""

    hide_from_registry = True

    def __init__(self, workflow=None, train_paths=(),
                 validation_paths=(), n_classes=6, minibatch_size=30,
                 size=(32, 32), **kwargs):
        from veles_tpu.loader.image import FileImageLoader
        kwargs.setdefault("learning_rate", 0.05)
        kwargs.setdefault("loss", "softmax")
        loader_kwargs = {
            "train_paths": tuple(train_paths),
            "validation_paths": tuple(validation_paths),
            "size": size, "minibatch_size": minibatch_size,
            "normalization_type": "linear",
        }
        super(ChannelsWorkflow, self).__init__(
            workflow,
            loader=lambda w: FileImageLoader(w, **loader_kwargs),
            layers=[
                {"type": "conv_relu", "n_kernels": 12, "kx": 5, "ky": 5},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "conv_relu", "n_kernels": 24, "kx": 3, "ky": 3},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "all2all_relu", "output_sample_shape": 64},
                {"type": "softmax", "output_sample_shape": n_classes},
            ], **kwargs)


class SequenceProvider(object):
    """Needle-token sequence classification (the attention sample's
    task): every sample is a (seq, dim) block of noise tokens with ONE
    position carrying one of ``n_classes`` fixed key patterns; the
    label is which pattern. Content-based lookup across positions —
    attention's home turf (the 2015 reference has no sequence models
    at all)."""

    def __init__(self, n_train=1600, n_valid=320, seq=16, dim=16,
                 n_classes=8, seed=23):
        self.args = (n_train, n_valid, seq, dim, n_classes, seed)

    def __call__(self):
        n_train, n_valid, seq, dim, n_classes, seed = self.args
        rng = numpy.random.RandomState(seed)
        patterns = rng.randn(n_classes, dim).astype(numpy.float32) * 2.0

        def make(n):
            x = rng.randn(n, seq, dim).astype(numpy.float32) * 0.3
            y = rng.randint(0, n_classes, n).astype(numpy.int32)
            pos = rng.randint(0, seq, n)
            x[numpy.arange(n), pos] = patterns[y] + \
                rng.randn(n, dim).astype(numpy.float32) * 0.2
            return x, y

        tx, ty = make(n_train)
        vx, vy = make(n_valid)
        return tx, ty, vx, vy


class SequenceWorkflow(StandardWorkflow):
    """Attention stack over token sequences: the beyond-reference
    long-context building block as a full training workflow — runs
    FUSED through the same step compiler as every other sample, and
    each attention layer can switch to ring attention on a seq mesh
    (``MultiHeadAttentionForward.use_ring``). ``moe=True`` inserts a
    Switch-style expert FFN between the attention layers
    (``MoEForward.use_experts`` shards it over an expert mesh)."""

    hide_from_registry = True

    def __init__(self, workflow=None, provider=None, minibatch_size=80,
                 heads=4, n_classes=8, moe=False, n_experts=4,
                 **kwargs):
        provider = provider or SequenceProvider(n_classes=n_classes)
        kwargs.setdefault("learning_rate", 0.1)
        kwargs.setdefault("loss", "softmax")
        layers = [
            {"type": "attention", "heads": heads, "causal": False},
        ]
        if moe:
            layers.append({"type": "moe", "n_experts": n_experts})
        layers += [
            {"type": "attention", "heads": heads, "causal": False},
            {"type": "softmax", "output_sample_shape": n_classes},
        ]
        super(SequenceWorkflow, self).__init__(
            workflow,
            loader=lambda w: TabularLoader(
                w, provider=provider, minibatch_size=minibatch_size,
                sequence=True, normalization_type="none"),
            layers=layers, **kwargs)


class LinesWorkflow(StandardWorkflow):
    """Small conv net over oriented strokes (reference lines sample)."""

    hide_from_registry = True

    def __init__(self, workflow=None, provider=None, minibatch_size=50,
                 **kwargs):
        provider = provider or LinesProvider()
        kwargs.setdefault("learning_rate", 0.05)
        kwargs.setdefault("loss", "softmax")
        super(LinesWorkflow, self).__init__(
            workflow,
            loader=lambda w: TabularLoader(
                w, provider=provider, minibatch_size=minibatch_size,
                normalization_type="none"),
            layers=[
                {"type": "conv_relu", "n_kernels": 8, "kx": 3, "ky": 3},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "all2all_relu", "output_sample_shape": 32},
                {"type": "softmax", "output_sample_shape": 4},
            ], **kwargs)
