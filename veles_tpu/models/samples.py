"""Small sample workflows mirroring the reference's Znicz sample set
(``.coveragerc:50-66``: wine, lines, kanji, channels — the samples the
reference shipped beyond the BASELINE configs).

The original datasets are not fetchable here (zero egress), so each
sample pairs its topology with a committed deterministic generator of
the same shape and difficulty class: ``wine`` (13-feature tabular,
3 classes), ``lines`` (oriented-stroke images, 4 angle classes — the
reference's conv primer), ``kanji`` (100-class warped glyph pairs on
the golden-digit renderer). The ``channels`` sample (small-image
multi-class conv classification) is the same problem family as
lines/CIFAR and is covered by those configs. All run fused through
StandardWorkflow.
"""

import numpy

from veles_tpu.loader.fullbatch import ProviderLoader
from veles_tpu.standard_workflow import StandardWorkflow


class WineProvider(object):
    """Tabular 13-feature, 3-class mixture dataset (UCI wine's shape):
    class-conditional Gaussians with overlapping covariance so a
    linear model errs a few percent, like the original."""

    def __init__(self, n_train=400, n_valid=100, seed=11):
        self.n_train = n_train
        self.n_valid = n_valid
        self.seed = seed

    def __call__(self):
        rng = numpy.random.RandomState(self.seed)
        total = self.n_train + self.n_valid
        labels = rng.randint(0, 3, total).astype(numpy.int32)
        centers = rng.randn(3, 13).astype(numpy.float32) * 1.5
        mix = rng.randn(13, 13).astype(numpy.float32) * 0.4
        data = centers[labels] + rng.randn(total, 13).astype(
            numpy.float32) @ mix
        return (data[:self.n_train], labels[:self.n_train],
                data[self.n_train:], labels[self.n_train:])


class LinesProvider(object):
    """Oriented-stroke images, 4 classes (horizontal / vertical / the
    two diagonals) — the shape of the reference's ``lines`` conv
    sample."""

    def __init__(self, n_train=800, n_valid=200, side=16, seed=5):
        self.n_train = n_train
        self.n_valid = n_valid
        self.side = side
        self.seed = seed

    def _draw(self, rng, klass):
        side = self.side
        img = rng.rand(side, side).astype(numpy.float32) * 0.25
        c = rng.randint(side // 4, 3 * side // 4)
        span = numpy.arange(side)
        if klass == 0:                      # horizontal
            img[c, :] += 1.0
        elif klass == 1:                    # vertical
            img[:, c] += 1.0
        elif klass == 2:                    # main diagonal
            off = rng.randint(-side // 4, side // 4)
            idx = numpy.clip(span + off, 0, side - 1)
            img[span, idx] += 1.0
        else:                               # anti-diagonal
            off = rng.randint(-side // 4, side // 4)
            idx = numpy.clip(side - 1 - span + off, 0, side - 1)
            img[span, idx] += 1.0
        return numpy.clip(img, 0.0, 1.0)

    def __call__(self):
        rng = numpy.random.RandomState(self.seed)
        total = self.n_train + self.n_valid
        labels = rng.randint(0, 4, total).astype(numpy.int32)
        data = numpy.stack([self._draw(rng, int(k)) for k in labels])
        data = data[..., None]  # NHWC
        return (data[:self.n_train], labels[:self.n_train],
                data[self.n_train:], labels[self.n_train:])


class KanjiProvider(object):
    """Many-class glyph classification (the reference ``kanji``
    sample's shape): each class is an ordered PAIR of digit glyphs
    rendered side by side (10×10 = 100 classes), warped per sample
    with the golden-digit renderer — small images, many classes, high
    intra-class variation."""

    def __init__(self, n_train=4000, n_valid=800, seed=17):
        self.n_train = n_train
        self.n_valid = n_valid
        self.seed = seed

    def __call__(self):
        from veles_tpu.datasets import _render
        rng = numpy.random.RandomState(self.seed)
        total = self.n_train + self.n_valid
        labels = rng.randint(0, 100, total).astype(numpy.int32)
        data = numpy.zeros((total, 24, 48), numpy.float32)
        for i, lbl in enumerate(labels):
            left = _render(int(lbl) // 10, rng, size=24)
            right = _render(int(lbl) % 10, rng, size=24)
            data[i, :, :24] = left
            data[i, :, 24:] = right
        return (data[:self.n_train], labels[:self.n_train],
                data[self.n_train:], labels[self.n_train:])


class TabularLoader(ProviderLoader):
    """Device-resident full batch over any (tx, ty, vx, vy) provider,
    mean/dispersion-normalized by default (the wine sample's recipe)."""

    hide_from_registry = True

    def __init__(self, workflow, provider=None, **kwargs):
        kwargs.setdefault("normalization_type", "mean_disp")
        super(TabularLoader, self).__init__(workflow, provider=provider,
                                            **kwargs)


class WineWorkflow(StandardWorkflow):
    """13 → 10 tanh → 3 softmax (the reference wine sample's shape)."""

    hide_from_registry = True

    def __init__(self, workflow=None, provider=None, minibatch_size=50,
                 **kwargs):
        provider = provider or WineProvider()
        kwargs.setdefault("learning_rate", 0.1)
        kwargs.setdefault("loss", "softmax")
        super(WineWorkflow, self).__init__(
            workflow,
            loader=lambda w: TabularLoader(
                w, provider=provider, minibatch_size=minibatch_size),
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 10},
                {"type": "softmax", "output_sample_shape": 3},
            ], **kwargs)


class KanjiWorkflow(StandardWorkflow):
    """Conv net over glyph pairs, 100 classes (reference kanji
    sample's shape class). At the defaults (20k samples, lr 0.2 — the
    100-class softmax needs the hotter rate: early gradients scale
    like p≈1/classes) it reaches **7.1%** validation error in 20
    epochs on one chip."""

    hide_from_registry = True

    def __init__(self, workflow=None, provider=None, minibatch_size=100,
                 **kwargs):
        provider = provider or KanjiProvider(n_train=20000,
                                             n_valid=2000)
        kwargs.setdefault("learning_rate", 0.2)
        kwargs.setdefault("loss", "softmax")
        super(KanjiWorkflow, self).__init__(
            workflow,
            loader=lambda w: TabularLoader(
                w, provider=provider, minibatch_size=minibatch_size,
                normalization_type="none"),
            layers=[
                {"type": "conv_relu", "n_kernels": 16, "kx": 5, "ky": 5},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "conv_relu", "n_kernels": 32, "kx": 3, "ky": 3},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "all2all_relu", "output_sample_shape": 128},
                {"type": "softmax", "output_sample_shape": 100},
            ], **kwargs)


class LinesWorkflow(StandardWorkflow):
    """Small conv net over oriented strokes (reference lines sample)."""

    hide_from_registry = True

    def __init__(self, workflow=None, provider=None, minibatch_size=50,
                 **kwargs):
        provider = provider or LinesProvider()
        kwargs.setdefault("learning_rate", 0.05)
        kwargs.setdefault("loss", "softmax")
        super(LinesWorkflow, self).__init__(
            workflow,
            loader=lambda w: TabularLoader(
                w, provider=provider, minibatch_size=minibatch_size,
                normalization_type="none"),
            layers=[
                {"type": "conv_relu", "n_kernels": 8, "kx": 3, "ky": 3},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "all2all_relu", "output_sample_shape": 32},
                {"type": "softmax", "output_sample_shape": 4},
            ], **kwargs)
