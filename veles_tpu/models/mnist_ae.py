"""MNIST autoencoder + Kohonen SOM workflows — BASELINE config 4
(reference baseline: AE validation RMSE 0.5478,
``manualrst_veles_algorithms.rst:69``; these configs exercise the
matrix_reduce + random kernel paths in the reference).
"""

import numpy

from veles_tpu.accelerated_units import AcceleratedWorkflow
from veles_tpu.loader.fullbatch import FullBatchLoaderMSE
from veles_tpu.nn.decision import DecisionMSE
from veles_tpu.nn.kohonen import KohonenForward, KohonenTrainer
from veles_tpu.plumbing import Repeater
from veles_tpu.standard_workflow import StandardWorkflow


class AutoencoderLoader(FullBatchLoaderMSE):
    """MSE loader whose targets ARE the (normalized) inputs."""

    hide_from_registry = True

    def __init__(self, workflow, provider=None, **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super(AutoencoderLoader, self).__init__(workflow, **kwargs)
        self.provider = provider
        self.has_labels = False

    def load_dataset(self):
        train_x, _, valid_x, _ = self.provider()
        data = numpy.concatenate([valid_x, train_x]).astype(numpy.float32)
        self.original_data.reset(data.reshape(len(data), -1))
        self.class_lengths = [0, len(valid_x), len(train_x)]
        self.has_labels = False

    def load_data(self):
        # bypass FullBatchLoaderMSE's targets check: targets are derived
        # FROM the loaded+normalized data, so load first, then copy
        from veles_tpu.loader.fullbatch import FullBatchLoader
        FullBatchLoader.load_data(self)
        if self.original_targets.mem is None:
            self.original_targets.reset(
                numpy.array(self.original_data.mem, copy=True))


class MnistAEWorkflow(StandardWorkflow):
    """784 -> bottleneck -> 784 tanh autoencoder under MSE."""

    hide_from_registry = True

    def __init__(self, workflow=None, provider=None, bottleneck=100,
                 **kwargs):
        kwargs.setdefault("loss", "mse")
        kwargs.setdefault("learning_rate", 0.05)
        minibatch_size = kwargs.pop("minibatch_size", 100)
        layers = kwargs.pop("layers", None) or [
            {"type": "all2all_tanh", "output_sample_shape": bottleneck},
            {"type": "all2all", "output_sample_shape": None},
        ]
        self._provider = provider

        def loader_factory(wf):
            return AutoencoderLoader(wf, provider=provider,
                                     minibatch_size=minibatch_size)

        # output layer size = input features; resolved after load in
        # initialize — use a placeholder now
        self._layers_cfg = layers
        super(MnistAEWorkflow, self).__init__(
            workflow, loader=loader_factory,
            layers=self._resolve_layers(layers, provider),
            mse_target_attr="minibatch_targets", **kwargs)

    @staticmethod
    def _resolve_layers(layers, provider):
        resolved = []
        features = None  # load the dataset at most ONCE, for the shape
        for descr in layers:
            descr = dict(descr)
            if descr.get("output_sample_shape") is None:
                if features is None:
                    train_x = provider()[0]
                    features = int(numpy.prod(train_x.shape[1:]))
                descr["output_sample_shape"] = features
            resolved.append(descr)
        return resolved


class KohonenWorkflow(AcceleratedWorkflow):
    """SOM training loop: repeater -> loader -> trainer (+forward)."""

    hide_from_registry = True

    def __init__(self, workflow=None, loader_factory=None, sx=8, sy=8,
                 epochs=10, **kwargs):
        super(KohonenWorkflow, self).__init__(workflow, **kwargs)
        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)
        self.loader = loader_factory(self)
        self.loader.link_from(self.repeater)
        self.trainer = KohonenTrainer(self, sx=sx, sy=sy)
        self.trainer.link_from(self.loader)
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"))
        self.forward = KohonenForward(self)
        self.forward.link_attrs(self.loader, ("input", "minibatch_data"))
        self.forward.link_attrs(self.trainer, "weights")

        from veles_tpu.mutable import Bool
        from veles_tpu.units import Unit

        class EpochCounter(Unit):
            hide_from_registry = True

            def __init__(self, wf, **kw):
                super(EpochCounter, self).__init__(wf, **kw)
                self.complete = Bool(False)
                self.demand("epoch_ended", "epoch_number")

            def initialize(self, **kw):
                pass

            def run(self):
                if bool(self.epoch_ended) and \
                        self.epoch_number >= epochs:
                    self.complete <<= True

        self.counter = EpochCounter(self, name="counter")
        self.counter.link_from(self.trainer)
        self.counter.link_attrs(self.loader, "epoch_ended",
                                "epoch_number")
        self.repeater.link_from(self.counter)
        self.repeater.gate_block = self.counter.complete
        self.end_point.link_from(self.counter)
        self.end_point.gate_block = ~self.counter.complete

    def make_fused_runner(self):
        """BASELINE config 4 runs fused too: the SOM epoch compiles to
        one scan (train/som.py) instead of per-unit eager dispatch."""
        if getattr(self.loader.original_data, "mem", None) is None:
            return None
        offset = getattr(self.loader, "_global_offset", 0)
        if 0 < offset < self.loader.total_samples:
            # a mid-epoch snapshot resume must continue at the saved
            # minibatch — the eager loop does that exactly; the fused
            # epoch scan would replay the epoch from the top
            return None
        from veles_tpu.train.som import SOMFusedRunner
        return SOMFusedRunner(self)
