"""MNIST fully-connected workflow — BASELINE config 1.

The reference topology (Znicz MnistWorkflow: All2AllTanh 784→100 →
All2AllSoftmax 100→10, EvaluatorSoftmax, DecisionGD, GDSoftmax+GDTanh,
Repeater loop; published baseline 1.48% validation error,
``manualrst_veles_algorithms.rst:32``) built the veles_tpu way. The
same workflow object also powers the conv variant via ``layers`` config.

Data comes from a pluggable provider so tests inject synthetic digits
while production reads the real IDX files (see MnistIdxLoader).
"""

import gzip
import os
import struct

import numpy

from veles_tpu.accelerated_units import AcceleratedWorkflow
from veles_tpu.loader.fullbatch import ProviderLoader
from veles_tpu.nn.all2all import All2AllSoftmax, All2AllTanh
from veles_tpu.nn.decision import DecisionGD
from veles_tpu.nn.evaluator import EvaluatorSoftmax
from veles_tpu.nn.gd import GDSoftmax, GDTanh
from veles_tpu.plumbing import Repeater


def read_idx(path):
    """Parse an (optionally gzipped) IDX file (MNIST's native format)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = numpy.frombuffer(f.read(), dtype=numpy.uint8)
    return data.reshape(dims)


class MnistLoader(ProviderLoader):
    """Full-batch loader over a provider callable returning
    (train_data, train_labels, valid_data, valid_labels): flat
    (n, 784) by default, (n, 28, 28, 1) NHWC with ``flatten=False``."""

    hide_from_registry = True

    def __init__(self, workflow, provider=None, flatten=True, **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super(MnistLoader, self).__init__(workflow, provider=provider,
                                          flatten=flatten, **kwargs)


def mnist_idx_provider(directory):
    """Provider reading the standard 4 MNIST IDX files from a directory
    (t10k = validation, following the reference's split)."""
    def provide():
        def grab(stem):
            for name in (stem, stem + ".gz"):
                path = os.path.join(directory, name)
                if os.path.exists(path):
                    return read_idx(path)
            raise FileNotFoundError(stem)
        return (grab("train-images-idx3-ubyte"),
                grab("train-labels-idx1-ubyte"),
                grab("t10k-images-idx3-ubyte"),
                grab("t10k-labels-idx1-ubyte"))
    return provide


class MnistWorkflow(AcceleratedWorkflow):
    """784 → layers... → 10 softmax classifier with the Znicz loop."""

    hide_from_registry = True

    def __init__(self, workflow=None, provider=None, layers=(100,),
                 minibatch_size=60, learning_rate=0.1, weights_decay=0.0,
                 momentum=0.0, lr_decay=1.0, max_epochs=None,
                 fail_iterations=100, **kwargs):
        super(MnistWorkflow, self).__init__(workflow, **kwargs)

        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.loader = MnistLoader(self, provider=provider,
                                  minibatch_size=minibatch_size,
                                  name="MnistLoader")
        self.loader.link_from(self.repeater)

        # forward chain
        self.forwards = []
        src = self.loader
        src_attr = "minibatch_data"
        for width in layers:
            fwd = All2AllTanh(self, output_sample_shape=(width,),
                              name="fc%d" % len(self.forwards))
            fwd.link_from(src if not self.forwards else self.forwards[-1])
            fwd.link_attrs(src if not self.forwards else self.forwards[-1],
                           ("input", src_attr))
            self.forwards.append(fwd)
            src_attr = "output"
        head = All2AllSoftmax(self, output_sample_shape=(10,),
                              name="softmax")
        prev = self.forwards[-1] if self.forwards else self.loader
        head.link_from(prev)
        head.link_attrs(prev, ("input", src_attr))
        self.forwards.append(head)

        # evaluator + decision
        self.evaluator = EvaluatorSoftmax(self, name="evaluator")
        self.evaluator.link_from(head)
        self.evaluator.link_attrs(head, "output")
        self.evaluator.link_attrs(self.loader,
                                  ("labels", "minibatch_labels"))
        self.evaluator.link_attrs(self.loader,
                                  ("batch_size", "minibatch_size"))

        self.decision = DecisionGD(self, max_epochs=max_epochs,
                                   fail_iterations=fail_iterations,
                                   name="decision")
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "last_minibatch", "epoch_ended",
                                 "epoch_number", "class_lengths",
                                 "minibatch_size")
        self.decision.link_attrs(self.evaluator,
                                 ("minibatch_n_err", "n_err"))

        # backward chain (reverse order), gated off non-train minibatches
        self.gds = []
        err_src, err_attr = self.evaluator, "err_output"
        for fwd in reversed(self.forwards):
            gd_cls = GDSoftmax if fwd is head else GDTanh
            gd = gd_cls(self, forward=fwd, learning_rate=learning_rate,
                        weights_decay=weights_decay, momentum=momentum,
                        solver_hp={"lr_decay": lr_decay}
                        if lr_decay != 1.0 else {},
                        need_err_input=fwd is not self.forwards[0],
                        name="gd_" + fwd.name)
            gd.link_from(self.gds[-1] if self.gds else self.decision)
            gd.link_attrs(err_src, ("err_output", err_attr))
            gd.gate_skip = self.decision.gd_skip
            self.gds.append(gd)
            err_src, err_attr = gd, "err_input"

        self.repeater.link_from(self.gds[-1])
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete

    def set_testing(self, testing=True):
        """Forward-only mode: one epoch, no weight updates (``--test``)."""
        self.evaluator.testing = testing
        self.decision.testing = testing
        if testing:
            self.decision.complete.value = False
