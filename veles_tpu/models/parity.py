"""Shared builders for the accuracy-parity runs.

One source of truth for the FC and conv parity configs, used by BOTH
``scripts/parity_run.py`` (full budget, writes docs/PARITY_RUNS.md)
and ``tests/test_parity.py`` (reduced budget, asserted in CI) — so
the committed numbers and the continuously-tested configuration can
never silently diverge.
"""

from veles_tpu import prng
from veles_tpu.backends import Device
from veles_tpu.dummy import DummyLauncher
from veles_tpu.models.mnist import MnistLoader, MnistWorkflow
from veles_tpu.train import FusedTrainer

#: the conv topology of BASELINE config 2's analog
CONV_LAYERS = [
    {"type": "conv_relu", "n_kernels": 16, "kx": 5, "ky": 5},
    {"type": "max_pooling", "kx": 2, "ky": 2},
    {"type": "conv_relu", "n_kernels": 32, "kx": 5, "ky": 5},
    {"type": "max_pooling", "kx": 2, "ky": 2},
    {"type": "all2all_relu", "output_sample_shape": 100},
    {"type": "softmax", "output_sample_shape": 10},
]


def best_val(history):
    return min(h["validation"]["normalized"] for h in history)


def train_fc(provider, max_epochs, learning_rate=0.04, weights_decay=0.0,
             momentum=0.9, lr_decay=1.0, backend=None):
    """784-100-10 (BASELINE config 1); returns best validation error.

    Momentum 0.9 with the learning rate scaled down to keep the same
    effective step is the reference's mnist recipe shape (its configs
    drove GradientDescent with gradient_moment=0.9). Swept r4 on
    golden digits: lr 0.04 + mom 0.9 → 1.05% vs 2.60% for the r3
    momentum-free run (reference real-MNIST bar: 1.48%); lr ≥ 0.06
    with momentum diverges, decay ≤ 0.999 undertrains at 40 epochs
    (VERDICT r3 weak #2)."""
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = MnistWorkflow(DummyLauncher(), provider=provider, layers=(100,),
                       minibatch_size=100, learning_rate=learning_rate,
                       weights_decay=weights_decay, momentum=momentum,
                       lr_decay=lr_decay,
                       max_epochs=max_epochs)
    wf.initialize(device=Device(backend=backend))
    return best_val(FusedTrainer(wf).train())


def train_conv(provider, max_epochs, learning_rate=0.03, layers=None,
               backend=None):
    """Conv stack on 28x28 NHWC; returns best validation error."""
    from veles_tpu.standard_workflow import StandardWorkflow
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = StandardWorkflow(
        DummyLauncher(),
        loader=lambda w: MnistLoader(w, provider=provider, flatten=False,
                                     minibatch_size=100),
        layers=layers if layers is not None else CONV_LAYERS,
        loss="softmax", learning_rate=learning_rate,
        max_epochs=max_epochs)
    wf.initialize(device=Device(backend=backend))
    return best_val(FusedTrainer(wf).train())


def train_cifar(provider, max_epochs, learning_rate=0.01, backend=None):
    """CIFAR-shaped conv stack (BASELINE config 2: cifar10-quick
    topology + mean_disp normalization in the loader path) on the
    golden-objects analog; returns best validation error."""
    from veles_tpu.models.cifar import CifarWorkflow
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = CifarWorkflow(DummyLauncher(), provider=provider,
                       learning_rate=learning_rate,
                       max_epochs=max_epochs)
    wf.initialize(device=Device(backend=backend))
    return best_val(FusedTrainer(wf).train())
