"""Shared builders for the accuracy-parity runs.

One source of truth for the FC and conv parity configs, used by BOTH
``scripts/parity_run.py`` (full budget, writes docs/PARITY_RUNS.md)
and ``tests/test_parity.py`` (reduced budget, asserted in CI) — so
the committed numbers and the continuously-tested configuration can
never silently diverge.
"""

from veles_tpu import prng
from veles_tpu.backends import Device
from veles_tpu.dummy import DummyLauncher
from veles_tpu.models.mnist import MnistLoader, MnistWorkflow
from veles_tpu.train import FusedTrainer

#: the conv topology of BASELINE config 2's analog
CONV_LAYERS = [
    {"type": "conv_relu", "n_kernels": 16, "kx": 5, "ky": 5},
    {"type": "max_pooling", "kx": 2, "ky": 2},
    {"type": "conv_relu", "n_kernels": 32, "kx": 5, "ky": 5},
    {"type": "max_pooling", "kx": 2, "ky": 2},
    {"type": "all2all_relu", "output_sample_shape": 100},
    {"type": "softmax", "output_sample_shape": 10},
]


def best_val(history):
    return min(h["validation"]["normalized"] for h in history)


def train_fc(provider, max_epochs, learning_rate=0.04, weights_decay=0.0,
             momentum=0.9, lr_decay=1.0, backend=None):
    """784-100-10 (BASELINE config 1); returns best validation error.

    Momentum 0.9 with the learning rate scaled down to keep the same
    effective step is the reference's mnist recipe shape (its configs
    drove GradientDescent with gradient_moment=0.9). Swept r4 on
    golden digits: lr 0.04 + mom 0.9 → 1.05% vs 2.60% for the r3
    momentum-free run (reference real-MNIST bar: 1.48%); lr ≥ 0.06
    with momentum diverges, decay ≤ 0.999 undertrains at 40 epochs
    (VERDICT r3 weak #2)."""
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = MnistWorkflow(DummyLauncher(), provider=provider, layers=(100,),
                       minibatch_size=100, learning_rate=learning_rate,
                       weights_decay=weights_decay, momentum=momentum,
                       lr_decay=lr_decay,
                       max_epochs=max_epochs)
    wf.initialize(device=Device(backend=backend))
    return best_val(FusedTrainer(wf).train())


def train_conv(provider, max_epochs, learning_rate=0.03, layers=None,
               backend=None):
    """Conv stack on 28x28 NHWC; returns best validation error."""
    from veles_tpu.standard_workflow import StandardWorkflow
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = StandardWorkflow(
        DummyLauncher(),
        loader=lambda w: MnistLoader(w, provider=provider, flatten=False,
                                     minibatch_size=100),
        layers=layers if layers is not None else CONV_LAYERS,
        loss="softmax", learning_rate=learning_rate,
        max_epochs=max_epochs)
    wf.initialize(device=Device(backend=backend))
    return best_val(FusedTrainer(wf).train())


def train_ae(provider, max_epochs, bottleneck=100, learning_rate=0.001,
             momentum=0.9, minibatch_size=100, backend=None):
    """MNIST autoencoder (BASELINE config 4's AE half); returns best
    validation RMSE — the metric whose reference value is 0.5478 on
    real MNIST (``manualrst_veles_algorithms.rst:69``). Here RMSE =
    sqrt(mean-over-samples of per-sample feature-mean squared error)
    on linearly normalized inputs (nn/evaluator.py:_mse_eval).

    Recipe swept r5 on golden digits (12k/2k, 30 epochs): lr 0.001 +
    momentum 0.9 → 0.1617; lr 0.003 no momentum → 0.2134; lr ≥ 0.01
    diverges to NaN by epoch 2 (the 784-wide MSE head's gradients are
    ~30x a softmax head's). Mean-predictor floor: 0.3358."""
    from veles_tpu.models.mnist_ae import MnistAEWorkflow
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = MnistAEWorkflow(DummyLauncher(), provider=provider,
                         bottleneck=bottleneck,
                         minibatch_size=minibatch_size,
                         learning_rate=learning_rate,
                         momentum=momentum,
                         max_epochs=max_epochs)
    wf.initialize(device=Device(backend=backend))
    history = FusedTrainer(wf).train()
    # fused stats carry normalized = mean per-sample MSE; the eager
    # Decision path's metric_rmse is sqrt of the same quantity
    import math
    return math.sqrt(best_val(history))


def train_som(provider, epochs, sx=8, sy=8, minibatch_size=100,
              backend=None):
    """Kohonen SOM (BASELINE config 4's map half); returns the quality
    dict from :func:`veles_tpu.nn.kohonen.som_quality` measured on the
    TRAIN samples after ``epochs`` sweeps, plus the same metrics for
    the untrained random codebook (the teeth baseline)."""
    from veles_tpu.models.mnist import MnistLoader
    from veles_tpu.models.mnist_ae import KohonenWorkflow
    from veles_tpu.nn.kohonen import som_quality
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = KohonenWorkflow(
        DummyLauncher(),
        loader_factory=lambda w: MnistLoader(
            w, provider=provider, minibatch_size=minibatch_size),
        sx=sx, sy=sy, epochs=epochs)
    wf.initialize(device=Device(backend=backend))
    import numpy
    # TRAIN class only: ProviderLoader lays data out [valid, train]
    data = numpy.asarray(
        wf.loader.original_data.mem)[wf.loader.class_lengths[1]:]
    untrained = som_quality(
        numpy.asarray(wf.trainer.weights.map_read()), sx, sy, data)
    wf.run()
    trained = som_quality(
        numpy.asarray(wf.trainer.weights.map_read()), sx, sy, data)
    trained["untrained_quantization_error"] = \
        untrained["quantization_error"]
    trained["untrained_topographic_error"] = \
        untrained["topographic_error"]
    return trained


def train_cifar(provider, max_epochs, learning_rate=0.01, backend=None):
    """CIFAR-shaped conv stack (BASELINE config 2: cifar10-quick
    topology + mean_disp normalization in the loader path) on the
    golden-objects analog; returns best validation error."""
    from veles_tpu.models.cifar import CifarWorkflow
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = CifarWorkflow(DummyLauncher(), provider=provider,
                       learning_rate=learning_rate,
                       max_epochs=max_epochs)
    wf.initialize(device=Device(backend=backend))
    return best_val(FusedTrainer(wf).train())
