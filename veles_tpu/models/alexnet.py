"""ImageNet AlexNet workflow — BASELINE config 3, the north-star
benchmark model ("Znicz ImageNet-AlexNet samples/sec/chip").

The classic 5-conv/3-fc AlexNet expressed as StandardWorkflow layer
descriptors (conv+LRN+maxpool stages, dropout on the fc trunk, softmax
head), NHWC on the MXU. Data comes from a provider callable (synthetic
ImageNet-shaped tensors for benchmarking; a real ImageNet loader plugs
in the same way).
"""

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.standard_workflow import StandardWorkflow

ALEXNET_LAYERS = [
    # space_to_depth: exact same math, executed as a stride-1 conv on
    # 4x4-patch channels — the 3-channel input otherwise wastes the
    # MXU's reduction depth (measured −39% conv1 fwd+bwd, docs/PERF.md)
    {"type": "conv_str", "n_kernels": 96, "kx": 11, "ky": 11,
     "sliding": (4, 4), "padding": 2, "space_to_depth": True},
    {"type": "norm", "n": 5, "alpha": 1e-4, "beta": 0.75},
    {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
    {"type": "conv_str", "n_kernels": 256, "kx": 5, "ky": 5,
     "padding": 2},
    {"type": "norm", "n": 5, "alpha": 1e-4, "beta": 0.75},
    {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
    {"type": "conv_str", "n_kernels": 384, "kx": 3, "ky": 3,
     "padding": 1},
    {"type": "conv_str", "n_kernels": 384, "kx": 3, "ky": 3,
     "padding": 1},
    {"type": "conv_str", "n_kernels": 256, "kx": 3, "ky": 3,
     "padding": 1},
    {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
    {"type": "all2all_str", "output_sample_shape": 4096},
    {"type": "dropout", "dropout_ratio": 0.5},
    {"type": "all2all_str", "output_sample_shape": 4096},
    {"type": "dropout", "dropout_ratio": 0.5},
    {"type": "softmax", "output_sample_shape": 1000},
]


def small_alexnet_layers(n_classes=1000):
    """A proportionally shrunk AlexNet for tests/small chips."""
    return [
        {"type": "conv_str", "n_kernels": 16, "kx": 5, "ky": 5,
         "sliding": (2, 2)},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "conv_str", "n_kernels": 32, "kx": 3, "ky": 3},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "all2all_str", "output_sample_shape": 128},
        {"type": "dropout", "dropout_ratio": 0.5},
        {"type": "softmax", "output_sample_shape": n_classes},
    ]


class SyntheticImageLoader(FullBatchLoader):
    """ImageNet-shaped synthetic data (benchmarking / smoke tests).

    ``dtype="bfloat16"`` halves dataset HBM (the bench stores 16k
    ImageNet-shaped samples in ~5 GB this way; real image pipelines
    store uint8 — bf16 is the analogous TPU-native compression).
    Generation is CHUNKED: a single f64 rand() at that size would
    transiently hold 13 GB of host memory."""

    hide_from_registry = True

    def __init__(self, workflow, n_train=512, n_valid=128, side=227,
                 channels=3, n_classes=1000, seed=1, dtype="float32",
                 **kwargs):
        kwargs.setdefault("normalization_type", "none")
        super(SyntheticImageLoader, self).__init__(workflow, **kwargs)
        self._gen = (n_train, n_valid, side, channels, n_classes, seed,
                     dtype)

    def load_dataset(self):
        (n_train, n_valid, side, channels, n_classes, seed,
         dtype) = self._gen
        # generation is deterministic from self._gen, so the arrays are
        # disk-cached keyed by it (the 86-107 s bench "loader init
        # (generation)" phase collapses to a read on warm runs;
        # VELES_DATASET_CACHE=0 restores always-generate)
        from veles_tpu.loader.dataset_cache import cached_build
        arrays = cached_build(
            "synthetic-image",
            {"n_train": n_train, "n_valid": n_valid, "side": side,
             "channels": channels, "n_classes": n_classes,
             "seed": seed, "dtype": dtype},
            self._generate)
        self.original_data.reset(arrays["data"])
        self.original_labels.reset(arrays["labels"])
        self.class_lengths = [0, n_valid, n_train]

    def _generate(self):
        (n_train, n_valid, side, channels, n_classes, seed,
         dtype) = self._gen
        if dtype == "bfloat16":
            import ml_dtypes
            np_dtype = ml_dtypes.bfloat16
        else:
            np_dtype = numpy.dtype(dtype)
        rng = numpy.random.RandomState(seed)
        total = n_train + n_valid
        data = numpy.empty((total, side, side, channels), np_dtype)
        for start in range(0, total, 512):
            stop = min(start + 512, total)
            data[start:stop] = (rng.rand(
                stop - start, side, side, channels).astype(
                numpy.float32) * 2 - 1).astype(np_dtype)
        labels = rng.randint(0, n_classes, total).astype(numpy.int32)
        return {"data": data, "labels": labels}


class AlexNetWorkflow(StandardWorkflow):
    """AlexNet over any FullBatch image loader."""

    hide_from_registry = True

    def __init__(self, workflow=None, loader_factory=None, layers=None,
                 **kwargs):
        kwargs.setdefault("loss", "softmax")
        kwargs.setdefault("learning_rate", 0.01)
        kwargs.setdefault("momentum", 0.9)
        kwargs.setdefault("weights_decay", 5e-4)
        super(AlexNetWorkflow, self).__init__(
            workflow,
            loader=loader_factory or (lambda wf: SyntheticImageLoader(wf)),
            layers=layers if layers is not None else ALEXNET_LAYERS,
            **kwargs)
