"""Global configuration tree.

Re-designs the reference's auto-vivifying ``root`` config
(``veles/config.py:60-325``): attribute access creates nested nodes on
demand (``root.loader.minibatch_size = 60``), config files are plain
Python that mutates ``root``, ``update()`` deep-merges dicts, keys can be
``protect()``-ed against further writes, and site override files are
applied at import. An attribute that was merely *read* (auto-vivified)
is an empty node: ``validate()`` and ``get()`` treat it as undefined, so
typos in workflow configs fail fast instead of training with defaults.
"""

import os
import runpy
import threading

from veles_tpu.envknob import env_knob


class Config(object):
    """One node of the configuration tree.

    Attribute reads auto-vivify child nodes; reading a node where a value
    was expected raises ``AttributeError`` from :meth:`validate` (the
    reference's undefined-leaf detection, ``veles/config.py:165-176``).
    """

    __slots__ = ("__dict__",)

    def __init__(self, path="root", **values):
        object.__setattr__(self, "__dict__", {
            "_path_": path, "_protected_": set()})
        for key, value in values.items():
            setattr(self, key, value)

    # -- tree construction ------------------------------------------------

    def __getattr__(self, name):
        if name.startswith("_") and name.endswith("_"):
            raise AttributeError(name)
        child = Config("%s.%s" % (self._path_, name))
        self.__dict__[name] = child
        return child

    def __setattr__(self, name, value):
        if name in self._protected_:
            raise AttributeError(
                "config key %s.%s is protected" % (self._path_, name))
        if isinstance(value, dict):
            node = self.__dict__.get(name)
            if not isinstance(node, Config):
                node = Config("%s.%s" % (self._path_, name))
                self.__dict__[name] = node
            node.update(value)
            return
        self.__dict__[name] = value

    # -- dict-ish access --------------------------------------------------

    def __getitem__(self, name):
        return getattr(self, name)

    def __setitem__(self, name, value):
        setattr(self, name, value)

    def __contains__(self, name):
        return name in self.keys()

    def keys(self):
        return [k for k, v in self.__dict__.items()
                if not (k.startswith("_") and k.endswith("_"))]

    def items(self):
        return [(k, self.__dict__[k]) for k in self.keys()]

    @staticmethod
    def _is_defined(value):
        # an empty Config child means the name was only ever *read*
        return not (isinstance(value, Config) and not value.keys())

    def get(self, name, default=None):
        """Read a leaf without vivifying it."""
        value = self.__dict__.get(name, default)
        return value if Config._is_defined(value) else default

    def update(self, tree):
        """Deep-merge a dict (or another Config) into this node."""
        if isinstance(tree, Config):
            tree = tree.to_dict()
        if not isinstance(tree, dict):
            raise TypeError("update() needs a dict, got %s" % type(tree))
        for key, value in tree.items():
            setattr(self, key, value)
        return self

    def to_dict(self):
        out = {}
        for key, value in self.items():
            out[key] = value.to_dict() if isinstance(value, Config) else value
        return out

    # -- integrity --------------------------------------------------------

    def protect(self, *names):
        """Forbid future writes to the named direct children."""
        self._protected_.update(names)

    def validate(self, *required):
        """Raise if any of the named leaves was never assigned."""
        missing = [n for n in required
                   if n not in self.__dict__ or
                   not Config._is_defined(self.__dict__[n])]
        if missing:
            raise AttributeError(
                "undefined config value(s) %s under %s" %
                (", ".join(missing), self._path_))

    def print_(self, indent=0, file=None):
        import sys
        file = file or sys.stdout
        for key, value in sorted(self.items()):
            if isinstance(value, Config):
                print("%s%s:" % ("  " * indent, key), file=file)
                value.print_(indent + 1, file)
            else:
                print("%s%s: %r" % ("  " * indent, key, value), file=file)

    def __repr__(self):
        return "<Config %s: %s>" % (self._path_, ", ".join(self.keys()))

    # Config nodes appear inside pickled workflows (snapshots).
    def __getstate__(self):
        return {"path": self._path_, "tree": self.to_dict()}

    def __setstate__(self, state):
        object.__setattr__(self, "__dict__", {
            "_path_": state["path"], "_protected_": set()})
        self.update(state["tree"])


#: The global configuration tree every workflow/config file mutates.
root = Config("root")

_config_lock = threading.Lock()


def _init_defaults():
    """Platform defaults (the reference's ``veles/config.py:178-291``)."""
    home = os.path.join(os.path.expanduser("~"), ".veles_tpu")
    root.common.update({
        "dirs": {
            "veles": os.path.dirname(os.path.abspath(__file__)),
            "user": home,
            "cache": os.path.join(home, "cache"),
            "snapshots": os.path.join(home, "snapshots"),
            "datasets": os.path.join(home, "datasets"),
        },
        "engine": {
            "backend": env_knob("VELES_TPU_BACKEND", "auto"),
            # fp precision policy: compute dtype for MXU matmuls and the
            # accumulation discipline replacing the reference's
            # PRECISION_LEVEL 0/1/2 (``veles/config.py:244-248``).
            "precision_type": env_knob("VELES_PRECISION", "float32"),
            "precision_level": env_knob("VELES_PRECISION_LEVEL", 0,
                                        parse=int),
        },
        "trace": {"run": False, "misprints": False},
        "timings": False,
        "exceptions": {"run_after_stop": True},
        "disable": {"plotting": "DISPLAY" not in os.environ,
                    "publishing": False, "snapshotting": False},
        "random_seed": None,
        "web": {"host": "localhost", "port": 8090,
                "notification_interval": 1.0},
        "api": {"host": "localhost", "port": 8180, "path": "/api"},
        "forge": {"service_name": "forge", "manifest": "manifest.json"},
        "ensemble": {"model_index": 0, "size": 0, "train_ratio": 1.0},
        "graphics": {"multicast_address": "239.192.1.1", "blacklisted_ifs": []},
    })


def apply_config_file(path, context=None):
    """Execute a Python config file that mutates ``root``.

    The reference runs config files via ``runpy`` with ``root`` injected
    (``veles/__main__.py:426-472``); same contract here.
    """
    with _config_lock:
        runpy.run_path(path, init_globals=dict(
            {"root": root}, **(context or {})))
    return root


def apply_overrides(pairs):
    """Apply CLI ``key=value`` overrides (evaluated as Python literals)."""
    import ast
    for pair in pairs:
        key, _, expr = pair.partition("=")
        if not _:
            raise ValueError("override %r is not key=value" % pair)
        try:
            value = ast.literal_eval(expr)
        except (ValueError, SyntaxError):
            value = expr
        node = root
        parts = key.strip().split(".")
        if parts[0] == "root":
            parts = parts[1:]
        for part in parts[:-1]:
            node = getattr(node, part)
        setattr(node, parts[-1], value)


def _apply_site_overrides():
    """Site override chain (``veles/config.py:293-308``): /etc, home, CWD."""
    for candidate in ("/etc/default/veles_tpu",
                      os.path.join(os.path.expanduser("~"), ".veles_tpu",
                                   "site_config.py"),
                      os.path.join(os.getcwd(), "site_config.py")):
        if os.path.isfile(candidate):
            try:
                apply_config_file(candidate)
            except Exception as exc:  # site files must never brick startup
                import logging
                logging.getLogger("config").warning(
                    "failed to apply site config %s: %s", candidate, exc)


_init_defaults()
_apply_site_overrides()
