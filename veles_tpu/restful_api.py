"""RESTful inference API (re-designs ``veles/restful_api.py:78-217``).

Turns a trained workflow into an HTTP service: ``POST <path>`` with a
JSON body ``{"input": <data>, "codec": "list"|"base64"[, "shape": [...],
"type": "float32"]}`` feeds the decoded sample into the workflow's
:class:`~veles_tpu.loader.restful.RestfulLoader`, the forward pass runs,
and the response is ``{"result": <output row>}``. Malformed requests get
``{"error": ...}`` with HTTP 400 — the same request contract (codec
validation, base64 shape/type requirements) as the reference.

The reference served through Twisted's reactor; here the server is a
stdlib :class:`~http.server.ThreadingHTTPServer` on a daemon thread —
requests rendezvous with the workflow's run loop through the loader's
feed queue and a matching FIFO of pending responses. Beyond the
reference: admission is bounded (``max_pending``; excess requests get
an immediate 503 + ``Retry-After`` instead of blocking), responses
echo the request's opaque ``"id"`` so concurrent clients can
correlate, and one forward pass answers up to ``batch_size`` pending
requests when the loader serves coalesced fills (link it:
``api.link_attrs(loader, ("batch_size", "minibatch_size"))``).
For production serving traffic, prefer the dedicated dynamic-batching
engine in :mod:`veles_tpu.serving` (``docs/SERVING.md``), which shares
this module's request contract via :func:`parse_payload`.

Wiring (see ``tests/test_restful.py``)::

    loader = RestfulLoader(wf, sample_shape=...)
    api = RESTfulAPI(wf, port=0)
    api.link_from(last_forward)
    api.link_attrs(last_forward, ("input", "output"))
    api.feed = loader.feed
"""

import base64
import binascii
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy

from veles_tpu.config import root
from veles_tpu.distributable import TriviallyDistributable
from veles_tpu.telemetry import tracing
from veles_tpu.units import Unit


class _NumpyJSONEncoder(json.JSONEncoder):
    """Serializes numpy scalars/arrays (``veles/json_encoders.py``)."""

    def default(self, obj):
        if isinstance(obj, numpy.ndarray):
            return obj.tolist()
        if isinstance(obj, numpy.integer):
            return int(obj)
        if isinstance(obj, numpy.floating):
            return float(obj)
        return super(_NumpyJSONEncoder, self).default(obj)


def respond_json(handler, code, payload, headers=None):
    """Write one JSON response (numpy-aware) with Content-Length and
    optional extra headers — the response half of the request contract,
    shared by this unit and the serving frontend."""
    body = json.dumps(payload, cls=_NumpyJSONEncoder).encode("utf-8")
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for key, value in (headers or {}).items():
        handler.send_header(key, value)
    handler.end_headers()
    handler.wfile.write(body)


def decode_base64_payload(request):
    """The base64 codec: needs "shape" and "type" attributes.

    Returns ``(array, None)`` or ``(None, error_message)``; shared by
    the workflow-riding API and the serving frontend
    (``veles_tpu/serving/frontend.py``)."""
    if "shape" not in request:
        return None, ("There is no \"shape\" attribute which "
                      "defines the input array shape")
    shape = request["shape"]
    if not isinstance(shape, list) or len(shape) < 1:
        return None, "\"shape\" must be a non-trivial array"
    if request.get("type") is None:
        return None, ("There is no \"type\" attribute which "
                      "defines the array data type (e.g., "
                      "\"float32\" or \"uint8\", see numpy.dtype)")
    dtype_name = request["type"]
    if not isinstance(dtype_name, str):
        return None, "\"type\" must be a string dtype name"
    byte_order = None
    if dtype_name and dtype_name[-1] in "<=>":
        byte_order = dtype_name[-1]
        dtype_name = dtype_name[:-1]
    try:
        dtype = numpy.dtype(dtype_name)
    except TypeError:
        return None, ("Invalid \"type\" value. For the list of "
                      "supported values, see numpy.dtype.")
    if byte_order is not None:
        dtype = dtype.newbyteorder(byte_order)
    try:
        buf = base64.b64decode(request["input"])
    except (binascii.Error, TypeError) as e:
        return None, "Failed to decode base64: %s." % e
    try:
        return numpy.frombuffer(buf, dtype).reshape(shape), None
    except Exception as e:
        return None, "Failed to create the numpy array: %s." % e


def parse_payload(request):
    """Validate + decode one ``{"input":..., "codec":...}`` request.

    Returns ``(array, None)`` on success, ``(None, error_message)``
    otherwise — the single source of the request contract for both
    HTTP services."""
    if not isinstance(request, dict) or "input" not in request \
            or "codec" not in request:
        return None, ("Invalid input format: there must be "
                      "\"input\" and \"codec\" attributes")
    codec = request["codec"]
    if codec not in ("list", "base64"):
        return None, ("Invalid codec value: must be either "
                      "\"list\" or \"base64\"")
    if codec == "list":
        try:
            return numpy.array(request["input"], numpy.float32), None
        except (TypeError, ValueError):
            return None, "Invalid input array format"
    return decode_base64_payload(request)


class _APIHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route to the unit's logger
        self.server.api.debug("http: " + fmt, *args)

    def do_POST(self):
        self.server.api.serve(self)


class _APIServer(ThreadingHTTPServer):
    daemon_threads = True
    # the stdlib default accept backlog (5) drops concurrent connect
    # bursts into kernel SYN retransmit stalls; an inference endpoint
    # must accept the burst and shed load at the application layer
    # (max_pending -> 503) where the client gets a real answer
    request_queue_size = 128


class RESTfulAPI(Unit, TriviallyDistributable):
    """Serves the owning workflow's forward pass over HTTP.

    Demands ``feed`` (the loader's feed method) and ``input`` (the last
    forward's output Array). ``result_transform``, if given, maps the
    raw output row to the response payload (e.g. argmax labeling).
    """

    def __init__(self, workflow, **kwargs):
        kwargs["view_group"] = "SERVICE"
        super(RESTfulAPI, self).__init__(workflow, **kwargs)
        self.host = kwargs.get("host", root.common.api.host)
        self.port = kwargs.get("port", root.common.api.port)
        self.path = kwargs.get("path", root.common.api.path)
        self.result_transform = kwargs.get("result_transform", None)
        #: seconds a request waits for the workflow before HTTP 500
        self.response_timeout = kwargs.get("response_timeout", 60.0)
        #: admission bound: further requests get 503 + Retry-After
        #: instead of blocking unboundedly behind the feed queue
        self.max_pending = kwargs.get("max_pending", 128)
        #: how many responses one forward pass answers; link to the
        #: loader's ``minibatch_size`` when it serves batched fills
        #: (``api.link_attrs(loader, ("batch_size", "minibatch_size"))``)
        self.batch_size = 1
        self.address = None
        self.demand("feed", "input")

    def init_unpickled(self):
        super(RESTfulAPI, self).init_unpickled()
        self._server_ = None
        self._pending_ = []
        self._pending_lock_ = threading.Lock()

    # -- validated properties (reference parity) --------------------------

    @property
    def port(self):
        return self._port

    @port.setter
    def port(self, value):
        if not isinstance(value, int):
            raise ValueError("port must be an integer (got %s)" % type(value))
        if value < 0 or value > 65535:
            raise ValueError("port is out of range (%d)" % value)
        self._port = value

    @property
    def path(self):
        return self._path

    @path.setter
    def path(self, value):
        if not value.startswith("/"):
            raise ValueError("Invalid path: %s" % value)
        self._path = value

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, **kwargs):
        self._server_ = _APIServer((self.host, self.port), _APIHandler)
        self._server_.api = self
        self.address = self._server_.server_address
        self.port = self.address[1]
        thread = threading.Thread(target=self._server_.serve_forever,
                                  daemon=True, name="%s-http" % self.name)
        thread.start()
        # stop serving (and unblock waiters) the moment the workflow ends
        from veles_tpu.workflow import Workflow
        if isinstance(self.workflow, Workflow):
            self.workflow.add_finished_callback(self.stop)
        self.info("listening on %s:%d%s", self.host, self.port, self.path)

    def stop(self):
        if self._server_ is not None:
            self._server_.shutdown()
            self._server_.server_close()
            self._server_ = None
        # unblock any requests still waiting on the workflow
        with self._pending_lock_:
            pending, self._pending_ = self._pending_, []
        for slot in pending:
            slot["error"] = "service stopped"
            slot["event"].set()

    # -- workflow side -----------------------------------------------------

    def run(self):
        """One forward pass finished: answer the oldest request(s).

        With a batched loader (``batch_size`` linked to the loader's
        ``minibatch_size``) one pass answers up to ``batch_size``
        requests — row *i* of the output belongs to the *i*-th oldest
        pending slot, because feeds and slot appends happen atomically
        under one lock in queue order."""
        try:
            count = max(1, int(self.batch_size))
        except (TypeError, ValueError):
            count = 1
        with self._pending_lock_:
            if not self._pending_:
                return  # e.g. the EOF minibatch that stops the loop
            count = min(count, len(self._pending_))
            slots, self._pending_ = (self._pending_[:count],
                                     self._pending_[count:])
        out = numpy.array(self.input.map_read()[:count], copy=True)
        for i, slot in enumerate(slots):
            if slot["abandoned"]:
                # its client already got a 504; the slot stayed in the
                # FIFO so sample<->response correlation survives
                continue
            row = out[i]
            slot["result"] = (self.result_transform(row)
                              if self.result_transform is not None
                              else row)
            slot["event"].set()

    # -- HTTP side ---------------------------------------------------------

    @staticmethod
    def _respond(handler, code, payload, headers=None):
        respond_json(handler, code, payload, headers=headers)

    def fail(self, handler, message, code=400, rid=None, headers=None):
        self.warning(message)
        payload = {"error": message}
        if rid is not None:
            payload["id"] = rid
        self._respond(handler, code, payload, headers=headers)

    def serve(self, handler):
        """Runs on the HTTP thread: decode, feed, wait, respond."""
        # drain the body before ANY fail path: on a keep-alive
        # connection unread body bytes would be parsed as the next
        # request line, corrupting the client's following request
        if handler.headers.get("Transfer-Encoding"):
            # chunked bodies can't be drained by length — and a request
            # carrying BOTH headers is the classic smuggling shape
            # (RFC 7230: TE wins) — so reject either way and close
            # before stray chunk bytes corrupt the next request
            handler.close_connection = True
            self.fail(handler, "Content-Length required "
                               "(Transfer-Encoding is not supported)",
                      code=411)
            return
        try:
            length = int(handler.headers.get("Content-Length", 0))
            raw = handler.rfile.read(length)
        except (TypeError, ValueError):
            handler.close_connection = True
            self.fail(handler, "Invalid Content-Length")
            return
        if handler.path != self.path:
            self.fail(handler, "API path %s is not supported" % handler.path,
                      code=404)
            return
        ctype = (handler.headers.get("Content-Type") or "").split(";")[0]
        if ctype.strip() != "application/json":
            self.fail(handler, "Unsupported Content-Type (must be "
                               "\"application/json\")")
            return
        try:
            request = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            self.fail(handler, "Failed to parse JSON")
            return
        # the request-id echo: concurrent clients correlate responses
        # to requests by their own opaque "id" value
        rid = request.get("id") if isinstance(request, dict) else None
        # the same id (or an X-Request-Id header) doubles as the trace
        # id of this request's span in --trace-out dumps
        trace_id = tracing.trace_id_from_request(handler.headers, rid)
        with tracing.request_span("http:%s" % self.path,
                                  trace_id=trace_id):
            self._serve_parsed(handler, request, rid)

    def _serve_parsed(self, handler, request, rid):
        data, error = parse_payload(request)
        if error is not None:
            self.fail(handler, error, rid=rid)
            return
        slot = {"event": threading.Event(), "result": None, "error": None,
                "abandoned": False}
        # feed + pending append under one lock: the loader queue and the
        # response FIFO must agree on ordering across HTTP threads
        feed_error = None
        stopped = False
        overloaded = False
        with self._pending_lock_:
            if self._server_ is None:
                # stop() already drained _pending_; feeding now would
                # block this client for the whole response_timeout
                stopped = True
            elif self.max_pending and \
                    len(self._pending_) >= self.max_pending:
                # fail fast instead of stacking blocked HTTP threads
                # behind a workflow that is already saturated
                overloaded = True
            else:
                try:
                    self.feed(data)
                except Exception as e:
                    feed_error = str(e) or type(e).__name__
                else:
                    self._pending_.append(slot)
        if stopped:
            self.fail(handler, "service stopped", code=503, rid=rid,
                      headers={"Retry-After": "5"})
            return
        if overloaded:
            self.fail(handler, "service overloaded: %d requests already "
                               "pending" % self.max_pending,
                      code=503, rid=rid, headers={"Retry-After": "1"})
            return
        if feed_error is not None:
            self.fail(handler, "Invalid input value: %s" % feed_error,
                      rid=rid)
            return
        if not slot["event"].wait(self.response_timeout):
            # do NOT remove the slot: the sample is already in the
            # loader queue, so run() must still pop this slot when the
            # pass completes or every later client would get the
            # previous request's result
            with self._pending_lock_:
                slot["abandoned"] = True
            self.fail(handler, "The workflow did not respond in time",
                      code=500, rid=rid)
            return
        if slot["error"] is not None:
            self.fail(handler, slot["error"], code=500, rid=rid)
            return
        payload = {"result": slot["result"]}
        if rid is not None:
            payload["id"] = rid
        self._respond(handler, 200, payload)
