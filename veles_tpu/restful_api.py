"""RESTful inference API (re-designs ``veles/restful_api.py:78-217``).

Turns a trained workflow into an HTTP service: ``POST <path>`` with a
JSON body ``{"input": <data>, "codec": "list"|"base64"[, "shape": [...],
"type": "float32"]}`` feeds the decoded sample into the workflow's
:class:`~veles_tpu.loader.restful.RestfulLoader`, the forward pass runs,
and the response is ``{"result": <output row>}``. Malformed requests get
``{"error": ...}`` with HTTP 400 — the same request contract (codec
validation, base64 shape/type requirements) as the reference.

The reference served through Twisted's reactor; here the server is a
stdlib :class:`~http.server.ThreadingHTTPServer` on a daemon thread —
the workflow side stays single-dispatch (the TPU-friendly scheduler in
:mod:`veles_tpu.workflow`), requests rendezvous with it through the
loader's feed queue and a matching FIFO of pending responses.

Wiring (see ``tests/test_restful.py``)::

    loader = RestfulLoader(wf, sample_shape=...)
    api = RESTfulAPI(wf, port=0)
    api.link_from(last_forward)
    api.link_attrs(last_forward, ("input", "output"))
    api.feed = loader.feed
"""

import base64
import binascii
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy

from veles_tpu.config import root
from veles_tpu.distributable import TriviallyDistributable
from veles_tpu.units import Unit


class _NumpyJSONEncoder(json.JSONEncoder):
    """Serializes numpy scalars/arrays (``veles/json_encoders.py``)."""

    def default(self, obj):
        if isinstance(obj, numpy.ndarray):
            return obj.tolist()
        if isinstance(obj, numpy.integer):
            return int(obj)
        if isinstance(obj, numpy.floating):
            return float(obj)
        return super(_NumpyJSONEncoder, self).default(obj)


class _APIHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route to the unit's logger
        self.server.api.debug("http: " + fmt, *args)

    def do_POST(self):
        self.server.api.serve(self)


class RESTfulAPI(Unit, TriviallyDistributable):
    """Serves the owning workflow's forward pass over HTTP.

    Demands ``feed`` (the loader's feed method) and ``input`` (the last
    forward's output Array). ``result_transform``, if given, maps the
    raw output row to the response payload (e.g. argmax labeling).
    """

    def __init__(self, workflow, **kwargs):
        kwargs["view_group"] = "SERVICE"
        super(RESTfulAPI, self).__init__(workflow, **kwargs)
        self.host = kwargs.get("host", root.common.api.host)
        self.port = kwargs.get("port", root.common.api.port)
        self.path = kwargs.get("path", root.common.api.path)
        self.result_transform = kwargs.get("result_transform", None)
        #: seconds a request waits for the workflow before HTTP 500
        self.response_timeout = kwargs.get("response_timeout", 60.0)
        self.address = None
        self.demand("feed", "input")

    def init_unpickled(self):
        super(RESTfulAPI, self).init_unpickled()
        self._server_ = None
        self._pending_ = []
        self._pending_lock_ = threading.Lock()

    # -- validated properties (reference parity) --------------------------

    @property
    def port(self):
        return self._port

    @port.setter
    def port(self, value):
        if not isinstance(value, int):
            raise ValueError("port must be an integer (got %s)" % type(value))
        if value < 0 or value > 65535:
            raise ValueError("port is out of range (%d)" % value)
        self._port = value

    @property
    def path(self):
        return self._path

    @path.setter
    def path(self, value):
        if not value.startswith("/"):
            raise ValueError("Invalid path: %s" % value)
        self._path = value

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, **kwargs):
        self._server_ = ThreadingHTTPServer(
            (self.host, self.port), _APIHandler)
        self._server_.api = self
        self._server_.daemon_threads = True
        self.address = self._server_.server_address
        self.port = self.address[1]
        thread = threading.Thread(target=self._server_.serve_forever,
                                  daemon=True, name="%s-http" % self.name)
        thread.start()
        # stop serving (and unblock waiters) the moment the workflow ends
        from veles_tpu.workflow import Workflow
        if isinstance(self.workflow, Workflow):
            self.workflow.add_finished_callback(self.stop)
        self.info("listening on %s:%d%s", self.host, self.port, self.path)

    def stop(self):
        if self._server_ is not None:
            self._server_.shutdown()
            self._server_.server_close()
            self._server_ = None
        # unblock any requests still waiting on the workflow
        with self._pending_lock_:
            pending, self._pending_ = self._pending_, []
        for slot in pending:
            slot["error"] = "service stopped"
            slot["event"].set()

    # -- workflow side -----------------------------------------------------

    def run(self):
        """One forward pass finished: answer the oldest request."""
        with self._pending_lock_:
            if not self._pending_:
                return  # e.g. the EOF minibatch that stops the loop
            slot = self._pending_.pop(0)
        if slot["abandoned"]:
            # its client already got a 504; the slot stayed in the FIFO
            # so sample<->response correlation survives the timeout
            return
        out = numpy.array(self.input.map_read()[0], copy=True)
        slot["result"] = (self.result_transform(out)
                          if self.result_transform is not None else out)
        slot["event"].set()

    # -- HTTP side ---------------------------------------------------------

    @staticmethod
    def _respond(handler, code, payload):
        body = json.dumps(payload, cls=_NumpyJSONEncoder).encode("utf-8")
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def fail(self, handler, message, code=400):
        self.warning(message)
        self._respond(handler, code, {"error": message})

    def _decode_base64(self, handler, request, input_obj):
        """The base64 codec: needs "shape" and "type" attributes."""
        if "shape" not in request:
            self.fail(handler, "There is no \"shape\" attribute which "
                               "defines the input array shape")
            return None
        shape = request["shape"]
        if not isinstance(shape, list) or len(shape) < 1:
            self.fail(handler, "\"shape\" must be a non-trivial array")
            return None
        if request.get("type") is None:
            self.fail(handler, "There is no \"type\" attribute which "
                               "defines the array data type (e.g., "
                               "\"float32\" or \"uint8\", see numpy.dtype)")
            return None
        dtype_name = request["type"]
        if not isinstance(dtype_name, str):
            self.fail(handler, "\"type\" must be a string dtype name")
            return None
        byte_order = None
        if dtype_name and dtype_name[-1] in "<=>":
            byte_order = dtype_name[-1]
            dtype_name = dtype_name[:-1]
        try:
            dtype = numpy.dtype(dtype_name)
        except TypeError:
            self.fail(handler, "Invalid \"type\" value. For the list of "
                               "supported values, see numpy.dtype.")
            return None
        if byte_order is not None:
            dtype = dtype.newbyteorder(byte_order)
        try:
            buf = base64.b64decode(input_obj)
        except (binascii.Error, TypeError) as e:
            self.fail(handler, "Failed to decode base64: %s." % e)
            return None
        try:
            return numpy.frombuffer(buf, dtype).reshape(shape)
        except Exception as e:
            self.fail(handler, "Failed to create the numpy array: %s." % e)
            return None

    def serve(self, handler):
        """Runs on the HTTP thread: decode, feed, wait, respond."""
        # drain the body before ANY fail path: on a keep-alive
        # connection unread body bytes would be parsed as the next
        # request line, corrupting the client's following request
        if handler.headers.get("Transfer-Encoding"):
            # chunked bodies can't be drained by length — and a request
            # carrying BOTH headers is the classic smuggling shape
            # (RFC 7230: TE wins) — so reject either way and close
            # before stray chunk bytes corrupt the next request
            handler.close_connection = True
            self.fail(handler, "Content-Length required "
                               "(Transfer-Encoding is not supported)",
                      code=411)
            return
        try:
            length = int(handler.headers.get("Content-Length", 0))
            raw = handler.rfile.read(length)
        except (TypeError, ValueError):
            handler.close_connection = True
            self.fail(handler, "Invalid Content-Length")
            return
        if handler.path != self.path:
            self.fail(handler, "API path %s is not supported" % handler.path,
                      code=404)
            return
        ctype = (handler.headers.get("Content-Type") or "").split(";")[0]
        if ctype.strip() != "application/json":
            self.fail(handler, "Unsupported Content-Type (must be "
                               "\"application/json\")")
            return
        try:
            request = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            self.fail(handler, "Failed to parse JSON")
            return
        if not isinstance(request, dict) or "input" not in request \
                or "codec" not in request:
            self.fail(handler, "Invalid input format: there must be "
                               "\"input\" and \"codec\" attributes")
            return
        codec = request["codec"]
        if codec not in ("list", "base64"):
            self.fail(handler, "Invalid codec value: must be either "
                               "\"list\" or \"base64\"")
            return
        if codec == "list":
            try:
                data = numpy.array(request["input"], numpy.float32)
            except (TypeError, ValueError):
                self.fail(handler, "Invalid input array format")
                return
        else:
            data = self._decode_base64(handler, request, request["input"])
            if data is None:
                return
        slot = {"event": threading.Event(), "result": None, "error": None,
                "abandoned": False}
        # feed + pending append under one lock: the loader queue and the
        # response FIFO must agree on ordering across HTTP threads
        feed_error = None
        stopped = False
        with self._pending_lock_:
            if self._server_ is None:
                # stop() already drained _pending_; feeding now would
                # block this client for the whole response_timeout
                stopped = True
            else:
                try:
                    self.feed(data)
                except Exception as e:
                    feed_error = str(e) or type(e).__name__
                else:
                    self._pending_.append(slot)
        if stopped:
            self.fail(handler, "service stopped", code=503)
            return
        if feed_error is not None:
            self.fail(handler, "Invalid input value: %s" % feed_error)
            return
        if not slot["event"].wait(self.response_timeout):
            # do NOT remove the slot: the sample is already in the
            # loader queue, so run() must still pop this slot when the
            # pass completes or every later client would get the
            # previous request's result
            with self._pending_lock_:
                slot["abandoned"] = True
            self.fail(handler, "The workflow did not respond in time",
                      code=500)
            return
        if slot["error"] is not None:
            self.fail(handler, slot["error"], code=500)
            return
        self._respond(handler, 200, {"result": slot["result"]})
