"""The step compiler: unit chain -> one XLA computation.

Contract with model workflows (MnistWorkflow et al. follow it):

* ``wf.loader``     — FullBatchLoader-like: device-resident
  ``original_data``/``original_labels``(/``original_targets``),
  ``shuffled_indices``, ``class_lengths``, ``max_minibatch_size``;
* ``wf.forwards``   — ordered ForwardBase list (pure ``apply``);
* ``wf.evaluator``  — EvaluatorSoftmax or EvaluatorMSE (selects loss);
* ``wf.gds``        — GD units (reverse order), giving each layer's
  solver + hyper-parameters;
* ``wf.decision``   — stop criterion (max_epochs / fail_iterations).

The compiled functions:

* ``train_segment(params, states, idx_matrix)`` — ``lax.scan`` over
  minibatches: gather → forward → loss → grad → per-layer solver
  update. On accelerators params/opt-states are donated, so weights
  stay in HBM across the whole segment with zero host traffic; on the
  CPU backend donation is OFF by default (``VELES_DONATE`` overrides)
  because this jaxlib's CPU client corrupts the heap under it — see
  :meth:`FusedTrainer._resolve_donate`;
* ``eval_segment(params, idx_matrix)`` — forward-only scan.

Epoch order mirrors the eager path (validation before train), so loss
curves are comparable run-to-run.

Training math parity: gradients here are d(mean CE)/dθ with padded rows
masked — identical to EvaluatorSoftmax's ``(p - onehot)/batch`` seed
through the GD chain.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy

from veles_tpu import prng
from veles_tpu.envknob import env_flag, env_knob
from veles_tpu.loader import prefetch
from veles_tpu.loader.base import TEST, TRAIN, VALIDATION, CLASS_NAMES
from veles_tpu.logger import Logger
from veles_tpu.nn.dropout import DropoutForward
from veles_tpu.nn.evaluator import EvaluatorMSE, EvaluatorSoftmax
from veles_tpu.nn.optim import get_solver
from veles_tpu.telemetry import profiler, tracing


class FusedTrainer(Logger):
    """Compiles and drives the fused train/eval loop of a workflow.

    Dataset residency generalizes the old all-or-nothing staging:
    *staged-resident* when the dataset fits the device budget (the
    pre-existing path, including the space-to-depth staging pack),
    *streamed* when it doesn't — fixed-size shards are host-gathered
    and transferred through :mod:`veles_tpu.loader.prefetch`'s
    double-buffered staging ring while the previous shard computes,
    so datasets larger than HBM train out-of-core instead of OOMing.
    ``stream=None`` auto-decides (``VELES_STREAM`` /
    ``VELES_DEVICE_BUDGET_MB`` override); True/False force.

    MODEL state gets the same treatment (ISSUE 17,
    :mod:`veles_tpu.train.offload`): when the params + optimizer state
    exceed the device budget (or ``VELES_OFFLOAD``/``offload=True``
    force it), the master copies stay on host and the step walks layer
    groups through a double-buffered staging ring — H2D prefetch of
    group k+1 overlaps group k's compute, updated groups retire D2H on
    a writeback thread. The loss curve is bit-identical to the in-core
    run (pinned by tests/test_offload.py). Offload composes with a
    RESIDENT dataset only; a streamed dataset wins the ring.
    """

    #: cost-book op namespace: parallel trainers that compile a
    #: DIFFERENT program for the same sweep (the GSPMD path's
    #: partitioned step, ISSUE 15) prefix their op names so their
    #: cost/collective-bytes rows never mix with the single-device
    #: program's — the runner reads this too
    _op_prefix = ""

    def __init__(self, workflow, donate=None, stage_s2d=True,
                 grad_norms=None, stream=None, prefetch_depth=None,
                 prefetch_workers=None, offload=None,
                 offload_depth=None, offload_workers=None):
        super(FusedTrainer, self).__init__()
        self.workflow = workflow
        self.loader = workflow.loader
        self.forwards = list(workflow.forwards)
        self.evaluator = workflow.evaluator
        self.decision = workflow.decision
        self.donate = self._resolve_donate(donate)
        self.stage_s2d = stage_s2d
        self.stream = stream
        self.prefetch_depth = prefetch_depth
        self.prefetch_workers = prefetch_workers
        #: model-state residency (ISSUE 17): ``None`` auto-decides
        #: (``VELES_OFFLOAD`` / device budget), True/False force
        self.offload = offload
        self.offload_depth = offload_depth
        self.offload_workers = offload_workers
        self.offloaded = False
        self._offload_engine = None
        #: cumulative step-thread input wait (streamed mode); the
        #: runner reads deltas of this per epoch
        self.input_wait_s = 0.0
        self._active_pipeline = None
        #: optional ``fn(trainer, params, states)`` fired after EVERY
        #: closed epoch (both the standalone :meth:`train` loop and the
        #: production FusedRunner honor it) — the elastic checkpoint
        #: seam (ISSUE 13): veles_tpu.parallel.elastic hangs its
        #: per-epoch sharded snapshot here. Observational only: it
        #: must not mutate params/states.
        self.epoch_callback = None
        # per-batch global gradient norms ride the train scan (the
        # flight recorder's divergence detector input); the norm is a
        # pure observation over grads the solver reads anyway, so the
        # update math is untouched
        self.track_grad_norms = (
            grad_norms if grad_norms is not None
            else env_flag("VELES_GRAD_NORMS", True))
        #: (n_batches,) f32 norms of the most recent train segment,
        #: None until one ran (or when tracking is off)
        self.last_grad_norms = None
        self._staged_s2d = False
        # map each forward to its GD unit (for solver + hyper)
        self.gd_for = {}
        for gd in getattr(workflow, "gds", []):
            self.gd_for[id(gd.forward)] = gd
        self._build()

    def _op(self, name):
        """Cost-book op name under this trainer's namespace."""
        return self._op_prefix + name

    @staticmethod
    def _resolve_donate(donate):
        """Donation policy: explicit arg > ``VELES_DONATE`` env > off
        on CPU, on elsewhere.

        Donation is an HBM-residency optimization — on TPU it keeps
        weights device-resident across segments without a spare copy.
        On the CPU backend it buys nothing (host RAM, no transfer) and
        this jaxlib's CPU client intermittently corrupts the glibc
        heap when scan-carried tuple params are donated: depending on
        allocator layout the run dies with ``free(): invalid next
        size`` / ``munmap_chunk(): invalid pointer`` aborts, segfaults
        materializing segment outputs, or silently-garbled weights —
        the long-standing "order-dependent eager-vs-fused flake"
        (reproduced standalone: tests/test_fused_runner.py fails or
        aborts ~5/6 runs with donation on CPU, 0/6 with it off)."""
        if donate is not None:
            return donate
        env = env_flag("VELES_DONATE", None)
        if env is not None:
            return env
        import jax
        return jax.default_backend() != "cpu"

    # -- pure functions ----------------------------------------------------

    def _forward(self, params_list, x, key, train, aux=None,
                 valid=None):
        """Run the forward chain; the head uses apply_for_grad (logits).

        ``aux`` (train path): a list that collects units' auxiliary
        loss terms (e.g. MoE load balancing) for the grad loss;
        ``valid`` is the padded-row mask those terms must respect."""
        return self._forward_range(params_list, x, key, train, 0,
                                   len(self.forwards), aux=aux,
                                   valid=valid)

    def _forward_range(self, params_list, x, key, train, lo, hi,
                       aux=None, valid=None):
        """Forward through layers ``[lo, hi)`` only — the group-walk
        primitive of offloaded execution (ISSUE 17); ``_forward`` is
        the full range. ``params_list`` holds ONLY the range's layers,
        but dropout keys fold by the ABSOLUTE layer index, so a
        grouped walk reproduces the fused chain bit-for-bit."""
        for j, fwd in enumerate(self.forwards[lo:hi]):
            i = lo + j
            if aux is not None:
                aux_fn = getattr(fwd, "aux_loss", None)
                if aux_fn is not None and \
                        getattr(fwd, "aux_loss_weight", 0.0):
                    aux.append(aux_fn(params_list[j], x, valid=valid))
            is_head = i == len(self.forwards) - 1
            if isinstance(fwd, DropoutForward):
                if train:
                    x = fwd.apply_with_key(params_list[j], x,
                                           jax.random.fold_in(key, i))
            elif i == 0 and self._staged_s2d:
                # dataset was packed to patch-channel layout at
                # staging (stored with trailing dims flattened — see
                # _maybe_stage_s2d); the reshape touches only the
                # ~40 MB minibatch, then the entry conv consumes it
                # directly — no per-step rearrange. Numerics identical
                # to fwd.apply on raw.
                x = x.reshape((x.shape[0],) + self._staged_sample_shape)
                x = fwd.apply_staged(params_list[j], x)
            elif is_head:
                x = fwd.apply_for_grad(params_list[j], x)
            else:
                x = fwd.apply(params_list[j], x)
        return x

    def _loss_and_metrics(self, out, labels_or_targets, valid):
        """Returns (grad_loss, report_loss, metric).

        ``grad_loss`` reproduces the eager evaluator's gradient seed
        EXACTLY: softmax err is (p - onehot)/batch (full padded batch,
        evaluator.py _softmax_eval), MSE err is diff/n_valid. The
        human-facing ``report_loss`` normalizes by valid rows."""
        # loss math always reduces in f32, whatever the compute policy
        # left the head output in
        out = out.astype(jnp.float32)
        batch = out.shape[0]
        if self.loss_kind == "softmax":
            labels = labels_or_targets
            safe = jnp.where(valid, labels, 0)
            logp = jax.nn.log_softmax(out.reshape(batch, -1))
            picked = jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0]
            n_valid = jnp.maximum(jnp.sum(valid), 1)
            grad_loss = -jnp.sum(picked * valid) / batch
            report_loss = -jnp.sum(picked * valid) / n_valid
            pred = jnp.argmax(logp, axis=1)
            n_err = jnp.sum((pred != safe) & valid)
            return grad_loss, report_loss, n_err
        # mse: eager err_output = diff/n_valid -> loss 0.5*sum(d^2)/n_valid
        target = labels_or_targets
        diff = (out.reshape(batch, -1) -
                target.reshape(target.shape[0], -1))
        diff = diff * valid[:, None]
        n_valid = jnp.maximum(jnp.sum(valid), 1)
        grad_loss = 0.5 * jnp.sum(jnp.square(diff)) / n_valid
        # metric matches DecisionMSE: summed per-sample mean-sq-error
        metric = jnp.sum(jnp.mean(jnp.square(diff), axis=1))
        return grad_loss, metric / n_valid, metric

    def _maybe_stage_s2d(self):
        """Pack the dataset to patch-channel layout ONCE, if the entry
        layer is a space-to-depth conv.

        The per-step ``s2d_pack_input`` on the gathered batch costs
        ~1.5 ms/step on the AlexNet flagship (docs/PERF.md); packing is
        row-wise and linear, so doing it at staging commutes with the
        index gather and the invalid-row zero mask — float math is
        unchanged. Upload happens chunked host->device into a donated
        buffer, so peak HBM is packed + one chunk (the raw full copy is
        never resident).

        The packed dataset is stored as (n, rows_y, rows_x*s2c) —
        each sample's trailing dims flattened to one wide row-major
        axis. Three measured failure modes force this shape (r4 on
        v5e; full table in docs/PERF.md):

        * (n, rows_y, rows_x, 48) 4D: XLA relayouts the WHOLE dataset
          in-program to lane-pad the 48-channel minor dim (2.9x =
          14.6 GB copy -> compile OOM);
        * (n, F) flat 2D: the row gather lowers to a one-hot matmul —
          O(n * mb * F) per step, +16 ms/step at n=16k (the whole
          dataset re-read every step);
        * (n, F/128, 128) lane-aligned 3D: generic scalar-core gather
          of many tiny slices, +23 ms/step.

        The wide row-major 3D shape gathers as per-row DMA slices
        (like the raw 4D dataset always did) with ~zero tile padding;
        the per-minibatch reshape back to NHWC touches only ~40 MB
        inside the step. Returns the packed ``jax.Array`` or None;
        per-sample shape lands in ``self._staged_sample_shape``.
        """
        from veles_tpu.nn.conv import Conv
        fwd0 = self.forwards[0] if self.forwards else None
        if (not self.stage_s2d or len(self.forwards) < 2 or
                not isinstance(fwd0, Conv) or
                not getattr(fwd0, "space_to_depth", False)):
            return None
        raw = self.loader.original_data.map_read()
        n = raw.shape[0]
        packed_sample = fwd0.s2d_packed_shape(raw.shape[1:])
        self._staged_sample_shape = packed_sample
        flat = int(numpy.prod(packed_sample))
        ry = packed_sample[0]
        inner = flat // ry

        def pack_flat(chunk):
            return fwd0.s2d_pack_input(chunk).reshape(
                chunk.shape[0], ry, inner)

        update = jax.jit(
            lambda buf, chunk, start: jax.lax.dynamic_update_slice(
                buf, pack_flat(chunk), (start, 0, 0)),
            donate_argnums=(0,) if self.donate else ())
        packed = jnp.zeros((n, ry, inner), dtype=raw.dtype)
        chunk = max(1, min(n, 512))
        for i, start in enumerate(range(0, n, chunk)):
            piece = jnp.asarray(raw[start:start + chunk])
            packed = update(packed, piece, start)
            if i % 8 == 7:
                # the TPU relay rejects deep async queues (>~20 in
                # flight); periodically drain before enqueuing more
                packed.block_until_ready()
        packed.block_until_ready()
        # the raw full copy must not ALSO sit on the device (some
        # eager path may have uploaded it before the fused build)
        self.loader.original_data.release_devmem()
        self.debug("staged space-to-depth dataset: %s -> %s",
                   raw.shape, packed.shape)
        return packed

    # -- dataset residency: staged-resident OR streamed --------------------

    def _dataset_device_bytes(self, total_bytes):
        """Bytes of the dataset ONE device would hold resident (the
        data-parallel trainer divides by its shard count)."""
        return total_bytes

    def _shard_placer(self):
        """host ndarray -> device shard array; the data-parallel
        trainer overrides this with a mesh-sharded placement."""
        return prefetch.default_placer(
            getattr(self.loader.original_data, "device", None))

    def _setup_data_residency(self):
        """The generalization of the old all-or-nothing staging:
        *staged-resident* (s2d-packed where applicable) when the
        dataset fits the device budget, *streamed* out-of-core through
        the prefetch staging ring when it doesn't."""
        loader = self.loader
        truth_arr = (loader.original_labels
                     if self.loss_kind == "softmax"
                     else loader.original_targets)
        total_bytes = loader.original_data.nbytes + truth_arr.nbytes
        device = getattr(loader.original_data, "device", None)
        self.streaming = prefetch.plan_residency(
            self._dataset_device_bytes(total_bytes), device=device,
            force=self.stream) == "streamed"
        if self.streaming and not hasattr(loader, "host_backing"):
            self.warning("loader %s has no host backing store — "
                         "cannot stream; forcing the dataset resident",
                         loader.name)
            self.streaming = False
        if not self.streaming:
            staged = self._maybe_stage_s2d()
            self._staged_s2d = staged is not None
            self._data_args = (
                staged if staged is not None
                else loader.original_data.devmem,
                truth_arr.devmem)
            return
        # streamed: the dataset NEVER becomes fully device-resident.
        # Space-to-depth staging is skipped — apply() packs per step,
        # trading ~1.5 ms/step (flagship) for fitting at all.
        self._staged_s2d = False
        self._data_args = None
        self._truth_kind = ("labels" if self.loss_kind == "softmax"
                            else "targets")
        data, truth = loader.host_backing(self._truth_kind)
        # an eager init may already have uploaded the full copy — a
        # streamed run must not keep it resident alongside the ring
        loader.original_data.release_devmem()
        truth_arr.release_devmem()
        mb = loader.max_minibatch_size
        batch_bytes = mb * (
            int(numpy.prod(data.shape[1:], dtype=numpy.int64)) *
            data.dtype.itemsize +
            int(numpy.prod(truth.shape[1:], dtype=numpy.int64)) *
            truth.dtype.itemsize)
        depth = (prefetch.default_depth() if self.prefetch_depth is None
                 else self.prefetch_depth)
        # shard sizing is per-DEVICE, like the budget: a data-parallel
        # mesh holds 1/N of every shard per device, so its shards carry
        # N times the minibatches for the same footprint
        self._batches_per_shard = prefetch.shard_batches(
            self._dataset_device_bytes(batch_bytes), depth=depth,
            budget_bytes=prefetch.device_budget_bytes(device))
        self._staging_ring = prefetch.StagingRing(
            max(1, depth) + 2, self._shard_placer())
        from veles_tpu.telemetry.registry import get_registry
        registry = get_registry()
        self._etl_ms = registry.histogram(
            "veles_prefetch_etl_ms", "Host ETL time per streamed shard")
        self._h2d_ms = registry.histogram(
            "veles_prefetch_h2d_ms",
            "Host->device transfer dispatch time per streamed shard")
        self.info(
            "dataset streams out-of-core: %.0f MB exceeds the device "
            "budget; shards of %d minibatches (%.0f MB), prefetch "
            "depth %d", total_bytes / 1e6, self._batches_per_shard,
            self._batches_per_shard * batch_bytes / 1e6, depth)

    # -- model residency: in-core OR host-offloaded (ISSUE 17) --------------

    def _setup_model_residency(self):
        """The model-state analogue of :meth:`_setup_data_residency`:
        params/opt-state stay device-resident across the segment scan
        when they fit the budget, or offload to host masters walked
        group-by-group through :mod:`veles_tpu.train.offload`'s
        double-buffered staging ring when they don't (``offload=`` /
        ``VELES_OFFLOAD`` force)."""
        from veles_tpu.train import offload
        device = getattr(self.loader.original_data, "device", None)
        layer_bytes = offload.model_layer_bytes(self.forwards,
                                                self.solvers)
        decision = offload.plan_offload(sum(layer_bytes), device=device,
                                        force=self.offload)
        if decision != "offloaded":
            return
        if self.streaming:
            self.warning(
                "offloaded model state requires a resident dataset — "
                "the streamed input pipeline already owns the staging "
                "budget; keeping params in-core")
            return
        depth = (offload.offload_depth() if self.offload_depth is None
                 else max(0, self.offload_depth))
        with profiler.phase("offload_plan"):
            with tracing.span("offload:plan"):
                plan = offload.OffloadPlan.build(
                    layer_bytes,
                    offload.group_budget_bytes(device, depth))
                self._offload_engine = offload.OffloadEngine(
                    self, plan, depth=depth,
                    workers=self.offload_workers)
        self.offloaded = True
        self.info(
            "model state offloads out-of-core: %.1f MB in %d layer "
            "groups (%s), staging depth %d",
            plan.total_bytes / 1e6, plan.n_groups,
            "/".join("%d-%d" % g for g in plan.groups), depth)

    @property
    def offload_wait_s(self):
        """Cumulative step-thread transfer wait of offloaded segments
        (the runner and benches read deltas — mirrors
        :attr:`input_wait_s`)."""
        engine = self._offload_engine
        return engine.wait_s if engine is not None else 0.0

    def _shard_bounds(self, n_rows):
        """[(row0, row1)] index-matrix row ranges, one per shard."""
        rows = max(1, min(self._batches_per_shard, n_rows))
        return [(r, min(r + rows, n_rows))
                for r in range(0, n_rows, rows)]

    def _stream_segment(self, kind, run_shard, idx_matrix):
        """Drive one class sweep shard-by-shard through the prefetch
        pipeline: worker threads fill+transfer shard N+k while
        ``run_shard(data_args, local_idx, row0, row1)`` computes shard
        N. Returns the list of per-shard outputs; publishes the step
        thread's input-wait histogram + starvation gauge."""
        idx_np = numpy.asarray(idx_matrix, numpy.int32)
        bounds = self._shard_bounds(idx_np.shape[0])
        ring = self._staging_ring
        loader = self.loader
        truth_kind = self._truth_kind

        def produce(i):
            row0, row1 = bounds[i]
            rows_idx = idx_np[row0:row1]
            t0 = time.perf_counter()
            data_rows, truth_rows = loader.fill_indices(
                rows_idx, kind=truth_kind)
            etl = time.perf_counter() - t0
            self._etl_ms.observe(etl * 1e3)
            tracing.add_complete("prefetch:etl", t0, etl, shard=i)
            t1 = time.perf_counter()
            placed = ring.place((data_rows, truth_rows))
            local = jnp.asarray(prefetch.local_indices(rows_idx))
            h2d = time.perf_counter() - t1
            self._h2d_ms.observe(h2d * 1e3)
            tracing.add_complete("prefetch:h2d", t1, h2d, shard=i)
            return placed, local, row0, row1

        pipe = prefetch.PrefetchPipeline(
            produce, len(bounds), depth=self.prefetch_depth,
            workers=self.prefetch_workers, name=kind)
        self._active_pipeline = pipe
        outs = []
        start = time.perf_counter()
        try:
            ring.reopen()  # a prior shutdown() may have closed it
            pipe.start()
            for _ in range(len(bounds)):
                (placed, local, row0, row1), _ = pipe.get()
                outs.append(run_shard(placed, local, row0, row1))
        finally:
            pipe.close()
            self._active_pipeline = None
            self.input_wait_s += pipe.wait_s
            wall = time.perf_counter() - start
            if wall > 0:
                prefetch.starvation_gauge().labels(phase=kind).set(
                    min(1.0, pipe.wait_s / wall))
        return outs

    def _train_segment_streamed(self, jit_train, params_list,
                                opt_states, idx_matrix, keys):
        state = [params_list, opt_states]

        def run_shard(data_args, local_idx, row0, row1):
            args = (data_args, state[0], state[1], local_idx,
                    keys[row0:row1])
            harvest = self._prepare_harvest(self._op("train_segment"), jit_train,
                                            args)
            out = jit_train(*args)
            if harvest is not None:
                harvest()
            state[0], state[1] = out[0], out[1]
            return out[2:]

        outs = self._stream_segment("train", run_shard, idx_matrix)
        merged = tuple(jnp.concatenate(parts)
                       for parts in zip(*outs))
        if self.track_grad_norms:
            losses, metrics, norms = merged
            self.last_grad_norms = norms
            return state[0], state[1], losses, metrics
        return (state[0], state[1]) + merged

    def _eval_segment_streamed(self, jit_eval, params_list, idx_matrix):
        def run_shard(data_args, local_idx, row0, row1):
            args = (data_args, params_list, local_idx)
            harvest = self._prepare_harvest(self._op("eval_segment"), jit_eval,
                                            args)
            out = jit_eval(*args)
            if harvest is not None:
                harvest()
            return out

        outs = self._stream_segment("eval", run_shard, idx_matrix)
        losses = jnp.concatenate([o[0] for o in outs])
        metrics = jnp.concatenate([o[1] for o in outs])
        if len(outs[0]) == 3:
            conf = outs[0][2]
            for o in outs[1:]:
                conf = conf + o[2]
            return losses, metrics, conf
        return losses, metrics

    def shutdown(self):
        """Join any live prefetch pipeline and drop staged shards.

        Idempotent: the streamed drivers already close their pipeline
        per segment — this is the crash/Ctrl-C backstop the runner
        (and tests' session teardown) call so worker threads never
        outlive the run."""
        pipe = self._active_pipeline
        if pipe is not None:
            pipe.close()
            self._active_pipeline = None
        ring = getattr(self, "_staging_ring", None)
        if ring is not None:
            ring.clear()
        engine = self._offload_engine
        if engine is not None:
            engine.close()

    @staticmethod
    def _gather(data_args, idx):
        dataset, truth_src = data_args
        data = jnp.take(dataset, jnp.maximum(idx, 0), axis=0)
        data = data * (idx >= 0).reshape(
            (-1,) + (1,) * (data.ndim - 1)).astype(data.dtype)
        truth = jnp.take(truth_src, jnp.maximum(idx, 0), axis=0)
        return data, truth

    def _build(self):
        if isinstance(self.evaluator, EvaluatorSoftmax):
            self.loss_kind = "softmax"
        elif isinstance(self.evaluator, EvaluatorMSE):
            self.loss_kind = "mse"
        else:
            raise TypeError("unsupported evaluator %r" % self.evaluator)
        solvers = []
        hypers = []
        for fwd in self.forwards:
            gd = self.gd_for.get(id(fwd))
            solvers.append(get_solver(gd.solver_name) if gd else None)
            hypers.append(gd.hyper if gd else None)
        self.solvers = solvers
        self.hypers = hypers

        # resolve the dataset's residency OUTSIDE any trace: calling
        # .devmem under jit would cache a tracer inside the Array.
        # CRITICAL: device arrays are passed to the compiled functions
        # as ARGUMENTS, never closed over — a closure-captured array is
        # baked into the HLO as a constant, which (a) bloats the
        # program by the whole dataset (hundreds of MB for ImageNet
        # shapes — enough to kill remote-compile services) and (b)
        # defeats donation/sharding of the dataset buffer.
        self._setup_data_residency()

        #: fold confusion accumulation into the eval scan (one forward
        #: sweep serves losses+metrics+confusion) whenever the evaluator
        #: asks for it — eager fills confusion_matrix per minibatch
        #: under the same flag (evaluator.py:153-154)
        self.wants_confusion = self.loss_kind == "softmax" and \
            bool(getattr(self.evaluator, "compute_confusion", False))

        # model residency rides AFTER data residency: offload needs to
        # know whether the dataset streams (the two rings don't compose)
        self._setup_model_residency()

        gather = self._gather

        def train_batch(data_args, carry, batch_in):
            params_list, opt_states = carry
            idx, key = batch_in
            x, truth = gather(data_args, idx)
            valid = idx >= 0

            def loss_fn(plist):
                aux = []
                out = self._forward(plist, x, key, train=True, aux=aux,
                                    valid=valid)
                grad_loss, report, metric = self._loss_and_metrics(
                    out, truth, valid)
                # auxiliary terms (MoE load balancing) shape gradients
                # only; the human-facing report stays the task loss
                for term in aux:
                    grad_loss = grad_loss + term
                return grad_loss, (report, metric)

            (_, (loss, metric)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params_list)
            new_params, new_states = [], []
            for i in range(len(params_list)):
                if self.solvers[i] is None or not params_list[i]:
                    new_params.append(params_list[i])
                    new_states.append(opt_states[i])
                    continue
                p, s = self.solvers[i].update(
                    params_list[i], grads[i], opt_states[i],
                    self.hypers[i])
                new_params.append(p)
                new_states.append(s)
            outs = (loss, metric)
            if track_norms:
                # global grad norm in f32 — observation only, and the
                # grads are being read by the solvers anyway so XLA
                # fuses the reduction into traffic already paid for
                gsq = jnp.asarray(0.0, jnp.float32)
                for g in jax.tree_util.tree_leaves(grads):
                    gsq = gsq + jnp.sum(jnp.square(
                        g.astype(jnp.float32)))
                outs = (loss, metric, jnp.sqrt(gsq))
            return (tuple(new_params), tuple(new_states)), outs

        track_norms = self.track_grad_norms

        def train_segment(data_args, params_list, opt_states, idx_matrix,
                          keys):
            (params_list, opt_states), outs = jax.lax.scan(
                lambda carry, batch_in: train_batch(data_args, carry,
                                                    batch_in),
                (params_list, opt_states), (idx_matrix, keys))
            return (params_list, opt_states) + tuple(outs)

        jit_train = self._compile_train(train_segment)

        def _train_segment_call(params_list, opt_states, idx_matrix, keys):
            if self.offloaded:
                params_list, opt_states, losses, metrics, norms = \
                    self._offload_engine.train_segment(
                        params_list, opt_states, idx_matrix, keys)
                if track_norms:
                    self.last_grad_norms = norms
                return params_list, opt_states, losses, metrics
            if self.streaming:
                return self._train_segment_streamed(
                    jit_train, params_list, opt_states, idx_matrix,
                    keys)
            args = (self._data_args, params_list, opt_states,
                    idx_matrix, keys)
            # abstract shapes are snapshotted BEFORE the jitted call
            # (it donates the params/states buffers), but the harvest
            # compile runs AFTER it: the call populates the persistent
            # XLA cache, so the harvest's lower().compile() of the
            # same program deserializes instead of recompiling, and it
            # overlaps the segment's async execution. Measured times
            # are observed by the callers that BLOCK on the results
            # (dispatch here is async — timing it would be a lie).
            harvest = self._prepare_harvest(self._op("train_segment"), jit_train,
                                            args)
            out = jit_train(*args)
            if harvest is not None:
                harvest()
            if track_norms:
                params_list, opt_states, losses, metrics, norms = out
                self.last_grad_norms = norms
                return params_list, opt_states, losses, metrics
            return out

        self._train_segment = _train_segment_call

        wants_confusion = self.wants_confusion

        def eval_segment_pure(data_args, params_list, idx_matrix):
            def body(_, idx):
                x, truth = gather(data_args, idx)
                valid = idx >= 0
                out = self._forward(params_list, x, None, train=False)
                _, report, metric = self._loss_and_metrics(out, truth,
                                                           valid)
                if wants_confusion:
                    conf = self._batch_confusion(out, truth, valid)
                    return None, (report, metric, conf)
                return None, (report, metric)
            _, outs = jax.lax.scan(body, None, idx_matrix)
            if wants_confusion:
                losses, metrics, confs = outs
                return losses, metrics, jnp.sum(confs, axis=0)
            return outs

        jit_eval = self._compile_eval(eval_segment_pure)

        def _eval_segment_call(params_list, idx_matrix):
            if self.offloaded:
                return self._offload_engine.eval_segment(params_list,
                                                         idx_matrix)
            if self.streaming:
                return self._eval_segment_streamed(
                    jit_eval, params_list, idx_matrix)
            args = (self._data_args, params_list, idx_matrix)
            harvest = self._prepare_harvest(self._op("eval_segment"), jit_eval,
                                            args)
            out = jit_eval(*args)
            if harvest is not None:
                harvest()
            return out

        self._eval_segment = _eval_segment_call

    def _prepare_harvest(self, op, jit_fn, args):
        """One-time cost-analysis harvest of a compiled segment
        (veles_op_flops/veles_op_bytes + the ``compile`` startup
        phase). Returns a thunk to invoke AFTER the real call (or None
        when nothing to do): the abstract shapes captured here never
        touch the donated buffers, and deferring the lower+compile
        until the jit call has populated the persistent XLA cache
        turns it into a cache deserialize. Never fatal — attribution
        is advisory."""
        book = profiler.get_cost_book()
        if not book.needs_harvest(op):
            return None
        try:
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                               jnp.result_type(x)),
                args)
        except Exception:
            return None

        def harvest():
            with profiler.phase("compile"):
                book.harvest(op, jit_fn, abstract)
        return harvest

    @staticmethod
    def _batch_confusion(out, truth, valid):
        """One minibatch's confusion counts (eager: evaluator.py:39-42)."""
        probs = out.reshape(out.shape[0], -1)
        n_classes = probs.shape[-1]
        pred = jnp.argmax(probs, axis=1)
        safe = jnp.where(valid, truth, 0)
        flat = safe * n_classes + pred
        return jnp.zeros((n_classes * n_classes,), jnp.int32).at[
            flat].add(valid.astype(jnp.int32)).reshape(
            n_classes, n_classes)

    def confusion_segment(self, params_list, idx_matrix):
        """Summed confusion matrix of a forward pass over a segment.

        Lazily compiled, and only needed for the TRAIN class when no
        validation set exists — eval segments already return confusion
        alongside losses when ``wants_confusion``. Whole-segment
        accumulation supersedes the eager evaluator's last-minibatch
        snapshot of ``confusion_matrix``."""
        if self.loss_kind != "softmax":
            raise TypeError("confusion requires a softmax evaluator")
        fn = getattr(self, "_conf_fn", None)
        if fn is None:
            def conf_pure(data_args, params_list, idx_matrix):
                def body(_, idx):
                    x, truth = self._gather(data_args, idx)
                    valid = idx >= 0
                    out = self._forward(params_list, x, None, train=False)
                    return None, self._batch_confusion(out, truth, valid)
                _, confs = jax.lax.scan(body, None, idx_matrix)
                return jnp.sum(confs, axis=0)
            fn = self._conf_fn = jax.jit(conf_pure)
        if self.offloaded:
            return self._offload_engine.confusion_segment(
                params_list, numpy.asarray(idx_matrix))
        if self.streaming:
            def run_shard(data_args, local_idx, row0, row1):
                return fn(data_args, params_list, local_idx)
            outs = self._stream_segment("eval", run_shard,
                                        numpy.asarray(idx_matrix))
            conf = outs[0]
            for o in outs[1:]:
                conf = conf + o
            return conf
        return fn(self._data_args, params_list, jnp.asarray(idx_matrix))

    def _dropout_base_key(self):
        """Per-epoch dropout key, drawn from the DROPOUT unit's stream
        (eager: DropoutForward._draw_mask uses prng.get(self.rand_name),
        nn/base.py:39) — never from the loader's, whose shuffle sequence
        must stay bit-identical to an eager run of the same seed."""
        for fwd in self.forwards:
            if isinstance(fwd, DropoutForward):
                return prng.get(fwd.rand_name).jax_key()
        # keys are dead in the trace without dropout; a constant keeps
        # every stream untouched
        return jax.random.PRNGKey(0)

    # -- class-level driving (shared by run_epoch and FusedRunner) ---------

    def eval_class(self, params, klass, skip=0):
        """Forward-only sweep of one class (from sample ``skip`` on).

        Returns ``(losses, metrics, confusion)`` where ``confusion`` is
        None unless it rides the eval scan (``wants_confusion``)."""
        idx = self._segment_indices(klass, skip=skip)
        # streamed mode slices the index matrix on the HOST per shard;
        # committing it to the device first would be a wasted upload
        out = self._eval_segment(
            params,
            idx if (self.streaming or self.offloaded) else jnp.asarray(idx))
        return out[0], out[1], out[2] if len(out) == 3 else None

    def train_class(self, params, states, skip=0):
        """One training sweep of the TRAIN class with per-batch dropout
        keys folded from the epoch's base key.

        On a mid-epoch resume (``skip`` > 0) the fold indices continue
        from the batch position within the epoch, so the key sequence
        matches an uninterrupted fused run of the same stream state."""
        idx = self._segment_indices(TRAIN, skip=skip)
        base = self._dropout_base_key()
        first = skip // self.loader.max_minibatch_size
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(first, first + idx.shape[0]))
        return self._train_segment(
            params, states,
            idx if (self.streaming or self.offloaded) else jnp.asarray(idx),
            keys)

    # -- compilation hooks (overridden by parallel trainers) ---------------
    # signatures: train fn(data_args, params, states, idx, keys),
    #             eval fn(data_args, params, idx)

    def _compile_train(self, fn):
        return jax.jit(fn, donate_argnums=(1, 2) if self.donate else ())

    def _compile_eval(self, fn):
        return jax.jit(fn)

    # -- parameter plumbing ------------------------------------------------

    def pull_params(self):
        """Unit Arrays -> device pytrees (one-time HBM residency).

        In offloaded mode the returned pytrees are HOST numpy masters
        instead (the pinned out-of-core copy); the staging ring uploads
        layer groups from them per step."""
        if self.offloaded:
            return self._pull_params_host()
        params = tuple(fwd.param_values() for fwd in self.forwards)
        states = []
        for i, fwd in enumerate(self.forwards):
            gd = self.gd_for.get(id(fwd))
            if gd is not None and params[i]:
                if gd.opt_state is None:
                    gd.opt_state = get_solver(gd.solver_name).init(
                        params[i])
                states.append(gd.opt_state)
            else:
                states.append({})
        return params, tuple(states)

    def _pull_params_host(self):
        """Unit Arrays -> HOST numpy masters (out-of-core residency).

        Params stay off the device entirely — ``map_read`` copies give
        the engine mutable masters and ``release_devmem`` drops any
        stale device mirror so the ring owns all HBM traffic. Restored
        opt states (which a snapshot may hand back as jax arrays) are
        normalized to numpy so a later upload sees uniform leaves."""
        t0 = time.perf_counter()
        params = []
        for fwd in self.forwards:
            layer = {}
            for k, arr in fwd.param_arrays().items():
                layer[k] = numpy.array(arr.map_read())
                arr.release_devmem()
            params.append(layer)
        states = []
        for i, fwd in enumerate(self.forwards):
            gd = self.gd_for.get(id(fwd))
            if gd is not None and params[i]:
                if gd.opt_state is None:
                    gd.opt_state = get_solver(gd.solver_name).init(
                        params[i])
                gd.opt_state = jax.tree_util.tree_map(
                    numpy.asarray, gd.opt_state)
                states.append(gd.opt_state)
            else:
                states.append({})
        tracing.add_complete("offload:pin", t0,
                             time.perf_counter() - t0)
        return tuple(params), tuple(states)

    def checkpoint_records(self, params, states):
        """``[(spec, leaf)]`` for a sharded checkpoint of the live
        training state (``snapshotter.save_snapshot_sharded``).

        Deterministic order (forward index, sorted keys/paths) so every
        SPMD process emits the SAME record list and per-process part
        files line up shard-for-shard. Specs are the layout
        ``snapshotter._apply_record`` installs back into a restored
        workflow's unit Arrays / GD opt states."""
        records = []
        for i, layer in enumerate(params):
            for name in sorted(layer):
                records.append(({"kind": "param", "forward": i,
                                 "name": name}, layer[name]))

        def walk(i, node, path):
            if isinstance(node, dict):
                for key in sorted(node):
                    walk(i, node[key], path + [key])
                return
            records.append(({"kind": "opt", "forward": i,
                             "path": path}, node))

        for i, state in enumerate(states):
            if state:
                walk(i, state, [])
        return records

    def push_params(self, params, states):
        """Device pytrees -> unit Arrays (after training).

        Offloaded runs hand back HOST masters: those go through
        ``Array.reset`` (replacing the host buffer, no device mirror)
        instead of ``assign_devmem``."""
        for fwd, p, s in zip(self.forwards, params, states):
            for k, arr in fwd.param_arrays().items():
                if self.offloaded:
                    arr.reset(numpy.array(p[k]))
                else:
                    arr.assign_devmem(p[k])
            gd = self.gd_for.get(id(fwd))
            if gd is not None:
                gd.opt_state = s

    # -- index plumbing ----------------------------------------------------

    def _segment_indices(self, klass, skip=0):
        """(n_batches, mb) int32 index matrix for a class, padded -1.

        ``skip`` drops the class's first samples — a mid-epoch snapshot
        resume serves only the REMAINING minibatches through the same
        scan (``veles/snapshotter.py:387-409`` resume semantics;
        minibatch boundaries are class-aligned, so ``skip`` is a
        multiple of the minibatch size)."""
        loader = self.loader
        ends = loader.class_end_offsets
        start = ends[klass] - loader.class_lengths[klass] + skip
        seg = numpy.asarray(
            loader.shuffled_indices.map_read()[start:ends[klass]],
            numpy.int32)
        mb = loader.max_minibatch_size
        n_batches = (len(seg) + mb - 1) // mb
        mat = numpy.full((max(n_batches, 1), mb), -1, numpy.int32)
        flat = mat.reshape(-1)
        flat[:len(seg)] = seg
        return mat

    # -- driving -----------------------------------------------------------

    def run_epoch(self, params, states, epoch):
        """One epoch: eval classes in reference order, then train."""
        stats = {}
        for klass in (TEST, VALIDATION):
            if not self.loader.class_lengths[klass]:
                continue
            losses, metrics, conf = self.eval_class(params, klass)
            if conf is not None:
                self.evaluator.confusion_matrix = numpy.asarray(conf)
            stats[CLASS_NAMES[klass]] = self._summarize(
                losses, metrics, klass)
        if self.loader.class_lengths[TRAIN]:
            t0 = time.perf_counter()
            params, states, losses, metrics = self.train_class(
                params, states)
            stats[CLASS_NAMES[TRAIN]] = self._summarize(
                losses, metrics, TRAIN)
            # _summarize forced the sync, so this elapsed covers the
            # whole sweep — the live-view gauges + MFU ride on it
            self._publish_live(stats[CLASS_NAMES[TRAIN]],
                               time.perf_counter() - t0)
            self.loader.epoch_number = epoch + 1
            if self.loader.epoch_number <= self.loader.shuffle_limit:
                self.loader.shuffle()
        return params, states, stats

    def _publish_live(self, train_stats, elapsed_s):
        """The live job view (ISSUE 19) for the class-level loop:
        FusedRunner publishes the same families on the launcher path;
        this keeps runs driving :meth:`run_epoch` directly (elastic
        workers, scheduled gangs) feeding the federation plane too."""
        from veles_tpu.telemetry import profiler
        from veles_tpu.telemetry.registry import get_registry
        registry = get_registry()
        registry.gauge(
            "veles_train_loss",
            "Last training batch loss").set(train_stats["loss"])
        if elapsed_s > 0:
            registry.gauge(
                "veles_train_samples_per_s",
                "Samples served per second over the last epoch").set(
                train_stats["samples"] / elapsed_s)
        profiler.get_cost_book().record_step_mfu(
            getattr(self, "_op_prefix", "") + "train_segment",
            elapsed_s)

    def _summarize(self, losses, metrics, klass):
        n = self.loader.class_lengths[klass]
        metric_sum = float(jnp.sum(metrics))
        return {"samples": n, "metric": metric_sum,
                "normalized": metric_sum / max(n, 1),
                "loss": float(jnp.mean(losses))}

    def train(self, max_epochs=None, epoch_callback=None,
              initial_state=None):
        """Full training loop with the decision unit's stop criterion.

        ``epoch_callback`` (or the :attr:`epoch_callback` attribute)
        fires after each epoch's bookkeeping closes — with the live
        ``(trainer, params, states)`` — which is exactly the complete
        step boundary an elastic checkpoint must be cut at. A restored
        workflow resumes transparently: the loop starts from the
        loader's ``epoch_number`` and the decision's restored history/
        best-state carry the stop criterion forward.
        ``initial_state`` accepts an already-pulled ``(params,
        states)`` so a caller that needed them before the loop (the
        elastic generation-initial checkpoint) does not pay the
        host→device placement twice."""
        decision = self.decision
        max_epochs = max_epochs if max_epochs is not None \
            else decision.max_epochs
        callback = (epoch_callback if epoch_callback is not None
                    else self.epoch_callback)
        params, states = (initial_state if initial_state is not None
                          else self.pull_params())
        epoch = self.loader.epoch_number
        start = time.perf_counter()
        while True:
            params, states, stats = self.run_epoch(params, states, epoch)
            stats["epoch"] = epoch
            decision.epoch_history.append(stats)
            key = ("validation" if self.loader.class_lengths[VALIDATION]
                   else "train")
            metric = stats[key]["normalized"]
            if metric < decision.best_metric:
                decision.best_metric = metric
                decision.best_epoch = epoch
                decision.improved <<= True
            else:
                decision.improved <<= False
            self.info("epoch %d: %s", epoch, "  ".join(
                "%s=%.4f" % (k, v["normalized"])
                for k, v in stats.items() if isinstance(v, dict)))
            if callback is not None:
                callback(self, params, states)
            epoch += 1
            if max_epochs is not None and epoch >= max_epochs:
                break
            # same inequality as DecisionBase._on_epoch_finished, where
            # epoch_number is the epoch just completed (= epoch - 1 here)
            if (epoch - 1) - decision.best_epoch > decision.fail_iterations:
                break
        elapsed = time.perf_counter() - start
        decision.complete <<= True
        self.workflow.stopped <<= True
        self.push_params(params, states)
        self.shutdown()
        n_train = self.loader.class_lengths[TRAIN]
        epochs_done = len(decision.epoch_history)
        self.info("fused training: %d epochs in %.2fs (%.0f samples/s)",
                  epochs_done, elapsed,
                  epochs_done * n_train / max(elapsed, 1e-9))
        return decision.epoch_history
