"""FusedRunner: the production driver of the step compiler.

The reference promises that the SAME entry point is the fast path
(``veles/__main__.py:820-856`` dispatches straight into the Twisted
run loop that drives the OpenCL/CUDA kernels).  Here the fast path is
the fused XLA step (:mod:`veles_tpu.train.step`), and this module makes
``python -m veles_tpu`` / :class:`~veles_tpu.launcher.Launcher` use it
by default whenever the workflow has the standard trainable shape:

    loader + forwards + evaluator(softmax|mse) + gds + decision

Everything the eager graph would do at epoch boundaries still happens,
through the SAME units: the decision's canonical bookkeeping
(``epoch_stats`` → ``_on_class_finished`` → ``_on_epoch_finished``,
giving identical ``epoch_history``, ``improved``/``best_*`` state, stop
criterion and log lines), and every service unit hanging off the graph
(plotters, snapshotter, ...) fires once per epoch with the loader's
``epoch_ended``/``last_minibatch`` flags raised — exactly the state the
eager scheduler shows them on the last minibatch of an epoch.

Nonstandard graphs (custom units on the training path, mid-epoch
snapshot resumes, unsupported evaluators) are detected by
:func:`fused_compatible` and fall back to the eager per-unit scheduler,
as does the explicit ``--eager`` flag.
"""

import collections
import time

import numpy

from veles_tpu.loader.base import TEST, TRAIN, VALIDATION
from veles_tpu.logger import Logger
from veles_tpu.nn.evaluator import EvaluatorMSE, EvaluatorSoftmax
from veles_tpu.plumbing import Repeater, StartPoint, EndPoint
from veles_tpu.telemetry import flight, profiler, tracing
from veles_tpu.telemetry.registry import get_registry
from veles_tpu.train.step import FusedTrainer

#: view groups whose units are epoch-boundary services — safe to fire
#: once per fused epoch instead of once per minibatch
SERVICE_VIEW_GROUPS = ("PLOTTER", "SERVICE")


def _covered_units(workflow):
    """Units whose work the fused step subsumes."""
    covered = {workflow.start_point, workflow.end_point,
               workflow.loader, workflow.evaluator, workflow.decision}
    covered.update(workflow.forwards)
    covered.update(getattr(workflow, "gds", ()))
    for unit in workflow:
        if isinstance(unit, (Repeater, StartPoint, EndPoint)):
            covered.add(unit)
    return covered


def fused_compatible(workflow):
    """None if ``workflow`` can run fused, else a human-readable reason.

    Conservative on purpose: any unit the step compiler does not model
    (other than pure epoch-boundary services) forces the eager path, so
    user graphs with custom per-minibatch units keep their semantics.
    """
    for attr in ("loader", "forwards", "evaluator", "decision"):
        if getattr(workflow, attr, None) is None:
            return "workflow has no %s" % attr
    if not workflow.forwards:
        return "workflow has an empty forward chain"
    evaluator = workflow.evaluator
    if not isinstance(evaluator, (EvaluatorSoftmax, EvaluatorMSE)):
        return "evaluator %s is not softmax/mse" % type(evaluator).__name__
    loader = workflow.loader
    for attr in ("original_data", "shuffled_indices", "class_lengths",
                 "max_minibatch_size"):
        if getattr(loader, attr, None) is None:
            return "loader lacks %s" % attr
    truth_attr = ("original_labels" if isinstance(evaluator,
                                                  EvaluatorSoftmax)
                  else "original_targets")
    truth = getattr(loader, truth_attr, None)
    if truth is None or getattr(truth, "mem", None) is None:
        return "loader has no device-resident %s" % truth_attr
    if getattr(loader.original_data, "mem", None) is None:
        return "loader dataset is not device-resident"
    offset = getattr(loader, "_global_offset", 0)
    if 0 < offset < loader.total_samples:
        # a mid-epoch snapshot resume runs the REMAINING minibatches
        # through the same scan (_resume_partial_epoch) — fused stays
        # the production path. Only two genuinely nonstandard states
        # still need the eager scheduler:
        if getattr(loader, "failed_minibatches", None):
            return "loader has requeued minibatches pending"
        ends = loader.class_end_offsets
        for klass, end in enumerate(ends):
            if offset < end and loader.class_lengths[klass]:
                within = offset - (end - loader.class_lengths[klass])
                if within % loader.max_minibatch_size != 0:
                    return ("resume offset %d is not minibatch-aligned"
                            % offset)
                break
    covered = _covered_units(workflow)
    for unit in workflow:
        if unit in covered:
            continue
        if unit.view_group in SERVICE_VIEW_GROUPS:
            continue
        return "unit %r (%s, view_group=%s) is outside the fused step" % (
            unit.name, type(unit).__name__, unit.view_group)
    return None


class FusedRunner(Logger):
    """Drive a standard workflow through compiled segments, firing the
    decision and the service units exactly as the eager scheduler would
    at each epoch boundary."""

    def __init__(self, workflow, trainer=None):
        super(FusedRunner, self).__init__()
        self.workflow = workflow
        self.trainer = trainer if trainer is not None \
            else FusedTrainer(workflow)
        self._last_batch = (0.0, 0.0)
        # per-epoch granularity: one observe per sweep, negligible next
        # to the compiled segments it measures
        registry = get_registry()
        self._step_ms = registry.histogram(
            "veles_step_ms", "Fused step (one class sweep) wall time",
            labels=("phase",))
        self._epoch_ms = registry.histogram(
            "veles_epoch_ms", "End-to-end epoch wall time")
        # the live job view (ISSUE 19): last-batch loss + epoch
        # throughput as gauges, so the federation/history plane has a
        # per-process training signal to carry without parsing logs
        self._m_loss = registry.gauge(
            "veles_train_loss", "Last training batch loss")
        self._m_samples_per_s = registry.gauge(
            "veles_train_samples_per_s",
            "Samples served per second over the last epoch")
        # the flight recorder (stall watchdog + NaN/divergence
        # detectors) and the cost book (per-op ms + step MFU) ride
        # every sweep; both are advisory and never raise into the run
        self._flight = flight.get_recorder()
        self._book = profiler.get_cost_book()
        self._epoch_index = 0
        self._first_step_done = False
        # streamed (out-of-core) input pipeline: per-epoch starvation
        # fraction = step-thread input wait / epoch wall (the overlap
        # win of ISSUE 8, measured not asserted)
        from veles_tpu.loader import prefetch
        self._starvation = prefetch.starvation_gauge()
        # out-of-core MODEL state (ISSUE 17): per-epoch compute/transfer
        # overlap fraction of the offload staging ring, same shape of
        # accounting as the input-side starvation gauge above
        from veles_tpu.train import offload
        self._offload_overlap = offload.overlap_gauge()

    def _timed_step(self, phase, fn, *args, **kwargs):
        """Run one sweep under a span + the step histogram, with the
        stall watchdog armed; the first TRAIN sweep (which holds the
        train-segment compile on a cold cache — epoch order runs the
        eval classes first, so "first sweep of the run" would record
        the small eval sweep instead) lands in ``first_step``."""
        self._flight.step_begin("%s sweep epoch %d"
                                % (phase, self._epoch_index))
        start = time.perf_counter()
        try:
            result = fn(*args, **kwargs)
        except Exception as e:
            self._flight.record_exception(
                e, step="%s sweep epoch %d" % (phase,
                                               self._epoch_index))
            raise
        finally:
            self._flight.step_end()
            elapsed = time.perf_counter() - start
            self._step_ms.labels(phase=phase).observe(elapsed * 1e3)
            tracing.add_complete("step:%s" % phase, start, elapsed)
            if phase == "train" and not self._first_step_done:
                self._first_step_done = True
                profiler.record_phase("first_step", elapsed)
        # parallel trainers compile a different program for the same
        # sweep — their _op_prefix keeps the cost rows separate (the
        # GSPMD path's rows are gspmd_train_segment etc., ISSUE 15)
        prefix = getattr(self.trainer, "_op_prefix", "")
        op = prefix + ("train_segment" if phase == "train"
                       else "eval_segment")
        self._book.observe_ms(op, elapsed)
        if phase == "train":
            self._book.record_step_mfu(prefix + "train_segment",
                                       elapsed)
        self._flight.observe_step(phase, elapsed,
                                  loss=self._last_batch[0],
                                  epoch=self._epoch_index)
        return result

    # -- epoch bodies ------------------------------------------------------

    def _eval_classes(self, params, testing, skips=None):
        """Forward-only passes in the eager serving order. When the
        evaluator computes a confusion matrix, it rides along in the
        same scan — no second forward sweep.

        ``skips`` (mid-epoch snapshot resume) maps class -> samples
        already served pre-snapshot; ``None`` = fully served, skip the
        class entirely."""
        trainer = self.trainer
        loader = trainer.loader
        evaluator = self.workflow.evaluator
        skips = skips or {}
        stats = {}
        klasses = (TEST, VALIDATION, TRAIN) if testing \
            else (TEST, VALIDATION)
        for klass in klasses:
            skip = skips.get(klass, 0)
            if not loader.class_lengths[klass] or skip is None:
                continue
            losses, metrics, conf = trainer.eval_class(params, klass,
                                                       skip=skip)
            if conf is not None and skip == 0:
                # later classes overwrite: confusion ends up for the
                # most meaningful class evaluated (validation over
                # test); a partial (resumed) sweep would understate it
                evaluator.confusion_matrix = numpy.asarray(conf)
            stats[klass] = trainer._summarize(losses, metrics, klass)
            if skip:
                stats[klass]["samples"] -= skip
            self._last_batch = (float(losses[-1]), float(metrics[-1]))
            try:
                self._flight.check_losses(losses,
                                          epoch=self._epoch_index,
                                          phase="eval")
            except Exception:
                pass
        return stats

    def _train_class(self, params, states, skip=0):
        trainer = self.trainer
        params, states, losses, metrics = trainer.train_class(
            params, states, skip=skip)
        self._last_batch = (float(losses[-1]), float(metrics[-1]))
        # detectors: the whole per-batch loss vector (a NaN that heals
        # by the last batch must still trip) + the grad-norm series
        try:
            self._flight.check_losses(losses, epoch=self._epoch_index,
                                      phase="train")
            if trainer.last_grad_norms is not None:
                self._flight.observe_grad_norms(
                    numpy.asarray(trainer.last_grad_norms),
                    epoch=self._epoch_index)
        except Exception:
            pass  # detection is advisory, training is not
        stats = trainer._summarize(losses, metrics, TRAIN)
        if skip:
            stats["samples"] -= skip
        return params, states, stats

    # -- epoch-boundary side effects ---------------------------------------

    def _close_epoch(self, stats):
        """Replay the decision unit's last-minibatch bookkeeping.

        Same calls the eager path makes (decision.py run():82-88), so
        epoch_history entries, improved/best_* state, stop decisions and
        log lines are identical between the two schedulers.

        Stats ACCUMULATE into the decision's epoch buckets: for a fresh
        epoch the buckets are zero (``_reset_epoch``) so this equals
        assignment, and for a mid-epoch snapshot resume the snapshot's
        partial sums complete to exactly the uninterrupted totals."""
        decision = self.workflow.decision
        loader = self.workflow.loader
        for klass in (TEST, VALIDATION, TRAIN):
            if klass not in stats:
                continue
            epoch_stats = decision.epoch_stats[klass]
            epoch_stats["samples"] += stats[klass]["samples"]
            epoch_stats["metric"] += stats[klass]["metric"]
            decision._on_class_finished(klass)
        loader.samples_served += sum(
            s["samples"] for s in stats.values())
        # evaluator summary state the eager path leaves behind (its last
        # minibatch's values) — result providers read these
        evaluator = self.workflow.evaluator
        last_loss, last_metric = self._last_batch
        if isinstance(evaluator, EvaluatorSoftmax):
            evaluator.loss = last_loss
            evaluator.n_err = int(last_metric)
        else:
            evaluator.rmse = float(max(last_loss, 0.0)) ** 0.5
        # the eager loader state at an epoch's last minibatch — so a
        # snapshot taken here resumes exactly like an eager one
        loader._global_offset = loader.total_samples
        loader.minibatch_offset = loader.total_samples
        loader.last_minibatch <<= True
        loader.epoch_ended <<= True
        decision._on_epoch_finished()

    def _fire_services(self, services):
        """One epoch-boundary pass over the service subgraph with the
        eager scheduler's exact signal semantics (workflow.py _drain):
        gate_block swallows the signal (dependents never fire),
        gate_skip propagates without running."""
        service_set = set(services)
        signals = collections.deque()
        for unit in services:
            for src in unit.links_from:
                if src not in service_set:
                    # the fused step stands in for every covered unit's
                    # firing on the epoch's last minibatch
                    signals.append((unit, src))
        while signals:
            dst, src = signals.popleft()
            if dst not in service_set:
                continue
            if bool(dst.gate_block):
                continue
            if not dst.open_gate(src):
                continue
            if bool(dst.gate_skip):
                for nxt in dst.links_to:
                    signals.append((nxt, dst))
                continue
            dst._run_wrapped()
            for nxt in dst.links_to:
                signals.append((nxt, dst))

    def _resume_partial_epoch(self, params, states, offset,
                              confusion_from_train=False):
        """Finish the epoch a mid-epoch snapshot interrupted — fused.

        The snapshot froze the loader at ``offset`` with the epoch's
        ``shuffled_indices`` intact and the decision's partial epoch
        sums in place (eager accumulates per minibatch). Serving the
        REMAINING samples of each class through the same compiled
        segments and letting ``_close_epoch`` accumulate reproduces the
        uninterrupted run exactly (``veles/snapshotter.py:387-409`` +
        ``veles/loader/base.py:880`` semantics on the fused path).
        """
        trainer = self.trainer
        loader = trainer.loader
        decision = self.workflow.decision
        testing = bool(decision.testing)
        ends = loader.class_end_offsets
        # per-class samples already served pre-snapshot; None = the
        # whole class was served (its _on_class_finished fired then)
        skips = {}
        for klass in (TEST, VALIDATION, TRAIN):
            length = loader.class_lengths[klass]
            if not length:
                continue
            skips[klass] = None if offset >= ends[klass] else \
                max(offset - (ends[klass] - length), 0)
        stats = self._eval_classes(params, testing, skips=skips)
        train_skip = skips.get(TRAIN)
        if not testing and train_skip is not None and \
                loader.class_lengths[TRAIN]:
            params, states, train_stats = self._train_class(
                params, states, skip=train_skip)
            stats[TRAIN] = train_stats
        if confusion_from_train and not testing:
            # the normal epoch loop refreshes the plotters' confusion
            # before closing; the resumed epoch must too, or they render
            # the snapshot's stale matrix
            self._feed_confusion_from_train(params)
        self.info("resumed mid-epoch snapshot at offset %d: served the "
                  "remaining %d samples fused", offset,
                  sum(s["samples"] for s in stats.values()))
        self._close_epoch(stats)
        return params, states, stats

    def _feed_confusion_from_train(self, params):
        """No validation set: confusion comes from a forward sweep of
        the TRAIN class (eval segments never see it outside testing
        mode). The common case — a validation class — gets confusion
        for free inside ``_eval_classes``."""
        trainer = self.trainer
        if not trainer.loader.class_lengths[TRAIN]:
            return
        idx = trainer._segment_indices(TRAIN)
        self.workflow.evaluator.confusion_matrix = numpy.asarray(
            trainer.confusion_segment(params, idx))

    # -- the loop ----------------------------------------------------------

    def run(self):
        workflow = self.workflow
        loader = workflow.loader
        decision = workflow.decision
        trainer = self.trainer
        services = [u for u in workflow.units_in_dependency_order
                    if u not in _covered_units(workflow)]
        workflow.event("run", "begin")
        workflow.stopped <<= False
        workflow.is_running = True
        start = time.perf_counter()
        epochs_done = 0
        samples_done = 0
        # with a validation class the confusion matrix rides the eval
        # scan for free (always filled, like eager); the validation-
        # LESS fallback costs a whole extra TRAIN forward sweep, so it
        # runs only when something actually consumes the matrix
        from veles_tpu.plotting_units import MatrixPlotter
        confusion_from_train = (
            trainer.wants_confusion and
            not loader.class_lengths[VALIDATION] and
            any(isinstance(u, MatrixPlotter) for u in services))
        params = states = None
        try:
            params, states = trainer.pull_params()
            offset = getattr(loader, "_global_offset", 0)
            if 0 < offset < loader.total_samples and not (
                    bool(decision.complete) or bool(workflow.stopped)):
                params, states, stats = self._resume_partial_epoch(
                    params, states, offset,
                    confusion_from_train=confusion_from_train)
                if trainer.epoch_callback is not None:
                    # the resumed epoch is a closed epoch like any
                    # other: it must checkpoint, or a later crash
                    # rewinds past it and replays it twice over
                    trainer.epoch_callback(trainer, params, states)
                if services:
                    trainer.push_params(params, states)
                self._fire_services(services)
                epochs_done += 1
                self._epoch_index = epochs_done
                samples_done += sum(s["samples"] for s in stats.values())
            while True:
                if bool(decision.complete) or bool(workflow.stopped):
                    # e.g. a resumed snapshot of a finished run: the
                    # eager end_point would fire immediately, with the
                    # loader state untouched
                    break
                if loader.total_samples and \
                        getattr(loader, "_global_offset", 0) >= \
                        loader.total_samples:
                    # the eager loader's lazy epoch wrap on next serve
                    # (loader/base.py _advance_global_offset:179-180)
                    loader._finish_epoch()
                    loader.epoch_ended <<= False
                    loader.last_minibatch <<= False
                epoch_start = time.perf_counter()
                epoch_wait0 = trainer.input_wait_s
                epoch_owait0 = getattr(trainer, "offload_wait_s", 0.0)
                testing = bool(decision.testing)
                stats = self._timed_step("eval", self._eval_classes,
                                         params, testing)
                if not testing and loader.class_lengths[TRAIN]:
                    params, states, train_stats = self._timed_step(
                        "train", self._train_class, params, states)
                    stats[TRAIN] = train_stats
                if confusion_from_train and not testing:
                    self._feed_confusion_from_train(params)
                self._close_epoch(stats)
                if trainer.epoch_callback is not None:
                    # the elastic checkpoint seam (ISSUE 13): cut the
                    # sharded snapshot at the closed-epoch boundary,
                    # same point the standalone train() loop uses
                    trainer.epoch_callback(trainer, params, states)
                if services:
                    # services may pickle/plot the unit arrays, whose
                    # previous buffers the compiled segment donated —
                    # rebind them to the live params first
                    trainer.push_params(params, states)
                self._fire_services(services)
                epoch_elapsed = time.perf_counter() - epoch_start
                self._epoch_ms.observe(epoch_elapsed * 1e3)
                tracing.add_complete("epoch", epoch_start, epoch_elapsed,
                                     index=epochs_done)
                if getattr(trainer, "streaming", False) and \
                        epoch_elapsed > 0:
                    epoch_wait = trainer.input_wait_s - epoch_wait0
                    fraction = min(1.0, epoch_wait / epoch_elapsed)
                    self._starvation.labels(phase="epoch").set(fraction)
                    self.debug("epoch %d input wait %.0f ms "
                               "(%.1f%% starved)", epochs_done,
                               epoch_wait * 1e3, fraction * 100.0)
                if getattr(trainer, "offloaded", False) and \
                        epoch_elapsed > 0:
                    owait = getattr(trainer, "offload_wait_s", 0.0) - \
                        epoch_owait0
                    overlap = max(0.0, 1.0 - owait / epoch_elapsed)
                    self._offload_overlap.labels(phase="epoch").set(
                        overlap)
                    self.debug("epoch %d offload wait %.0f ms "
                               "(%.1f%% overlapped)", epochs_done,
                               owait * 1e3, overlap * 100.0)
                epochs_done += 1
                self._epoch_index = epochs_done
                epoch_samples = sum(s["samples"] for s in stats.values())
                samples_done += epoch_samples
                self._m_loss.set(self._last_batch[0])
                if epoch_elapsed > 0:
                    self._m_samples_per_s.set(
                        epoch_samples / epoch_elapsed)
        except Exception as e:
            # the crash path: persist the black box BEFORE the
            # exception unwinds the run (sweep-level failures already
            # dumped in _timed_step; the recorder rate-limits dupes)
            self._flight.record_exception(
                e, step="epoch %d" % self._epoch_index)
            raise
        finally:
            # rebind unit arrays even on an exception / Ctrl-C: the
            # epochs that DID complete must survive into any subsequent
            # snapshot (eager keeps unit arrays current every minibatch)
            if params is not None:
                trainer.push_params(params, states)
            # join any prefetch workers / drop staged shards: pipeline
            # threads must never outlive the run (crash/Ctrl-C included)
            trainer.shutdown()
            workflow.is_running = False
            elapsed = time.perf_counter() - start
            workflow._run_time += elapsed
            workflow.event("run", "end")
        workflow.on_workflow_finished()
        self.info("fused run: %d epochs, %d samples in %.2fs "
                  "(%.0f samples/s)", epochs_done, samples_done, elapsed,
                  samples_done / max(elapsed, 1e-9))
        return workflow
