"""Fused training execution (the TPU hot path).

The reference re-enters Python per unit per minibatch; on TPU that
pattern wastes the chip (SURVEY.md §7 "hard parts": the training-loop
boundary). :class:`~veles_tpu.train.step.FusedTrainer` lowers a standard
workflow (loader → forwards → evaluator → decision → gds) into jitted
segment functions — ``lax.scan`` over a segment's minibatch index
matrix, parameters donated across steps — so one epoch is a handful of
device calls regardless of minibatch count. The unit graph remains the
model *description* (and the parity/debug path); this is the model
*execution*.
"""

from veles_tpu.train.step import FusedTrainer  # noqa: F401
from veles_tpu.train.runner import (FusedRunner,  # noqa: F401
                                    fused_compatible)
